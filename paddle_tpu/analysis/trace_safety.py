"""Layer 1: framework-aware AST trace-safety lint (rules PT001–PT007).

Stdlib-``ast`` only. The rules encode the trace-time failure modes this
jit+SPMD stack actually bites people with — each one is a bug class a
tier-1 unit test cannot see because the poisoned value is only wrong
*across* traces or *across* threads:

  PT001  tracer leak        jit-traced code stores a traced value on
                            ``self``/a global; the Tracer outlives its
                            trace and the next call explodes (or worse,
                            silently constant-folds the stale value)
  PT002  concretization     ``bool()/int()/float()/.item()/if tensor:``
                            on a traced value forces a host sync or a
                            ConcretizationTypeError under ``to_static``
  PT003  PRNG key reuse     the same key fed to two consumers without a
                            ``split`` — correlated randomness, the
                            classic silent-statistics bug
  PT004  bad static args    ``static_argnames`` naming a parameter that
                            does not exist (the arg silently stays
                            traced) or a static parameter with a
                            non-hashable default
  PT005  silent swallow     broad ``except:`` whose body is only
                            pass/continue — a black hole PR 3's fault
                            injection cannot see through
  PT006  mutable default    the shared-across-calls list/dict default
  PT007  unmarked slow test test sleeps or runs a huge loop without a
                            ``slow``/``chaos`` marker (tier-1 budget)

Reachability: a function is considered jit-traced when it is decorated
with / passed to ``jax.jit``/``pjit``/``to_static`` (any dotted
spelling), or is called — by unambiguous name — from such a function in
the same module (one module-local BFS; cross-module reachability is out
of scope and handled by the baseline).
"""
from __future__ import annotations

import ast

from .report import Violation

__all__ = ["analyze_source", "analyze_file", "RULE_IDS"]

RULE_IDS = ("PT001", "PT002", "PT003", "PT004", "PT005", "PT006",
            "PT007")

_JIT_SUFFIXES = ("jit", "pjit", "to_static")
# split/fold_in/key only mint keys in a PRNG context: either the dotted
# callee mentions the rng machinery, or the receiver is a tracked key
# (`cats.split("|")` on a string must not register)
_KEY_MAKER_NAMES = ("prngkey", "key", "fold_in", "split")
_KEY_CONTEXTS = ("random", "rng", "generator", "prng")
_KEY_REFRESHERS = {"split", "fold_in", "clone"}
_KEY_EXEMPT_SINKS = {"str", "repr", "print", "len", "id", "hash",
                     "isinstance", "type", "list", "tuple", "format"}
_CONCRETIZERS = {"bool", "int", "float"}
_CONCRETIZING_METHODS = {"item", "tolist", "numpy", "__bool__",
                         "__int__", "__float__"}
# a call in an except body with one of these names counts as "the
# failure was observed" (logging, metrics, flight, re-raise helpers)
_OBSERVERS = {
    "log", "debug", "info", "warning", "warn", "error", "exception",
    "critical", "record", "inc", "observe", "set_gauge", "instant",
    "dump", "print", "emit", "fire", "fail", "abort",
}
_SLEEP_THRESHOLD_S = 0.5
_LOOP_THRESHOLD = 100_000


def _dotted(node) -> str:
    """Best-effort dotted name for a Name/Attribute chain ('' else)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_callee(node) -> bool:
    dotted = _dotted(node)
    if not dotted:
        return False
    last = dotted.rsplit(".", 1)[-1]
    return last in _JIT_SUFFIXES


def _jit_decorator(dec) -> bool:
    """True for @jax.jit / @to_static / @partial(jax.jit, ...) /
    @jit.to_static(input_spec=...) style decorators."""
    if _is_jit_callee(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_jit_callee(dec.func):
            return True
        if _dotted(dec.func).rsplit(".", 1)[-1] == "partial" and dec.args:
            return _is_jit_callee(dec.args[0])
    return False


def _mutable_default(node) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call):
        return _dotted(node.func) in ("list", "dict", "set", "bytearray")
    return False


def _const_num(node):
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)) and not isinstance(node.value, bool):
        return node.value
    return None


class _FunctionIndex:
    """All function/method defs in a module plus a name->def map that
    only answers for *unambiguous* simple names (the conservative basis
    of the reachability BFS)."""

    def __init__(self, tree: ast.Module):
        self.defs: list = []
        by_name: dict = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.append(node)
                by_name.setdefault(node.name, []).append(node)
        self._unique = {name: defs[0] for name, defs in by_name.items()
                        if len(defs) == 1}

    def resolve(self, name: str):
        return self._unique.get(name)


def _called_names(fn) -> set:
    """Simple callee names invoked inside `fn` (not inside nested
    defs — those have their own trace context)."""
    names = set()

    class V(ast.NodeVisitor):
        def __init__(self):
            self.depth = 0

        def visit_FunctionDef(self, node):
            if node is fn:
                self.generic_visit(node)
            # nested defs: their calls happen when *they* run, not here

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            dotted = _dotted(node.func)
            if dotted:
                names.add(dotted.rsplit(".", 1)[-1])
            self.generic_visit(node)

    V().visit(fn)
    return names


def _traced_functions(tree: ast.Module, index: _FunctionIndex) -> set:
    """The set of FunctionDef nodes reachable from a jit entry point."""
    entries = set()
    for fn in index.defs:
        if any(_jit_decorator(d) for d in fn.decorator_list):
            entries.add(fn)
    # call sites: jax.jit(fn) / to_static(fn, ...) with fn a bare name
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_callee(node.func):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name):
                    target = index.resolve(arg.id)
                    if target is not None:
                        entries.add(target)
    # BFS through unambiguous module-local callees
    traced, frontier = set(), list(entries)
    while frontier:
        fn = frontier.pop()
        if fn in traced:
            continue
        traced.add(fn)
        for name in _called_names(fn):
            target = index.resolve(name)
            if target is not None and target not in traced:
                frontier.append(target)
    return traced


# --------------------------- per-rule visitors ---------------------------


def _check_traced_body(fn, path, out):
    """PT001 + PT002 inside one jit-traced function body."""
    params = {a.arg for a in (
        list(fn.args.posonlyargs) + list(fn.args.args)
        + list(fn.args.kwonlyargs))}
    params.discard("self")
    globals_decl = set()

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            if node is fn:
                self.generic_visit(node)
            # nested defs keep their own context (they are reached by
            # the BFS if called unambiguously)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Global(self, node):
            globals_decl.update(node.names)
            self.generic_visit(node)

        def _flag_store(self, target, node):
            if isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name) and \
                    target.value.id == "self":
                out.append(Violation(
                    path, node.lineno, "PT001",
                    f"jit-traced `{fn.name}` stores to "
                    f"self.{target.attr} — a traced value leaks the "
                    f"trace (stale Tracer on the next call)"))
            elif isinstance(target, ast.Name) and \
                    target.id in globals_decl:
                out.append(Violation(
                    path, node.lineno, "PT001",
                    f"jit-traced `{fn.name}` stores to global "
                    f"`{target.id}` — a traced value leaks the trace"))

        def visit_Assign(self, node):
            if not isinstance(node.value, ast.Constant):
                for t in node.targets:
                    self._flag_store(t, node)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._flag_store(node.target, node)
            self.generic_visit(node)

        def visit_Call(self, node):
            dotted = _dotted(node.func)
            if dotted in _CONCRETIZERS and node.args and isinstance(
                    node.args[0], ast.Name) and \
                    node.args[0].id in params:
                out.append(Violation(
                    path, node.lineno, "PT002",
                    f"`{dotted}()` on traced argument "
                    f"`{node.args[0].id}` inside jit-traced "
                    f"`{fn.name}` — concretizes under trace"))
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _CONCRETIZING_METHODS and \
                    not node.args:
                base = node.func.value
                if isinstance(base, ast.Name) and base.id in params:
                    out.append(Violation(
                        path, node.lineno, "PT002",
                        f"`.{node.func.attr}()` on traced argument "
                        f"`{base.id}` inside jit-traced `{fn.name}` — "
                        f"forces a host transfer under trace"))
            self.generic_visit(node)

        def _flag_branch(self, node, kind):
            test = node.test
            if isinstance(test, ast.UnaryOp) and isinstance(
                    test.op, ast.Not):
                test = test.operand
            if isinstance(test, ast.Name) and test.id in params:
                out.append(Violation(
                    path, node.lineno, "PT002",
                    f"`{kind} {test.id}:` on traced argument inside "
                    f"jit-traced `{fn.name}` — data-dependent python "
                    f"control flow concretizes under trace"))

        def visit_If(self, node):
            self._flag_branch(node, "if")
            self.generic_visit(node)

        def visit_While(self, node):
            self._flag_branch(node, "while")
            self.generic_visit(node)

    V().visit(fn)


def _is_key_maker(call: ast.Call, state: dict) -> bool:
    dotted = _dotted(call.func)
    if not dotted:
        return False
    low = dotted.lower()
    last = low.rsplit(".", 1)[-1]
    if last == "prngkey":
        return True
    if last not in _KEY_MAKER_NAMES:
        return False
    if any(ctx in low for ctx in _KEY_CONTEXTS):
        return True
    # receiver is itself a tracked key: k2 = key.split()
    base = dotted.rsplit(".", 1)[0] if "." in dotted else ""
    return base in state


def _check_key_reuse(fn, path, out):
    """PT003: statement-order scan of one function body.

    Branch-aware (if/else arms see copies of the key state, merged
    afterwards: a key consumed once in EACH arm is used once, not
    twice) and loop-aware (loop bodies run twice, so a key minted
    before the loop and consumed inside it without an in-loop split is
    reuse)."""
    found: dict = {}  # (line, var) -> Violation, deduped across passes

    def flag(var, callee, line):
        found.setdefault((line, var), Violation(
            path, line, "PT003",
            f"PRNG key `{var}` passed to a second consumer "
            f"(`{callee}`) without a split in `{fn.name}` — "
            f"correlated randomness"))

    def visit_expr(node, state):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested scope: separate key discipline
        if isinstance(node, ast.Call):
            visit_expr(node.func, state)
            callee = _dotted(node.func).rsplit(".", 1)[-1]
            consumes = callee not in _KEY_REFRESHERS and \
                callee not in _KEY_EXEMPT_SINKS
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in state:
                    if consumes:
                        if state[arg.id] == "used":
                            flag(arg.id, callee, node.lineno)
                        else:
                            state[arg.id] = "used"
                else:
                    visit_expr(arg, state)
            return
        for child in ast.iter_child_nodes(node):
            visit_expr(child, state)

    def assign_targets(node):
        targets = []
        for t in node.targets:
            if isinstance(t, ast.Name):
                targets.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(e.id for e in t.elts
                               if isinstance(e, ast.Name))
        return targets

    def merge(base, arms):
        """Key state after diverging control flow: tracked only if
        tracked in every arm; 'used' as soon as any arm used it."""
        for var in list(base):
            if not all(var in arm for arm in arms):
                del base[var]
            elif any(arm[var] == "used" for arm in arms):
                base[var] = "used"
        for arm in arms:  # keys minted inside an arm
            for var, st in arm.items():
                if var not in base and all(var in a for a in arms):
                    base[var] = "used" if any(
                        a[var] == "used" for a in arms) else st

    def run(stmts, state):
        for node in stmts:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(node, ast.Assign):
                visit_expr(node.value, state)
                fresh = isinstance(node.value, ast.Call) and \
                    _is_key_maker(node.value, state)
                for name in assign_targets(node):
                    if fresh:
                        state[name] = "fresh"
                    else:
                        state.pop(name, None)
            elif isinstance(node, ast.If):
                body_state = dict(state)
                else_state = dict(state)
                run(node.body, body_state)
                run(node.orelse, else_state)
                merge(state, [body_state, else_state])
            elif isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    visit_expr(node.iter, state)
                else:
                    visit_expr(node.test, state)
                # two passes: the second flags keys re-consumed across
                # iterations without an in-loop split
                run(node.body, state)
                run(node.body, state)
                run(node.orelse, state)
            elif isinstance(node, ast.With):
                for item in node.items:
                    visit_expr(item.context_expr, state)
                run(node.body, state)
            elif isinstance(node, ast.Try):
                run(node.body, state)
                for handler in node.handlers:
                    run(handler.body, dict(state))
                run(node.orelse, state)
                run(node.finalbody, state)
            else:
                visit_expr(node, state)

    run(fn.body, {})
    out.extend(found.values())


def _check_jit_static_args(tree, index, path, out):
    """PT004: static_argnames/nums vs the wrapped function's signature."""

    def check(fn, call, lineno):
        pos_params = [a.arg for a in (
            list(fn.args.posonlyargs) + list(fn.args.args))]
        all_params = set(pos_params) | {
            a.arg for a in fn.args.kwonlyargs}
        defaults = {}
        pos_with_default = pos_params[len(pos_params)
                                      - len(fn.args.defaults):]
        defaults.update(zip(pos_with_default, fn.args.defaults))
        for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if d is not None:
                defaults[a.arg] = d
        static_names, static_nums = [], []
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                if isinstance(kw.value, ast.Constant) and isinstance(
                        kw.value.value, str):
                    static_names.append(kw.value.value)
                elif isinstance(kw.value, (ast.Tuple, ast.List)):
                    static_names.extend(
                        e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str))
            elif kw.arg == "static_argnums":
                if isinstance(kw.value, ast.Constant) and isinstance(
                        kw.value.value, int):
                    static_nums.append(kw.value.value)
                elif isinstance(kw.value, (ast.Tuple, ast.List)):
                    static_nums.extend(
                        e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int))
        has_kwargs = fn.args.kwarg is not None
        for name in static_names:
            if name not in all_params and not has_kwargs:
                out.append(Violation(
                    path, lineno, "PT004",
                    f"static_argnames={name!r} does not name a "
                    f"parameter of `{fn.name}` — the intended static "
                    f"arg silently stays traced"))
            elif name in defaults and _mutable_default(defaults[name]):
                out.append(Violation(
                    path, lineno, "PT004",
                    f"static parameter `{name}` of `{fn.name}` has a "
                    f"non-hashable default — jit cache key will raise "
                    f"TypeError at call time"))
        has_vararg = fn.args.vararg is not None
        for num in static_nums:
            if num >= len(pos_params) and not has_vararg:
                out.append(Violation(
                    path, lineno, "PT004",
                    f"static_argnums={num} is out of range for "
                    f"`{fn.name}` ({len(pos_params)} positional "
                    f"parameters)"))
            elif 0 <= num < len(pos_params):
                name = pos_params[num]
                if name in defaults and _mutable_default(defaults[name]):
                    out.append(Violation(
                        path, lineno, "PT004",
                        f"static parameter `{name}` of `{fn.name}` "
                        f"has a non-hashable default — jit cache key "
                        f"will raise TypeError at call time"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_callee(node.func):
            if node.args and isinstance(node.args[0], ast.Name):
                fn = index.resolve(node.args[0].id)
                if fn is not None:
                    check(fn, node, node.lineno)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _jit_decorator(dec):
                    check(node, dec, dec.lineno)


def _broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in ("Exception", "BaseException")
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name)
                   and e.id in ("Exception", "BaseException")
                   for e in t.elts)
    return False


def _check_silent_swallow(tree, path, out):
    """PT005: broad except whose body is only pass/continue/break."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _broad_handler(node):
            continue
        trivial = all(
            isinstance(s, (ast.Pass, ast.Continue, ast.Break))
            or (isinstance(s, ast.Expr)
                and isinstance(s.value, ast.Constant))
            for s in node.body)
        if trivial:
            caught = _dotted(node.type) if node.type is not None else \
                "bare except"
            out.append(Violation(
                path, node.lineno, "PT005",
                f"broad `except {caught or '...'}` swallows the "
                f"failure with no flight/metrics/log signal — "
                f"narrow it or record it"))


def _check_mutable_defaults(tree, path, out):
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            if _mutable_default(d):
                out.append(Violation(
                    path, d.lineno, "PT006",
                    f"mutable default argument on `{node.name}` — "
                    f"shared across calls"))


def _has_marker(decorators, markers=("slow", "chaos")) -> bool:
    for dec in decorators:
        target = dec.func if isinstance(dec, ast.Call) else dec
        dotted = _dotted(target)
        if dotted.rsplit(".", 1)[-1] in markers and "mark" in dotted:
            return True
    return False


def _check_unmarked_slow_tests(tree, path, out):
    """PT007 (tests/ only): sleeps/huge loops without slow|chaos mark."""

    def check_test(fn, class_marked):
        if class_marked or _has_marker(fn.decorator_list):
            return
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                last = dotted.rsplit(".", 1)[-1]
                if last == "sleep" and node.args:
                    v = _const_num(node.args[0])
                    if v is not None and v >= _SLEEP_THRESHOLD_S:
                        out.append(Violation(
                            path, node.lineno, "PT007",
                            f"test `{fn.name}` sleeps {v}s without a "
                            f"slow/chaos marker — tier-1 budget"))
                elif last == "range" and node.args:
                    # range(stop) / range(start, stop[, step]): the
                    # trip count lives in the stop arg, not args[-1]
                    stop = node.args[1] if len(node.args) >= 2 \
                        else node.args[0]
                    v = _const_num(stop)
                    if v is not None and v >= _LOOP_THRESHOLD:
                        out.append(Violation(
                            path, node.lineno, "PT007",
                            f"test `{fn.name}` loops over {int(v)} "
                            f"steps without a slow/chaos marker — "
                            f"tier-1 budget"))

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name.startswith("test"):
            check_test(node, False)
        elif isinstance(node, ast.ClassDef):
            marked = _has_marker(node.decorator_list)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and sub.name.startswith("test"):
                    check_test(sub, marked)


# --------------------------- entry points ---------------------------


def analyze_source(source: str, path: str, is_test_file=None,
                   tree: ast.Module | None = None) -> list:
    """All Layer-1 violations for one file's source (suppressions NOT
    applied here — the runner owns them; see runner.analyze_repo).
    Pass `tree` to reuse an existing parse (the runner parses once and
    shares it across layers)."""
    if tree is None:
        tree = ast.parse(source)
    out: list = []
    index = _FunctionIndex(tree)
    traced = _traced_functions(tree, index)
    for fn in sorted(traced, key=lambda f: f.lineno):
        _check_traced_body(fn, path, out)
    for fn in index.defs:
        _check_key_reuse(fn, path, out)
    _check_jit_static_args(tree, index, path, out)
    _check_silent_swallow(tree, path, out)
    _check_mutable_defaults(tree, path, out)
    if is_test_file is None:
        norm = path.replace("\\", "/")
        is_test_file = norm.startswith("tests/") or "/tests/" in norm
    if is_test_file:
        _check_unmarked_slow_tests(tree, path, out)
    out.sort(key=Violation.sort_key)
    return out


def analyze_file(path: str, rel: str | None = None) -> list:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return analyze_source(source, rel or path)
