"""Violation model, inline suppressions, and the committed baseline.

Every analysis layer (AST trace-safety, lock discipline, jaxpr/HLO
audit, manifest drift) reports findings as `Violation` objects so the
CLI, the baseline gate, and the tests speak one format:

    file:line RULE message

Baselines key on ``file|rule|message`` — deliberately line-free, so an
unrelated edit that shifts a suppressed finding by ten lines does not
resurrect it, while a *new* instance of the same rule in the same file
with a different message (messages name the offending attribute /
function / op) fails the gate.

Inline suppressions (``# pt-lint: ok[PT005]`` or bare ``# pt-lint: ok``)
work at three scopes: the violating line, the line directly above it, or
a ``def``/``class`` header line (covers the whole body — the idiom for
"this helper is always called with the lock held").

Stdlib-only on purpose: `tools/pt_lint.py` must run without importing
jax-heavy `paddle_tpu`.
"""
from __future__ import annotations

import ast
import json
import re

__all__ = [
    "Violation", "Suppressions", "load_baseline", "save_baseline",
    "baseline_counts", "diff_against_baseline", "render_report",
    "save_budget", "load_budget", "diff_against_budget",
    "render_budget_diff",
]

_SUPPRESS_RE = re.compile(
    r"#\s*pt-lint\s*:\s*ok(?:\[([A-Za-z0-9_, ]+)\])?")


class Violation:
    """One finding. `file` is a repo-relative posix path; `message` must
    be stable across unrelated edits (name things, don't quote lines)."""

    __slots__ = ("file", "line", "rule", "message")

    def __init__(self, file: str, line: int, rule: str, message: str):
        self.file = str(file).replace("\\", "/")
        self.line = int(line)
        self.rule = str(rule)
        self.message = str(message)

    def key(self) -> str:
        return f"{self.file}|{self.rule}|{self.message}"

    def render(self) -> str:
        return f"{self.file}:{self.line} {self.rule} {self.message}"

    def sort_key(self):
        return (self.file, self.line, self.rule, self.message)

    def __repr__(self):  # debugging convenience
        return f"Violation({self.render()!r})"

    def __eq__(self, other):
        return isinstance(other, Violation) and \
            self.sort_key() == other.sort_key()

    def __hash__(self):
        return hash(self.sort_key())


class Suppressions:
    """Per-file suppression index built from source text (+ AST for
    def/class-scoped suppressions)."""

    def __init__(self, source: str, tree: ast.AST | None = None):
        # line -> set of rule ids (empty set = suppress every rule)
        self._lines: dict = {}
        # line -> the free-text reason following the marker ("(callers
        # hold _lock)") — Layer 5 machine-reads the caller-holds idiom
        self._reasons: dict = {}
        for i, line in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = m.group(1)
                self._lines[i] = (
                    set() if rules is None
                    else {r.strip() for r in rules.split(",") if r.strip()})
                self._reasons[i] = line[m.end():].strip()
        # (start, end, rules) ranges from def/class headers carrying a
        # suppression comment — covers the whole body
        self._ranges: list = []
        if tree is not None:
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    rules = self._lines.get(node.lineno)
                    if rules is not None:
                        end = getattr(node, "end_lineno", node.lineno)
                        self._ranges.append((node.lineno, end, rules))

    @staticmethod
    def _matches(rules: set, rule: str) -> bool:
        return not rules or rule in rules

    def suppressed(self, line: int, rule: str) -> bool:
        for probe in (line, line - 1):
            rules = self._lines.get(probe)
            if rules is not None and self._matches(rules, rule):
                return True
        for start, end, rules in self._ranges:
            if start <= line <= end and self._matches(rules, rule):
                return True
        return False

    def listed_rules(self, line: int) -> set:
        """Rule ids EXPLICITLY named in a suppression on `line` or the
        line above (a bare ``ok`` contributes nothing)."""
        out: set = set()
        for probe in (line, line - 1):
            rules = self._lines.get(probe)
            if rules:
                out |= rules
        return out

    def guard_claims(self, line: int) -> set:
        """Rule ids whose suppression on `line`/line-above carries a
        caller-holds-the-lock reason — the repo's documented idiom
        ``# pt-lint: ok[PT102] (callers hold _lock)``.  Layer 5 treats
        these as machine-read guard facts: the helper's body is
        analyzed as if the named lock were held, and PT504 reports any
        call site where inference shows NO lock actually held.  A
        waiver with any other reason ("set once at construction") stays
        a plain suppression."""
        out: set = set()
        for probe in (line, line - 1):
            rules = self._lines.get(probe)
            if rules and re.search(r"\bholds?\b",
                                   self._reasons.get(probe, "")):
                out |= rules
        return out

    def apply(self, violations):
        return [v for v in violations
                if not self.suppressed(v.line, v.rule)]


# --------------------------- baseline ---------------------------

BASELINE_VERSION = 1


def baseline_counts(violations) -> dict:
    counts: dict = {}
    for v in violations:
        counts[v.key()] = counts.get(v.key(), 0) + 1
    return counts


def save_baseline(path: str, violations) -> dict:
    data = {
        "version": BASELINE_VERSION,
        "comment": "pt_lint suppression baseline — regenerate with "
                   "`python tools/pt_lint.py --update-baseline`. The "
                   "gate fails only on violations NOT counted here.",
        "counts": dict(sorted(baseline_counts(violations).items())),
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


def load_baseline(path: str) -> dict:
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    counts = data.get("counts", {})
    return counts if isinstance(counts, dict) else {}


def diff_against_baseline(violations, baseline: dict):
    """Split `violations` into (new, known) against baseline counts and
    report stale baseline keys (fixed findings still counted).

    When a file has more instances of an identical (rule, message) key
    than the baseline allows, the *later* ones (by line) are the new
    ones — deterministic, and matches "the code you just added is below
    the code that was already there" often enough to be useful."""
    by_key: dict = {}
    for v in sorted(violations, key=Violation.sort_key):
        by_key.setdefault(v.key(), []).append(v)
    new, known = [], []
    for key, vs in by_key.items():
        allowed = int(baseline.get(key, 0))
        known.extend(vs[:allowed])
        new.extend(vs[allowed:])
    stale = sorted(
        key for key, allowed in baseline.items()
        if allowed > len(by_key.get(key, [])))
    new.sort(key=Violation.sort_key)
    known.sort(key=Violation.sort_key)
    return new, known, stale


def render_report(violations) -> str:
    return "\n".join(
        v.render() for v in sorted(violations, key=Violation.sort_key))


# --------------------------- perf budgets ---------------------------
#
# The perf-audit layer (perf_audit.py, PT4xx) does not gate on a
# violation baseline: its findings are *quantified costs* (transpose
# bytes, replicated MiB, host syncs) that are nonzero today by design.
# Instead each audited program carries a committed budget —
# tools/perf_budget.json — and the gate fails when any metric EXCEEDS
# its budget. Lower is always better; a drop is reported as an
# improvement so the budget ratchets down via --update-budget, the
# exact analog of the lint baseline's stale-entry note.

BUDGET_VERSION = 1


def save_budget(path: str, metrics: dict) -> dict:
    """Write {program: {metric: value}} deterministically: sorted keys,
    values already rounded by the auditor, newline-terminated — two
    audits of the same tree must produce byte-identical files."""
    data = {
        "version": BUDGET_VERSION,
        "comment": "pt_lint static perf budgets — regenerate with "
                   "`python tools/pt_lint.py --update-budget`. The "
                   "--perf gate fails only on metrics that EXCEED "
                   "their budget; lower numbers are improvements "
                   "(ratchet the budget down).",
        "budgets": {prog: dict(sorted(vals.items()))
                    for prog, vals in sorted(metrics.items())},
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


def load_budget(path: str) -> dict:
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    budgets = data.get("budgets", {})
    return budgets if isinstance(budgets, dict) else {}


def diff_against_budget(metrics: dict, budget: dict):
    """Compare audited metrics to committed budgets.

    Returns ``(regressions, improvements, unbudgeted)`` — lists of
    ``(program, metric, value, budgeted)`` tuples. A metric with no
    budget entry and a nonzero value is a regression (the gate must
    force a conscious --update-budget, exactly like a NEW lint
    violation); a zero-valued unbudgeted metric passes (adding a new
    always-zero metric must not break CI). Only programs present in
    ``metrics`` are judged: a fast-subset audit does not vouch for the
    slow-tier programs' budgets."""
    regressions, improvements, unbudgeted = [], [], []
    for prog in sorted(metrics):
        have = metrics[prog]
        want = budget.get(prog, {})
        if not isinstance(want, dict):
            want = {}
        for name in sorted(have):
            value = have[name]
            if name not in want:
                if value > 0:
                    regressions.append((prog, name, value, None))
                else:
                    unbudgeted.append((prog, name, value, None))
                continue
            budgeted = want[name]
            if value > budgeted + 1e-9:
                regressions.append((prog, name, value, budgeted))
            elif value < budgeted - 1e-9:
                improvements.append((prog, name, value, budgeted))
    return regressions, improvements, unbudgeted


def render_budget_diff(regressions, improvements) -> str:
    lines = []
    for prog, name, value, budgeted in regressions:
        if budgeted is None:
            lines.append(f"REGRESS  {prog}.{name}: {value} "
                         f"(no budget entry — run --update-budget "
                         f"if intended)")
        else:
            lines.append(f"REGRESS  {prog}.{name}: {value} exceeds "
                         f"budget {budgeted}")
    for prog, name, value, budgeted in improvements:
        lines.append(f"improved {prog}.{name}: {value} (budget "
                     f"{budgeted} — ratchet down with --update-budget)")
    return "\n".join(lines)
