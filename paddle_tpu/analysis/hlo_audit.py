"""Layer 3: jaxpr/HLO audit (rules PT201/PT202/PT203).

Where Layers 1–2 read source, this layer reads the *program*: trace a
callable to its jaxpr (or lower it to StableHLO) and flag the three
compiled-program sins that silently cap a TPU step:

  PT201  host transfer      a callback/infeed/outfeed primitive inside
                            a traced function — every call is a device
                            round-trip hidden in what looks like one
                            fused XLA program
  PT202  f64 promotion      an op whose inputs are ≤f32 but whose
                            output is f64 — doubles bytes moved and
                            falls off the MXU entirely
  PT203  un-donated buffer  a train-step argument big enough to matter
                            (params/opt state) lowered without
                            ``tf.aliasing_output``/buffer donation —
                            doubles peak memory for the step

Entry points:
  * ``audit_jaxpr(closed_jaxpr, where)``      — walk eqns recursively
  * ``audit_callable(fn, *args, where=...)``  — make_jaxpr + audit
  * ``audit_lowered_donation(text, where)``   — PT203 on StableHLO text
  * ``audit_op_table(...)``                   — trace the exported op
    surface from OPS_MANIFEST.json conformance kinds (unary/binary)
  * ``audit_train_step(...)``                 — the hybrid GPT train
    step via tools/memory_report (slow: builds + lowers a real model)

jax imports are function-local: importing this module costs nothing, so
`tools/pt_lint.py` can expose the layer behind a flag without paying a
jax import for the AST-only fast path.
"""
from __future__ import annotations

import os
import re
import sys

from .report import Violation

__all__ = [
    "audit_jaxpr", "audit_callable", "audit_lowered_donation",
    "audit_op_table", "audit_train_step", "RULE_IDS",
    "HOST_TRANSFER_PRIMITIVES",
]

RULE_IDS = ("PT200", "PT201", "PT202", "PT203")

HOST_TRANSFER_PRIMITIVES = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "infeed", "outfeed", "host_local_array_to_global",
}

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _walk_eqns(jaxpr):
    """Yield every eqn in a (closed) jaxpr, recursing into sub-jaxprs
    (cond/scan/while/pjit bodies)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for param in eqn.params.values():
            for sub in _iter_subjaxprs(param):
                yield from _walk_eqns(sub)


def _iter_subjaxprs(param):
    import jax.core as jcore

    closed = getattr(jcore, "ClosedJaxpr", ())
    raw = getattr(jcore, "Jaxpr", ())
    if isinstance(param, (closed, raw)):
        yield param
    elif isinstance(param, (list, tuple)):
        for p in param:
            yield from _iter_subjaxprs(p)


def _dtype_of(var):
    aval = getattr(var, "aval", None)
    return getattr(aval, "dtype", None)


def audit_jaxpr(closed_jaxpr, where: str) -> list:
    """PT201 + PT202 over one traced program."""
    out = []
    for eqn in _walk_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if name in HOST_TRANSFER_PRIMITIVES:
            out.append(Violation(
                where, 0, "PT201",
                f"host-transfer primitive `{name}` inside traced "
                f"program — device round-trip per call"))
        in_dtypes = {str(d) for d in map(_dtype_of, eqn.invars)
                     if d is not None}
        if any("float64" in str(_dtype_of(v)) for v in eqn.outvars
               if _dtype_of(v) is not None) and \
                "float64" not in in_dtypes:
            out.append(Violation(
                where, 0, "PT202",
                f"primitive `{name}` promotes ≤f32 inputs to a "
                f"float64 output — silent f64 promotion"))
    return out


def audit_callable(fn, *args, where: str, enable_x64: bool = True,
                   **kwargs) -> list:
    """Trace `fn(*args)` and audit the jaxpr. x64 is enabled during the
    trace by default: without it jax silently *downcasts* f64, so the
    promotion this rule exists to catch is unobservable."""
    import jax

    try:
        if enable_x64:
            from jax.experimental import enable_x64 as _x64ctx

            with _x64ctx():
                jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
        else:
            jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    except Exception as e:  # tracing failed — report, don't crash the lint
        return [Violation(
            where, 0, "PT200",
            f"trace failed ({type(e).__name__}) — program could not "
            f"be audited")]
    return audit_jaxpr(jaxpr, where)


# --------------------------- PT203: donation ---------------------------

_ALIAS_RE = re.compile(r"tf\.aliasing_output|jax\.buffer_donor")
#     tensor<512x512xf32> / tensor<f32> — dims are digit groups, the
# dtype starts with a letter (`\w+` alone would eat "512x512xf32":
# `x` is a word character)
_TENSOR_RE = re.compile(r"tensor<(?:(\d+(?:x\d+)*)x)?([a-z]\w*)>")


def audit_lowered_donation(stablehlo_text: str, where: str,
                           min_mbytes: float = 1.0) -> list:
    """PT203: big @main arguments with no aliasing/donation marker.

    Only arguments at least `min_mbytes` matter — activations/ids ride
    through undonated by design; params and optimizer state must not.

    Parsing splits the @main signature on `%argN:` tokens rather than
    regexing one attr dict: sharding attrs contain *nested braces
    inside quoted strings* (``mhlo.sharding = "{replicated}"``), which
    a naive ``\\{[^}]*\\}`` silently truncates — exactly the kind of
    wrong-tool parse that once reported 0 donated args on a fully
    donated step."""
    out = []
    main = stablehlo_text.split("func.func public @main", 1)
    if len(main) < 2:
        return out
    header = main[1].split("->", 1)[0]
    itemsize = {"bf16": 2, "f16": 2, "f32": 4, "f64": 8, "i32": 4,
                "ui32": 4, "i64": 8, "i8": 1, "i1": 1}
    undonated_mb = 0.0
    n_undonated = 0
    chunks = re.split(r"%arg\d+:", header)[1:]
    for chunk in chunks:
        m = _TENSOR_RE.search(chunk)
        if m is None:
            continue
        dims, dt = m.groups()
        numel = 1
        for d in (dims or "").split("x"):
            if d.strip():
                numel *= int(d)
        mb = numel * itemsize.get(dt, 4) / 2**20
        if mb < min_mbytes:
            continue
        if not _ALIAS_RE.search(chunk):
            n_undonated += 1
            undonated_mb += mb
    if n_undonated:
        out.append(Violation(
            where, 0, "PT203",
            f"{n_undonated} train-step argument(s) ≥{min_mbytes} MiB "
            f"lowered without buffer donation "
            f"({undonated_mb:.1f} MiB un-donated — doubles peak "
            f"memory)"))
    return out


# --------------------------- op-table audit ---------------------------


def _manifest_conformance_ops(manifest_path=None):
    """(name, kind) for every manifest op with a unary/binary
    conformance sweep entry — the machine-true 'exported op table'."""
    import json

    path = manifest_path or os.path.join(_REPO, "OPS_MANIFEST.json")
    with open(path) as f:
        manifest = json.load(f)
    out = []
    for entry in manifest.get("ops", []):
        conf = entry.get("conformance") or {}
        if entry.get("present") and conf.get("kind") in ("unary",
                                                         "binary"):
            out.append((entry["name"], conf["kind"]))
    return sorted(out)


def _resolve_op(name):
    import paddle_tpu as P

    for mod in (P, P.nn.functional, P.linalg, P.fft, P.signal, P.sparse,
                P.geometric, P.incubate.nn.functional, P.vision.ops):
        obj = getattr(mod, name, None)
        if callable(obj):
            return obj
    return None


def iter_op_callables(limit: int | None = None, manifest_path=None):
    """Yield ``(name, traced_fn_or_None, args)`` for every manifest op
    with a unary/binary conformance sweep — the shared program source
    for this layer's correctness audit and the perf layer's op-table
    sweep (one place decides what 'the exported op surface' means).

    ``traced_fn`` is a plain jax-traceable callable using the sweep's
    own domain-correct input factories; ``None`` when the op does not
    resolve."""
    import jax.numpy as jnp

    import paddle_tpu as P
    from paddle_tpu.core.tensor import Tensor

    sys.path.insert(0, os.path.join(_REPO, "tests"))
    try:
        import conformance_tables
    finally:
        sys.path.pop(0)

    ops = _manifest_conformance_ops(manifest_path)
    if limit is not None:
        ops = ops[:limit]

    def unwrap(r):
        if isinstance(r, (tuple, list)):
            return [unwrap(x) for x in r]
        return r._value if isinstance(r, Tensor) else r

    for name, kind in ops:
        fn = _resolve_op(name)
        table = conformance_tables.UNARY_OPS if kind == "unary" \
            else conformance_tables.BINARY_OPS
        spec = table.get(name)
        if fn is None or spec is None:
            yield name, None, ()
            continue
        shape = (3, 4)
        if kind == "unary":
            # UNARY_OPS rows carry the sweep's own domain-correct input
            # factory — e.g. acosh needs inputs > 1
            try:
                x = jnp.asarray(spec[0](shape))
            except Exception:
                x = jnp.ones(shape, jnp.float32)

            def traced(a, _fn=fn):
                return unwrap(_fn(P.to_tensor(a)))
            args = (x,)
        else:
            x = jnp.asarray(
                conformance_tables._pos(shape))  # positive: safe for
            # divide/pow/log-family binary domains

            def traced(a, b, _fn=fn):
                return unwrap(_fn(P.to_tensor(a), P.to_tensor(b)))
            args = (x, x + 0.5)
        yield name, traced, args


def audit_op_table(limit: int | None = None, manifest_path=None) -> list:
    """Trace every conformance-swept unary/binary op from the manifest
    with the sweep's own input factories and audit each jaxpr.

    Tracing only — no compilation, no execution — so the full ~200-op
    sweep is seconds, not minutes; still gated behind the slow tier /
    ``--jaxpr`` because it imports jax + paddle_tpu + the model stack."""
    import paddle_tpu as P
    from paddle_tpu.core.tensor import Tensor

    def unwrap(r):
        if isinstance(r, (tuple, list)):
            return [unwrap(x) for x in r]
        return r._value if isinstance(r, Tensor) else r

    out = []
    for name, traced, args in iter_op_callables(limit, manifest_path):
        if traced is None:
            out.append(Violation(
                "OPS_MANIFEST.json", 0, "PT200",
                f"op `{name}` claims a conformance sweep but does not "
                f"resolve — cannot audit"))
            continue
        found = audit_callable(traced, *args, where=f"op:{name}")
        if found and found[0].rule == "PT200" and len(args) == 2:
            # ternary-shaped "binary" ops (lerp: x, y, weight): retry
            # with a scalar third operand before reporting un-auditable
            fn = _resolve_op(name)

            def traced3(a, b, _fn=fn):
                return unwrap(_fn(P.to_tensor(a), P.to_tensor(b), 0.5))
            found = audit_callable(traced3, *args, where=f"op:{name}")
        out.extend(found)
    return out


def audit_train_step(batch: int = 2, seq: int = 128, layers: int = 1) -> list:
    """Lower the hybrid GPT train step (small proxy shape) and audit
    donation + host transfers + promotions. Heavy (model build + CPU
    lowering): slow tier / ``--jaxpr`` only."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        from memory_report import _build_lowered
    finally:
        sys.path.pop(0)

    lowered, _model = _build_lowered(
        dict(vocab_size=1024, hidden_size=64, num_layers=layers,
             num_heads=4, max_seq_len=seq, fused_head_ce=True,
             dropout=0.0),
        batch, seq)
    text = lowered.as_text()
    where = "train_step"
    out = audit_lowered_donation(text, where, min_mbytes=0.05)
    # host transfers / f64 in the lowered program: textual scan of the
    # StableHLO (the jaxpr is gone by this point; custom_call with a
    # callback target or any f64 tensor type is the same evidence)
    if re.search(r"stablehlo\.custom_call[^\n]*callback", text):
        out.append(Violation(
            where, 0, "PT201",
            "callback custom_call inside the lowered train step — "
            "host round-trip per step"))
    for m in re.finditer(r"tensor<[0-9x]*x?f64>", text):
        out.append(Violation(
            where, 0, "PT202",
            "f64 tensor inside the lowered train step — silent "
            "promotion"))
        break  # one finding per program is enough signal
    return out
