"""Layer 4: static performance auditor (rules PT400–PT405).

Layers 1–3 catch *correctness* bug classes; this layer catches the
*cost* classes PERF.md's xprof forensics measured on hardware — and
holds them to committed per-model budgets so they cannot regress
silently on a CPU-only CI box:

  PT400  audit failure      a representative program failed to build/
                            trace/lower — the auditor is blind there;
                            surfaced, never swallowed
  PT401  layout tax         explicit transpose/copy/bitcast-convert ops
                            and the bytes they move per step — the
                            static twin of the measured 66 ms/step (20%)
                            transpose burn (PERF.md "Where the remaining
                            MFU lives")
  PT402  recompile hazard   weak-typed scalar inputs to a traced
                            program (a Python float and a jnp.float32
                            compile twice), and call sites feeding a
                            jitted function host scalars / unhashable
                            literals — PT004 generalized from signatures
                            to call sites
  PT403  replicated state   big (≥ threshold) program arguments the
                            sharding spec leaves replicated — params or
                            optimizer state that a ZeRO-1/weight-update
                            sharding pass should shard (ROADMAP item 3)
  PT404  collective shape   all-gather whose result is immediately
                            reduced (a reduce-scatter + smaller gather
                            does the same work moving 1/N the bytes),
                            and chained collectives with no compute
                            between them (nothing to overlap with)
  PT405  hot-loop host sync device round-trips (callbacks/infeed)
                            *inside a compiled loop body* — PT201 with
                            loop context: once per step is bad, once per
                            scan iteration caps decode throughput
  PT406  dequant placement  int8→float dequantize ops traced OUTSIDE the
                            decode scan body (weight-only tier, ISSUE
                            12): a dequant hoisted out of the loop
                            materializes a full-precision weight copy
                            and the per-step HBM stream is no longer
                            int8 — the measured 1.33×/1.91× win
                            evaporates.  Audited at the JAXPR level
                            (the view WE control): the XLA:CPU proxy's
                            LICM hoists loop-invariant dequant fusions
                            regardless (observed, documented in
                            PERF.md), while the TPU pipeline does not
                            hoist size-inflating ops — so the
                            source-placement pin is the honest gate.

Representative programs (all built under ``JAX_PLATFORMS=cpu``):
  * ``train_step``  — the hybrid GPT train step at a small proxy shape
                      (same structure/dtypes as the bench shape)
  * ``sharded_train_step`` — the SAME GPT proxy under the default
                      multi-chip configuration (dp=8 over the audit
                      env's virtual devices → auto ZeRO-1, ISSUE 11):
                      its committed budget pins the sharded weight
                      update — ``pt403_replicated_*`` ≈ 0 (params AND
                      optimizer state live dp-sharded) and the
                      ``pt404_opt_*`` collective counts hold the wire
                      shape, so a reintroduced replicated update fails
                      CI before a TPU ever runs
  * ``swin_train_step`` — the Swin train step at a tiny proxy shape
                      (pins the windowed-attention layout tax: roll /
                      window-partition transposes, rel-pos-bias
                      plumbing — ISSUE 10)
  * ``decode_step`` — the scanned KV-cache decode program
                      (``GenerationMixin._decode_chunk_program``)
  * ``call_sites``  — AST scan of the repo for PT402 call-site hazards
                      (stdlib-only: no jax import)
  * ``op_table``    — the OPS_MANIFEST unary/binary conformance surface
                      (tracing only; slow tier)

Each program yields a metrics dict (``pt401_transpose_mbytes`` …)
aggregated into ``tools/perf_budget.json`` — the perf analog of
``tools/lint_baseline.json``.  ``tools/pt_lint.py --perf --check``
exits 2 when any metric exceeds its committed budget;
``--update-budget`` ratchets the file after a verified win.
``tools/perf_gate.py`` merges the same budgets next to its measured
bench metrics (rows named ``static.<program>.<metric>``) so a PR that
adds transposes fails CI before a TPU ever runs.

jax imports are function-local: importing this module is stdlib-cheap,
so the ``call_sites`` program (and the CLI fast path) never pays for
the model stack.
"""
from __future__ import annotations

import ast
import os
import re
import sys

from .report import Violation
from .trace_safety import _dotted, _is_jit_callee, _jit_decorator

__all__ = [
    "RULE_IDS", "DEFAULT_PROGRAMS", "FULL_PROGRAMS",
    "layout_tax", "weak_input_count", "replicated_args",
    "replicated_arg_details", "collective_hlo_counts",
    "collective_patterns", "host_sync_counts", "dequant_placement",
    "call_site_hazards",
    "audit_program_texts", "audit_perf", "metrics_to_static_rows",
    "audit_hlo", "train_step_hlo",
]

RULE_IDS = ("PT400", "PT401", "PT402", "PT403", "PT404", "PT405",
            "PT406")

# program names: the fast subset runs in the tier-1 smoke; FULL adds the
# op-table sweep (slow tier — imports + traces the whole exported surface)
DEFAULT_PROGRAMS = ("train_step", "sharded_train_step", "swin_train_step",
                    "decode_step", "paged_decode_step",
                    "quantized_decode_step", "cached_prefill_step",
                    "call_sites")
FULL_PROGRAMS = ("train_step", "sharded_train_step", "swin_train_step",
                 "decode_step", "paged_decode_step",
                 "quantized_decode_step", "cached_prefill_step",
                 "call_sites", "op_table")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_ITEMSIZE = {"bf16": 2, "f16": 2, "f32": 4, "f64": 8, "i64": 8,
             "i32": 4, "ui32": 4, "i16": 2, "i8": 1, "ui8": 1, "i1": 1}

# collective primitives as they appear in jaxprs (psum_scatter is jax's
# reduce-scatter; ppermute shows up in ring schedules)
_COLLECTIVE_PRIMS = {
    "psum", "pmax", "pmin", "all_gather", "all_to_all", "ppermute",
    "psum_scatter", "reduce_scatter",
}
_REDUCE_PRIMS = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
                 "reduce_and", "reduce_or", "argmax", "argmin"}
_HOST_SYNC_PRIMS = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "infeed", "outfeed",
}
_LOOP_PRIMS = {"scan", "while", "fori_loop", "cumred_loop"}


def _numel(dims: str) -> int:
    n = 1
    for d in dims.split("x"):
        if d.strip():
            n *= int(d)
    return n


def _r2(x: float) -> float:
    """Budget values are rounded once, here — the determinism contract
    (byte-identical budget JSON across runs) depends on every float
    passing through exactly one rounding."""
    return round(float(x), 2)


# ------------------------- PT401: layout tax -------------------------

_SHLO_TRANSPOSE = re.compile(
    r"stablehlo\.transpose[^\n]*?->\s*tensor<([0-9x]+)x(\w+)>")
# optimized HLO: `%name = f32[4,8]{1,0} transpose(...)` — the op name
# sits between the shape/layout annotation and the open paren
_OPT_OP = re.compile(
    r"=\s*[a-z0-9]+\[[0-9,]*\][^ ]*\s+(transpose|copy|bitcast-convert)\(")


def layout_tax(stablehlo_text: str, opt_hlo_text: str = "") -> dict:
    """PT401 metrics for one program.

    StableHLO transposes are the backend-independent (deterministic)
    budget basis; the optimized-HLO counts record what the compiled
    executable actually schedules (fusion elides some, layout
    assignment adds copies) — both are budgeted so a regression in
    either view trips the gate."""
    count, mbytes = 0, 0.0
    for m in _SHLO_TRANSPOSE.finditer(stablehlo_text):
        dims, dt = m.groups()
        count += 1
        mbytes += _numel(dims) * _ITEMSIZE.get(dt, 4) / 2**20
    opt = {"transpose": 0, "copy": 0, "bitcast-convert": 0}
    for m in _OPT_OP.finditer(opt_hlo_text):
        opt[m.group(1)] += 1
    return {
        "pt401_transpose_count": count,
        "pt401_transpose_mbytes": _r2(mbytes),
        "pt401_opt_transpose_count": opt["transpose"],
        "pt401_opt_copy_count": opt["copy"],
        "pt401_opt_bitcast_convert_count": opt["bitcast-convert"],
    }


# --------------------- PT402: recompile hazards ---------------------


def weak_input_count(closed_jaxpr) -> int:
    """Weak-typed input avals: each is a cache-key split (`f(x, 0.1)`
    and `f(x, jnp.float32(0.1))` compile two programs) and a promotion
    trap (weak f32 scalar * bf16 array stays bf16, but a strong one
    promotes)."""
    return sum(1 for a in getattr(closed_jaxpr, "in_avals", ())
               if getattr(a, "weak_type", False))


_HOST_SCALAR_CALLS = {"int", "float", "bool", "len"}


def _jitted_wrapper_names(tree: ast.Module) -> set:
    """Names bound to a jit-wrapped callable in this module:
    ``g = jax.jit(f, ...)`` assignments plus ``@jax.jit``-decorated
    defs (any dotted jit/pjit/to_static spelling)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _is_jit_callee(node.value.func):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_jit_decorator(d) for d in node.decorator_list):
                names.add(node.name)
    return names


def call_site_hazards(source: str, path: str,
                      tree: ast.Module | None = None) -> list:
    """PT402 at call sites: arguments to a known-jitted callable that
    force recompiles or cache-key churn —

      * ``g(x, int(n))`` / ``float(...)`` / ``len(...)`` / ``.item()``:
        a host Python scalar rebuilt per call; as a static arg it
        retraces per distinct value, as a traced arg it is a weak-type
        cache split (and the ``.item()`` is a device sync besides)
      * ``g(x, [1, 2])`` / ``{...}``: a fresh mutable literal per call —
        unhashable if static (TypeError at call time), retrace-bait if
        its contents ever vary

    Constant-folded literals (plain numbers/strings) are fine and not
    flagged."""
    if tree is None:
        tree = ast.parse(source)
    jitted = _jitted_wrapper_names(tree)
    out = []
    if not jitted:
        return out

    def hazard_of(arg) -> str:
        if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
            return "a mutable literal (unhashable as a static arg, " \
                   "retrace-bait as a traced one)"
        if isinstance(arg, ast.Call):
            callee = _dotted(arg.func)
            if callee in _HOST_SCALAR_CALLS:
                return (f"`{callee}(...)` — a host Python scalar per "
                        f"call (weak-type cache split / retrace per "
                        f"value)")
            if isinstance(arg.func, ast.Attribute) and \
                    arg.func.attr == "item":
                return "`.item()` — a device sync feeding a fresh " \
                       "Python scalar per call"
        return ""

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jitted):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            why = hazard_of(arg)
            if why:
                out.append(Violation(
                    path, node.lineno, "PT402",
                    f"jitted `{node.func.id}` called with {why}"))
    return out


# ------------------- PT403: replicated big buffers -------------------

_ARG_TENSOR = re.compile(r"tensor<(?:(\d+(?:x\d+)*)x)?([a-z]\w*)>")
_SHARDED_ATTR = re.compile(r'mhlo\.sharding\s*=\s*"\{devices=')
_DONATED = re.compile(r"tf\.aliasing_output|jax\.buffer_donor")


def _iter_replicated_args(stablehlo_text: str, min_mbytes: float):
    """Yield ``(arg_index, mbytes)`` for every ``@main`` argument at
    least ``min_mbytes`` big whose sharding attr is absent or
    ``{replicated}``."""
    main = stablehlo_text.split("func.func public @main", 1)
    if len(main) < 2:
        return
    header = main[1].split("->", 1)[0]
    parts = re.split(r"%arg(\d+):", header)[1:]
    for i in range(0, len(parts) - 1, 2):
        idx, chunk = int(parts[i]), parts[i + 1]
        m = _ARG_TENSOR.search(chunk)
        if m is None:
            continue
        dims, dt = m.groups()
        mb = _numel(dims or "") * _ITEMSIZE.get(dt, 4) / 2**20
        if mb < min_mbytes:
            continue
        if not _SHARDED_ATTR.search(chunk):
            yield idx, mb


def replicated_args(stablehlo_text: str, min_mbytes: float = 0.05) -> dict:
    """PT403: ``@main`` arguments at least ``min_mbytes`` big whose
    sharding attr is absent or ``{replicated}`` — the state a
    cross-replica weight-update sharding pass (ZeRO-1) should shard.
    Donated-but-replicated still counts: donation halves peak memory,
    sharding divides it by the replica count."""
    count, mbytes = 0, 0.0
    for _idx, mb in _iter_replicated_args(stablehlo_text, min_mbytes):
        count += 1
        mbytes += mb
    return {"pt403_replicated_count": count,
            "pt403_replicated_mbytes": _r2(mbytes)}


def replicated_arg_details(stablehlo_text: str, min_mbytes: float = 0.05,
                           arg_names=None) -> list:
    """PT403 offenders as ``[(owner, mbytes)]``, biggest first.  With
    ``arg_names`` (flattened jit-argument names, index-aligned with the
    ``@main`` args) the owner is the PARAMETER the replicated buffer
    belongs to — budget regressions become actionable from the lint
    output alone (ISSUE 11 satellite)."""
    out = []
    for idx, mb in _iter_replicated_args(stablehlo_text, min_mbytes):
        name = None
        if arg_names is not None and 0 <= idx < len(arg_names):
            name = arg_names[idx]
        out.append((name or f"arg{idx}", _r2(mb)))
    out.sort(key=lambda t: (-t[1], t[0]))
    return out


# ---------------- PT404: compiled collective shape ----------------

# optimized-HLO collective ops (async forms count once via `-start`;
# `-done` is the same op completing).  The result-type run between `=`
# and the op name must admit parentheses: async collectives carry TUPLE
# result types (`= (f32[64]{0}, f32[64]{0}) all-reduce-start(`).  `%`
# stays excluded so operand references to collective-named values
# (`fusion(f32[] %all-reduce.3)`) never count.
_OPT_COLLECTIVE = re.compile(
    r"=\s*[a-z0-9_\[\](),{}:\s]*?\b"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute)"
    r"(?:-start)?\(")


def collective_hlo_counts(opt_hlo_text: str) -> dict:
    """PT404 metrics from the COMPILED (partitioned) program: how many
    of each collective the executable actually schedules.  For the
    sharded train step these pin the ZeRO-1 wire shape from both
    directions: the committed count ceilings catch growth-class
    regressions (per-layer param gathers), and the derived
    ``pt404_grad_sync_deficit`` (params minus scheduled additive
    collectives, budget 0 — computed in ``audit_perf``) catches the
    opposite one, grad syncs fused into an end-of-backward barrier,
    which LOWERS the raw counts and would otherwise read as an
    "improvement".  Note the CPU
    partitioner realizes reduce-scatter as all-reduce+dynamic-slice
    (the fused op is the TPU pipeline's rewrite — the *Automatic
    Cross-Replica Sharding* pass), so ``reduce_scatter`` may read 0 on
    the CPU-audited view while the same program scatters on TPU."""
    counts = {"all-reduce": 0, "all-gather": 0, "reduce-scatter": 0,
              "collective-permute": 0}
    for m in _OPT_COLLECTIVE.finditer(opt_hlo_text):
        counts[m.group(1)] += 1
    return {
        "pt404_opt_all_reduce_count": counts["all-reduce"],
        "pt404_opt_all_gather_count": counts["all-gather"],
        "pt404_opt_reduce_scatter_count": counts["reduce-scatter"],
        "pt404_opt_collective_permute_count": counts["collective-permute"],
    }


# -------------------- PT404 / PT405: jaxpr walks --------------------


def _iter_subjaxprs(param):
    import jax.core as jcore

    closed = getattr(jcore, "ClosedJaxpr", ())
    raw = getattr(jcore, "Jaxpr", ())
    if isinstance(param, (closed, raw)):
        yield param
    elif isinstance(param, (list, tuple)):
        for p in param:
            yield from _iter_subjaxprs(p)


def _walk_eqns_ctx(jaxpr, in_loop=False):
    """Yield ``(eqn, in_loop)`` for every eqn, recursing into sub-jaxprs
    and marking everything under a scan/while body as loop context."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn, in_loop
        child_loop = in_loop or eqn.primitive.name in _LOOP_PRIMS
        for param in eqn.params.values():
            for sub in _iter_subjaxprs(param):
                yield from _walk_eqns_ctx(sub, child_loop)


def collective_patterns(closed_jaxpr) -> dict:
    """PT404 metrics: all-gather feeding a reduction, and collectives
    chained output-to-input (back-to-back on the wire — nothing between
    them for the scheduler to overlap)."""
    producer = {}  # id(var) -> primitive name
    allgather_reduce = 0
    chained = 0
    for eqn, _ in _walk_eqns_ctx(closed_jaxpr):
        name = eqn.primitive.name
        in_prims = {producer.get(id(v)) for v in eqn.invars}
        if name in _REDUCE_PRIMS and "all_gather" in in_prims:
            allgather_reduce += 1
        if name in _COLLECTIVE_PRIMS and in_prims & _COLLECTIVE_PRIMS:
            chained += 1
        for v in eqn.outvars:
            producer[id(v)] = name
    return {"pt404_allgather_reduce": allgather_reduce,
            "pt404_chained_collectives": chained}


def host_sync_counts(closed_jaxpr) -> dict:
    """PT405 metrics: host round-trips total and inside loop bodies."""
    total, in_loop = 0, 0
    for eqn, loop in _walk_eqns_ctx(closed_jaxpr):
        if eqn.primitive.name in _HOST_SYNC_PRIMS:
            total += 1
            if loop:
                in_loop += 1
    return {"pt405_host_syncs": total, "pt405_loop_host_syncs": in_loop}


def dequant_placement(closed_jaxpr) -> dict:
    """PT406 metrics: int8→float ``convert_element_type`` eqns inside
    vs outside compiled loop bodies.  In the quantized decode program
    every dequant (weights AND KV pages) must be traced INSIDE the scan
    body — a count appearing outside means someone moved
    `_dequant_params` (or the page dequant) out of the loop, and the
    weights would stream full-precision per step on every backend."""
    import jax.numpy as jnp

    in_loop, hoisted = 0, 0
    for eqn, loop in _walk_eqns_ctx(closed_jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = getattr(getattr(eqn.invars[0], "aval", None), "dtype",
                      None)
        dst = getattr(getattr(eqn.outvars[0], "aval", None), "dtype",
                      None)
        if src is None or dst is None:
            continue
        if src == jnp.int8 and jnp.issubdtype(dst, jnp.floating):
            if loop:
                in_loop += 1
            else:
                hoisted += 1
    return {"pt406_dequant_in_loop_count": in_loop,
            "pt406_dequant_hoisted_count": hoisted}


# ---------------------- per-program aggregation ----------------------


def audit_program_texts(where: str, closed_jaxpr=None,
                        stablehlo_text: str = "",
                        opt_hlo_text: str = "",
                        min_replicated_mbytes: float = 0.05,
                        arg_names=None):
    """(violations, metrics) for one program given whichever of its
    three views (jaxpr / StableHLO / optimized HLO) the caller has.
    Pure aggregation — no jax imports, so text fixtures test it
    directly.  ``arg_names`` (flattened jit-argument names) lets the
    PT403 finding name the owning parameters."""
    metrics = {}
    metrics.update(layout_tax(stablehlo_text, opt_hlo_text))
    metrics.update(replicated_args(stablehlo_text,
                                   min_replicated_mbytes))
    metrics.update(collective_hlo_counts(opt_hlo_text))
    if closed_jaxpr is not None:
        metrics["pt402_weak_inputs"] = weak_input_count(closed_jaxpr)
        metrics.update(collective_patterns(closed_jaxpr))
        metrics.update(host_sync_counts(closed_jaxpr))
    out = []
    w = f"perf:{where}"
    if metrics.get("pt401_transpose_count"):
        out.append(Violation(
            w, 0, "PT401",
            f"layout tax: {metrics['pt401_transpose_count']} explicit "
            f"transpose(s) moving {metrics['pt401_transpose_mbytes']} "
            f"MiB per step (compiled: "
            f"{metrics['pt401_opt_transpose_count']} transpose / "
            f"{metrics['pt401_opt_copy_count']} copy / "
            f"{metrics['pt401_opt_bitcast_convert_count']} "
            f"bitcast-convert)"))
    if metrics.get("pt402_weak_inputs"):
        out.append(Violation(
            w, 0, "PT402",
            f"{metrics['pt402_weak_inputs']} weak-typed scalar "
            f"input(s) — each is a jit cache-key split (Python scalar "
            f"vs array argument compile twice)"))
    if metrics.get("pt403_replicated_count"):
        owners = replicated_arg_details(
            stablehlo_text, min_replicated_mbytes, arg_names)
        top = ", ".join(f"{n} {mb} MiB" for n, mb in owners[:4])
        if len(owners) > 4:
            top += f", +{len(owners) - 4} more"
        out.append(Violation(
            w, 0, "PT403",
            f"{metrics['pt403_replicated_count']} argument(s) "
            f"≥{min_replicated_mbytes} MiB left replicated "
            f"({metrics['pt403_replicated_mbytes']} MiB — ZeRO-1 "
            f"weight-update sharding opportunity; top: {top})"))
    if metrics.get("pt404_allgather_reduce"):
        out.append(Violation(
            w, 0, "PT404",
            f"{metrics['pt404_allgather_reduce']} all-gather(s) feeding "
            f"a reduction — reduce-scatter moves 1/N the bytes"))
    if metrics.get("pt404_chained_collectives"):
        out.append(Violation(
            w, 0, "PT404",
            f"{metrics['pt404_chained_collectives']} collective(s) "
            f"chained back-to-back — nothing between them to overlap"))
    if metrics.get("pt405_loop_host_syncs"):
        out.append(Violation(
            w, 0, "PT405",
            f"{metrics['pt405_loop_host_syncs']} host round-trip(s) "
            f"inside a compiled loop body — one device sync per "
            f"iteration"))
    elif metrics.get("pt405_host_syncs"):
        out.append(Violation(
            w, 0, "PT405",
            f"{metrics['pt405_host_syncs']} host round-trip(s) in the "
            f"step program — a device sync per call"))
    return out, metrics


# ---------------------- representative programs ----------------------


def _flat_arg_names(step, placed):
    """Flattened jit-argument names for a ``DistributedTrainStep``'s
    compiled step, index-aligned with the lowered ``@main`` arguments
    (jit flattens positional args in order; dict leaves flatten in
    sorted-key order).  Lets PT403 findings name the owning parameter
    instead of a bare arg index."""
    import jax

    def walk(label, tree):
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        out = []
        for path, _leaf in flat:
            suffix = ""
            for k in path:
                part = getattr(k, "key", None)
                if part is None:
                    part = getattr(k, "idx", None)
                if part is None:
                    part = getattr(k, "name", k)
                suffix += f".{part}"
            out.append(label + suffix)
        return out

    s = step._state
    names = walk("param", s["params"]) + walk("opt", s["opt"]) + \
        walk("buffer", s["buffers"]) + ["key", "lr"]
    names += [f"batch.{i}" for i in range(len(placed))]
    return names


def _train_step_program(batch=2, seq=128, layers=1):
    """The hybrid GPT train step at the proxy shape the Layer-3 audit
    uses (same structure/dtypes as the bench shape, small enough that
    CPU lowering is seconds). Returns ``(lowered, closed_jaxpr)`` — the
    jaxpr is retraced from the step's own ``_step_fn`` with the exact
    placed arguments the executed program sees."""
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        from memory_report import _build_lowered
    finally:
        sys.path.pop(0)
    import paddle_tpu as P

    rs_cfg = dict(vocab_size=1024, hidden_size=64, num_layers=layers,
                  num_heads=4, max_seq_len=seq, fused_head_ce=True,
                  dropout=0.0)
    lowered, model = _build_lowered(rs_cfg, batch, seq)
    step = model._train_step
    jaxpr = names = None
    if step is not None and getattr(step, "_step_fn", None) is not None:
        import numpy as np

        rs = np.random.RandomState(0)
        ids = P.to_tensor(
            rs.randint(0, rs_cfg["vocab_size"], (batch, seq)), "int32")
        labels = P.to_tensor(
            rs.randint(0, rs_cfg["vocab_size"], (batch, seq)), "int32")
        placed, _ = step._place_batch((ids, labels), batch_axis=0)
        s = step._state
        lr = jnp.asarray(step.optimizer.get_lr(), jnp.float32)
        jaxpr = jax.make_jaxpr(step._step_fn)(
            s["params"], s["opt"], s["buffers"], s["key"], lr, *placed)
        names = _flat_arg_names(step, placed)
    return lowered, jaxpr, names


def build_default_multichip_step(model_cfg=None, dp=8, seq=128, layers=1):
    """ONE definition of "the default multi-chip training
    configuration" (docs/SHARDING.md): dp=``dp`` with
    ``sharding_degree=dp`` and NO explicit stage, so the fleet wiring
    must auto-resolve ZeRO-1.  Shared by the static audit below and
    bench.py's ``--multichip-sharded-probe`` — the CI gate and the
    bench placement proof audit the SAME configuration by
    construction.  Returns ``(step, cfg)``; raises if the wiring does
    not resolve ZeRO-1."""
    import paddle_tpu as P
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": dp}
    fleet.init(is_collective=True, strategy=strategy)
    P.seed(0)
    cfg = model_cfg or GPTConfig(
        vocab_size=1024, hidden_size=64, num_layers=layers,
        num_heads=4, max_seq_len=seq, fused_head_ce=True, dropout=0.0)
    inner = GPTForCausalLM(cfg)
    model = fleet.distributed_model(inner)
    opt = fleet.distributed_optimizer(P.optimizer.AdamW(
        parameters=model.parameters(), learning_rate=1e-4))
    step = model.build_train_step(
        opt, GPTPretrainingCriterion(model=inner),
        amp_dtype="bfloat16")
    if step.sharding_stage != 1:
        raise RuntimeError(
            f"expected auto ZeRO-1 under sharding_degree={dp}, got "
            f"stage {step.sharding_stage} — fleet sharding_degree "
            f"wiring broken")
    return step, cfg


def _sharded_train_step_program(batch=8, seq=128, layers=1):
    """The SAME GPT proxy as ``train_step``, built under the default
    multi-chip configuration (``build_default_multichip_step``) — this
    program audits the path users actually get, not a hand-assembled
    one.  The global fleet/topology state it installs is RESTORED
    afterwards: audit results must not depend on program order (the
    later programs re-audit under their own configs, and in-process
    callers like pytest keep their fleet).  Returns
    ``(lowered, closed_jaxpr, arg_names)``."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    import paddle_tpu as P
    from paddle_tpu.distributed import fleet, topology

    prev_topo = topology._topology
    prev_strategy = fleet._state.strategy
    prev_fleet_topo = fleet._state.topo
    prev_init = fleet._state.initialized
    try:
        step, cfg = build_default_multichip_step(
            dp=8, seq=seq, layers=layers)
        rs = np.random.RandomState(0)
        ids = P.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)),
                          "int32")
        labels = P.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)),
                             "int32")
        lowered = step.lower(ids, labels)
        placed, _ = step._place_batch((ids, labels), batch_axis=0)
        s = step._state
        lr = jnp.asarray(step.optimizer.get_lr(), jnp.float32)
        jaxpr = jax.make_jaxpr(step._step_fn)(
            s["params"], s["opt"], s["buffers"], s["key"], lr, *placed)
        return lowered, jaxpr, _flat_arg_names(step, placed)
    finally:
        topology.set_topology(prev_topo)
        fleet._state.strategy = prev_strategy
        fleet._state.topo = prev_fleet_topo
        fleet._state.initialized = prev_init


def _decode_step_program(batch=2, prompt=8, new_tokens=8):
    """The scanned KV-cache decode program — the exact jit object
    ``generate()`` dispatches per chunk (``_decode_chunk_program``),
    lowered at a tiny proxy shape. Returns ``(lowered, closed_jaxpr)``."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as P
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=1,
                    num_heads=4, max_seq_len=prompt + new_tokens)
    model = GPTForCausalLM(cfg)
    model.eval()
    params, buffers = model.functional_state()
    caches = model.init_kv_caches(batch, prompt + new_tokens)
    cap = caches[0][0].shape[2]
    decode_n = model._decode_chunk_program(
        new_tokens, batch, cap, False, 1.0, 0, False, None)
    args = (params, buffers, jnp.zeros((batch,), jnp.int32), caches,
            jnp.asarray(prompt, jnp.int32), jax.random.PRNGKey(0),
            None, jnp.zeros((batch,), bool))
    lowered = decode_n.lower(*args)
    jaxpr = jax.make_jaxpr(decode_n)(*args)
    return lowered, jaxpr


def _swin_train_step_program(batch=2, img=32):
    """The Swin train step at a tiny proxy shape (one shifted block in
    stage 1, bf16 AMP, Momentum) — the vision twin of ``train_step``.
    Its PT401 numbers pin the windowed-attention layout tax (roll /
    window-partition 6-D transposes, rel-pos-bias plumbing) statically,
    the same way the GPT step's budget pins the flash layout tax
    (ISSUE 10; PERF.md Swin ablation: that machinery alone costs ~43%
    of achievable step rate on-chip).  Returns ``(lowered, jaxpr)``."""
    import numpy as np

    import jax

    import paddle_tpu as P
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.vision.models import SwinTransformer

    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    P.seed(0)
    inner = SwinTransformer(img_size=img, patch_size=4, embed_dim=32,
                            depths=(2, 2), num_heads=(2, 4),
                            window_size=4, num_classes=8)
    model = fleet.distributed_model(inner)
    opt = fleet.distributed_optimizer(P.optimizer.Momentum(
        parameters=model.parameters(), learning_rate=1e-3, momentum=0.9))
    step = model.build_train_step(opt, P.nn.CrossEntropyLoss(),
                                  amp_dtype="bfloat16")
    rs = np.random.RandomState(0)
    imgs = P.to_tensor(rs.rand(batch, 3, img, img).astype(np.float32))
    labels = P.to_tensor(rs.randint(0, 8, (batch,)), "int32")
    lowered = step.lower(imgs, labels)
    jaxpr = names = None
    if getattr(step, "_step_fn", None) is not None:
        import jax.numpy as jnp

        placed, _ = step._place_batch((imgs, labels), batch_axis=0)
        s = step._state
        lr = jnp.asarray(step.optimizer.get_lr(), jnp.float32)
        jaxpr = jax.make_jaxpr(step._step_fn)(
            s["params"], s["opt"], s["buffers"], s["key"], lr, *placed)
        names = _flat_arg_names(step, placed)
    return lowered, jaxpr, names


def _paged_decode_step_program(slots=2, pages_per_seq=4, page_size=8,
                               chunk=4):
    """The continuous-batching engine's ragged paged decode program
    (``InferenceEngine._decode_program``) at a tiny proxy shape — the
    serving hot step (ISSUE 8).  Budgeting its layout/transpose counts
    means a relayout regression in the paged-attention path fails CI
    before any hardware run.  Returns ``(lowered, closed_jaxpr)``."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as P
    from paddle_tpu.inference.engine import EngineConfig, InferenceEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(0)
    max_len = page_size * pages_per_seq
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=1,
                    num_heads=4, max_seq_len=max_len)
    model = GPTForCausalLM(cfg)
    model.eval()
    eng = InferenceEngine(model, EngineConfig(
        page_size=page_size, max_slots=slots, decode_chunk=chunk,
        max_seq_len=max_len))
    decode = eng._decode_program(chunk)
    args = (eng._params, eng._buffers, eng._k_pools, eng._v_pools,
            [], [],
            jnp.zeros((slots,), jnp.int32),
            jnp.zeros((slots, eng.max_pages_per_seq), jnp.int32),
            jnp.zeros((slots,), jnp.int32))
    lowered = decode.lower(*args)
    jaxpr = jax.make_jaxpr(decode)(*args)
    return lowered, jaxpr


def _quantized_decode_step_program(slots=2, pages_per_seq=4, page_size=8,
                                   chunk=4):
    """The SAME paged decode proxy under BOTH quantized tiers
    (``weight_precision='int8'`` + ``kv_precision='int8'`` — ISSUE 12):
    its budget pins the quantized hot step's layout counts AND the
    PT406 dequant placement (every int8→float dequant traced inside the
    scan body, none hoisted).  Returns
    ``(lowered, closed_jaxpr, None, meta)`` where meta carries the
    expected dequant count (quantized weights + K/V page dequants per
    layer) for the derived ``pt406_dequant_deficit``."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as P
    from paddle_tpu.inference.engine import EngineConfig, InferenceEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(0)
    max_len = page_size * pages_per_seq
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=1,
                    num_heads=4, max_seq_len=max_len)
    model = GPTForCausalLM(cfg)
    model.eval()
    eng = InferenceEngine(model, EngineConfig(
        page_size=page_size, max_slots=slots, decode_chunk=chunk,
        max_seq_len=max_len, weight_precision="int8",
        kv_precision="int8"))
    decode = eng._decode_program(chunk)
    args = (eng._params, eng._buffers, eng._k_pools, eng._v_pools,
            eng._k_scales, eng._v_scales,
            jnp.zeros((slots,), jnp.int32),
            jnp.zeros((slots, eng.max_pages_per_seq), jnp.int32),
            jnp.zeros((slots,), jnp.int32))
    lowered = decode.lower(*args)
    jaxpr = jax.make_jaxpr(decode)(*args)
    # per scan step: one dequant per quantized weight + one per K and V
    # page gather per layer
    meta = {"expected_s8_dequants": len(eng._wq_meta) + 2 * eng._layers}
    return lowered, jaxpr, None, meta


def _cached_prefill_step_program(slots=2, pages_per_seq=8, page_size=8,
                                 tail_bucket=8, prefix_pages=2):
    """The prefix cache's WARM tail-prefill program
    (``InferenceEngine._cached_prefill_program``, ISSUE 13) at a tiny
    proxy shape: prefix capacity bucketed to `prefix_pages` (power of
    two), tail bucketed to `tail_bucket`.  Budgeting it pins the warm
    path's layout counts AND its PT402 surface — a per-cached-length
    recompile hazard (shapes leaking the actual shared length instead
    of the bucket) is exactly the regression this program exists to
    catch.  Returns ``(lowered, closed_jaxpr)``."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as P
    from paddle_tpu.inference.engine import EngineConfig, InferenceEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(0)
    max_len = page_size * pages_per_seq
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=1,
                    num_heads=4, max_seq_len=max_len)
    model = GPTForCausalLM(cfg)
    model.eval()
    eng = InferenceEngine(model, EngineConfig(
        page_size=page_size, max_slots=slots,
        prefill_bucket=tail_bucket, max_seq_len=max_len))
    cpre = eng._cached_prefill_program(tail_bucket, prefix_pages)
    args = (eng._params, eng._buffers,
            jnp.zeros((1, tail_bucket), jnp.int32),
            jnp.zeros((1,), jnp.int32),
            jnp.zeros((prefix_pages,), jnp.int32),
            jnp.asarray(page_size * prefix_pages, jnp.int32),
            eng._k_pools, eng._v_pools)
    lowered = cpre.lower(*args)
    jaxpr = jax.make_jaxpr(cpre)(*args)
    return lowered, jaxpr


def _audit_lowered(name: str, lowered, jaxpr=None, arg_names=None):
    """All three views of one lowered program -> (violations, metrics).
    A missing view is a PT400 — an absent metric is invisible to the
    budget diff (only present metrics are judged), so partial blindness
    must fail the gate loudly, not pass quietly."""
    text = lowered.as_text()
    opt = ""
    pre = []
    if jaxpr is None:
        pre.append(Violation(f"perf:{name}", 0, "PT400",
                             "jaxpr view unavailable — PT402/PT404/"
                             "PT405 metrics not audited for this "
                             "program"))
    try:
        opt = lowered.compile().as_text()
    except Exception as e:
        # compiled view is additive evidence — keep the text/jaxpr audit
        # alive on backends that refuse to compile the proxy shape, but
        # surface the blind spot
        pre.append(Violation(f"perf:{name}", 0, "PT400",
                             f"compile failed ({type(e).__name__}) — "
                             f"optimized-HLO view unavailable"))
    v, m = audit_program_texts(name, closed_jaxpr=jaxpr,
                               stablehlo_text=text, opt_hlo_text=opt,
                               arg_names=arg_names)
    return pre + v, m


def _audit_op_table(limit=None):
    """PT4xx sweep over the manifest's unary/binary conformance surface
    (tracing only — the jaxpr carries everything these rules need for
    elementwise ops)."""
    import jax

    from .hlo_audit import iter_op_callables

    violations, totals = [], {
        "pt401_transpose_count": 0, "pt402_weak_inputs": 0,
        "pt404_allgather_reduce": 0, "pt404_chained_collectives": 0,
        "pt405_host_syncs": 0, "pt405_loop_host_syncs": 0,
    }
    for name, fn, args in iter_op_callables(limit=limit):
        if fn is None:
            violations.append(Violation(
                f"perf:op:{name}", 0, "PT400",
                "op does not resolve — cannot audit"))
            continue
        try:
            jaxpr = jax.make_jaxpr(fn)(*args)
        except Exception as e:
            jaxpr = None
            if len(args) == 2:
                # ternary-shaped "binary" ops (lerp): scalar third
                # operand, mirroring the Layer-3 sweep's retry
                from .hlo_audit import _resolve_op

                import paddle_tpu as P
                from paddle_tpu.core.tensor import Tensor

                op = _resolve_op(name)

                def traced3(a, b, _op=op):
                    r = _op(P.to_tensor(a), P.to_tensor(b), 0.5)
                    return r._value if isinstance(r, Tensor) else r
                try:
                    jaxpr = jax.make_jaxpr(traced3)(*args)
                except Exception:
                    jaxpr = None
            if jaxpr is None:
                violations.append(Violation(
                    f"perf:op:{name}", 0, "PT400",
                    f"trace failed ({type(e).__name__})"))
                continue
        totals["pt402_weak_inputs"] += weak_input_count(jaxpr)
        for k, v in collective_patterns(jaxpr).items():
            totals[k] += v
        for k, v in host_sync_counts(jaxpr).items():
            totals[k] += v
        n_t = sum(1 for eqn, _ in _walk_eqns_ctx(jaxpr)
                  if eqn.primitive.name == "transpose")
        totals["pt401_transpose_count"] += n_t
        if n_t:
            violations.append(Violation(
                f"perf:op:{name}", 0, "PT401",
                f"{n_t} transpose(s) in an elementwise op's trace"))
    return violations, totals


def _audit_call_sites(repo_root=None, roots=None):
    """The stdlib-only program: PT402 call-site hazards across the
    tree."""
    from .runner import DEFAULT_ROOTS, iter_python_files

    repo_root = repo_root or _REPO
    violations = []
    for rel in iter_python_files(repo_root, roots or DEFAULT_ROOTS):
        with open(os.path.join(repo_root, rel), encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue  # the ast layer owns PT000 for unparsable files
        violations.extend(call_site_hazards(source, rel, tree=tree))
    return violations, {"pt402_call_site_hazards": len(violations)}


def _ensure_cpu_env():
    """Pin the audit environment to CPU + 8 virtual devices — the same
    mesh the test conftest forces. The optimized-HLO metrics are only
    byte-stable within one backend config, so the CLI and the pytest
    gate must compile under the same one or the committed budget cannot
    satisfy both.

    This container's sitecustomize imports jax and pins
    ``JAX_PLATFORMS=axon`` at interpreter start, so "jax not imported
    yet" cannot be assumed and env vars alone do not stick: when the
    config already points at a non-CPU platform, route through
    ``backend_guard.force_cpu_mesh`` (drops the axon factory, overrides
    the captured config, clears stale backends). A jax already on CPU
    (the pytest path — conftest set the 8-device mesh) is left alone:
    force-clearing live backends mid-suite would invalidate arrays."""
    if "jax" not in sys.modules:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        # fall through: sitecustomize may still have pinned the config
    import jax

    platforms = getattr(jax.config, "jax_platforms", None) or \
        os.environ.get("JAX_PLATFORMS", "")
    if platforms and not str(platforms).startswith("cpu"):
        try:
            from ..backend_guard import force_cpu_mesh
        except ImportError:
            # standalone package load (pt_lint's jax-free fast path
            # loads analysis/ as top-level `pt_analysis`)
            from paddle_tpu.backend_guard import force_cpu_mesh

        force_cpu_mesh(8)


def audit_perf(programs=DEFAULT_PROGRAMS, repo_root=None):
    """Run the perf audit over the named representative programs.

    Returns ``(violations, metrics)`` where metrics is
    ``{program_name: {metric: number}}`` — the budget unit. Program
    build failures surface as PT400 findings with an empty metrics
    entry (a blind audit must fail the gate loudly, not pass quietly)."""
    if set(programs) - {"call_sites"}:
        _ensure_cpu_env()
    violations, metrics = [], {}
    for prog in programs:
        if prog == "call_sites":
            v, m = _audit_call_sites(repo_root)
        elif prog in ("train_step", "sharded_train_step",
                      "swin_train_step", "decode_step",
                      "paged_decode_step", "quantized_decode_step",
                      "cached_prefill_step"):
            full = {"train_step": "gpt125m_train_step",
                    "sharded_train_step": "gpt_sharded_train_step",
                    "swin_train_step": "swin_train_step",
                    "decode_step": "gpt_decode_step",
                    "paged_decode_step": "gpt_paged_decode_step",
                    "quantized_decode_step":
                        "gpt_quantized_decode_step",
                    "cached_prefill_step":
                        "gpt_cached_prefill_step"}[prog]
            build = {"train_step": _train_step_program,
                     "sharded_train_step": _sharded_train_step_program,
                     "swin_train_step": _swin_train_step_program,
                     "decode_step": _decode_step_program,
                     "paged_decode_step": _paged_decode_step_program,
                     "quantized_decode_step":
                         _quantized_decode_step_program,
                     "cached_prefill_step":
                         _cached_prefill_step_program}[prog]
            try:
                out = build()
            except Exception as e:
                v, m = [Violation(f"perf:{full}", 0, "PT400",
                                  f"{prog} failed to build/lower "
                                  f"({type(e).__name__}: "
                                  f"{str(e)[:80]})")], {}
            else:
                lowered, jaxpr = out[0], out[1]
                names = out[2] if len(out) > 2 else None
                prog_meta = out[3] if len(out) > 3 else {}
                v, m = _audit_lowered(full, lowered, jaxpr,
                                      arg_names=names)
                if prog == "quantized_decode_step" and m \
                        and jaxpr is not None:
                    # PT406: every int8 dequant must be TRACED inside
                    # the scan body — hoisted > 0 means the weights
                    # stream full-precision per step; the deficit
                    # (expected minus in-loop, floored at 0) catches
                    # the opposite failure, the tier silently not
                    # quantizing at all (fewer dequants would read as
                    # an "improvement" under a plain ceiling)
                    m.update(dequant_placement(jaxpr))
                    expected = prog_meta.get("expected_s8_dequants", 0)
                    m["pt406_dequant_deficit"] = max(
                        0, expected - m["pt406_dequant_in_loop_count"])
                    if m["pt406_dequant_hoisted_count"]:
                        v.append(Violation(
                            f"perf:{full}", 0, "PT406",
                            f"{m['pt406_dequant_hoisted_count']} "
                            f"int8 dequant(s) traced OUTSIDE the "
                            f"decode scan body — the weight stream "
                            f"is full-precision per step"))
                    if m["pt406_dequant_deficit"]:
                        v.append(Violation(
                            f"perf:{full}", 0, "PT406",
                            f"only "
                            f"{m['pt406_dequant_in_loop_count']} of "
                            f"{expected} expected int8 dequants in "
                            f"the scan body — a quantized tier is "
                            f"silently inactive"))
                if prog == "sharded_train_step" and m and names:
                    # per-parameter grad sync or bust: the raw counts
                    # only gate INCREASES (budget = ceiling), but the
                    # fused-barrier regression LOWERS them — this
                    # derived deficit (params minus scheduled additive
                    # collectives, floored at 0) rises instead, and its
                    # committed budget of 0 makes `--perf --check` fail
                    n_params = sum(1 for x in names
                                   if x.startswith("param."))
                    sync = m.get("pt404_opt_all_reduce_count", 0) + \
                        m.get("pt404_opt_reduce_scatter_count", 0)
                    m["pt404_grad_sync_deficit"] = max(
                        0, n_params - sync)
                    if m["pt404_grad_sync_deficit"]:
                        v.append(Violation(
                            f"perf:{full}", 0, "PT404",
                            f"only {sync} additive collective(s) for "
                            f"{n_params} parameters — grad sync has "
                            f"been fused toward a barrier (overlap "
                            f"lost)"))
            metrics[full] = m
            violations.extend(v)
            continue
        elif prog == "op_table":
            v, m = _audit_op_table()
        else:
            raise ValueError(f"unknown perf program {prog!r}; expected "
                             f"one of {FULL_PROGRAMS}")
        metrics[prog] = m
        violations.extend(v)
    violations.sort(key=Violation.sort_key)
    return violations, metrics


def metrics_to_static_rows(metrics: dict) -> list:
    """Budget metrics -> perf_gate-compatible metric rows
    (``static.<program>.<metric>``, all lower-better: every PT4xx
    number is a cost)."""
    rows = []
    for prog in sorted(metrics):
        for name in sorted(metrics[prog]):
            rows.append({"metric": f"static.{prog}.{name}",
                         "value": metrics[prog][name],
                         "unit": "mbytes" if name.endswith("_mbytes")
                         else "count",
                         "lower_better": True})
    return rows


# ----------------- MFU forensics (tools/hlo_audit shim) -----------------

_DOT = re.compile(
    r"stablehlo\.dot_general[^\n]*:\s*\(tensor<[0-9x]+x(\w+)>,\s*"
    r"tensor<[0-9x]+x(\w+)>\)\s*-> tensor<([0-9x]+)x(\w+)>")
_TRANSPOSE_FULL = re.compile(
    r"stablehlo\.transpose[^\n]*?dims = \[([\d, ]+)\][^\n]*"
    r"-> tensor<([0-9x]+)x(\w+)>")


def audit_hlo(hlo_text: str, min_numel: int = 1 << 14):
    """Bucket dots by OPERAND dtype and big transposes by moved bytes —
    the chip-free MFU forensics previously in ``tools/hlo_audit.py``
    (that file is now a thin shim over this function, so the tool and
    the analysis package cannot drift).

    bf16 operands with f32 accumulation (``preferred_element_type``) is
    the full-rate MXU mode — a dot is only a quarter-rate problem when
    an OPERAND is f32."""
    dots = {"bf16_operands": 0, "f32_operands": 0, "mixed": 0, "other": 0}
    f32_dot_shapes = []
    for m in _DOT.finditer(hlo_text):
        lhs, rhs, dims, _ = m.groups()
        if lhs == rhs == "bf16":
            key = "bf16_operands"
        elif lhs == rhs == "f32":
            key = "f32_operands"
        elif {lhs, rhs} <= {"bf16", "f32"}:
            key = "mixed"
        else:
            key = "other"
        dots[key] += 1
        if key != "bf16_operands" and _numel(dims) >= min_numel:
            f32_dot_shapes.append(f"{lhs}x{rhs}->[{dims}]")
    transposes = []
    for m in _TRANSPOSE_FULL.finditer(hlo_text):
        perm, dims, dt = m.groups()
        n = _numel(dims)
        if n >= min_numel:
            transposes.append(
                {"dtype": dt, "shape": dims,
                 "perm": perm.replace(" ", ""),
                 "mbytes": round(n * _ITEMSIZE.get(dt, 4) / 2**20, 2)})
    transposes.sort(key=lambda t: -t["mbytes"])
    return {"dot_counts": dots,
            "big_non_bf16_dots": f32_dot_shapes[:20],
            "big_transposes": transposes[:20],
            "transpose_mbytes_total": round(
                sum(t["mbytes"] for t in transposes), 1)}


def train_step_hlo(batch=4, seq=1024, layers=2):
    """Lower the GPT train step at bench dtypes (reduced batch/depth)
    and return its PRE-OPTIMIZATION StableHLO text. Pre-optimization is
    the honest view for dtypes: XLA:CPU's optimized HLO legalizes every
    bf16 dot to f32 (no bf16 units on CPU), which says nothing about
    the TPU program."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        from memory_report import _build_lowered
    finally:
        sys.path.pop(0)
    lowered, _ = _build_lowered(
        dict(vocab_size=50304, hidden_size=768, num_layers=layers,
             num_heads=12, max_seq_len=seq, fused_head_ce=True,
             dropout=0.0),
        batch, seq)
    return lowered.as_text()
