"""Layer 5: whole-program concurrency auditor (rules PT501–PT505).

Layer 2 (PT101/PT102) checks attributes someone already wrote a
``with self._lock:`` around at least once — it cannot see the shared
attribute nobody thought to guard, the blocking call made while a lock
is held, or two locks taken in opposite orders.  Those are exactly the
bug classes every serving-fleet review has caught by hand (the PR 9
monitor blocking under its own supervision lock, PR 14's mid-sweep
membership races).  This layer *infers* the concurrency structure from
:mod:`.threadmodel` — thread roots, per-class lock models, held-lock
sets per access — and reports:

  PT501  a blocking call executed while a lock is held: ``time.sleep``,
         ``subprocess``/``Popen.wait``/``.join()``, socket/HTTP
         requests, ``queue.get()``/``cv.wait()``/``Event.wait()``
         without a timeout, ``open()`` file I/O — the monitor-stall
         class.  Interprocedural one level: ``with self._lock:
         self._helper()`` flags when the helper's body blocks.
         Waiting on a condition variable whose OWN lock is the only
         one held is exempt (the wait releases it).
  PT502  a lock-order inversion: a cycle in the acquisition-order
         graph (lock B taken while A held on one path, A while B held
         on another), including cross-class edges when a guarded
         method calls into another lock-owning object
         (``self.attr.m()`` with ``attr``'s class known).
  PT503  an attribute reachable from ≥2 inferred thread roots, written
         at least once outside construction, with NO lock observed
         guarding any access — the shared state nobody thought about.
  PT504  guard drift: the same attribute guarded by lock A at some
         sites and lock B at others; or read under a lock while every
         write is lock-free; or a helper annotated "callers hold the
         lock" (``# pt-lint: ok[PT101,...]`` on its ``def``) actually
         called somewhere with no lock held — the annotation
         contradicts what inference derives, loudly.
  PT505  condition-variable misuse: ``cv.wait()`` outside a ``while``
         predicate loop (an ``if`` does not survive spurious wakeups),
         or ``notify``/``notify_all`` without holding the cv.

The pass is whole-program over ``paddle_tpu/`` + ``tools/`` (tests are
fixture-heavy by design and excluded), stdlib-only, and flows through
the standard `Violation`/suppression/baseline machinery: annotate a
deliberate lock-free reader with ``# pt-lint: ok[PT503] (why)`` and
the gate stays green with an EMPTY baseline.
"""
from __future__ import annotations

import ast
import os

from . import threadmodel as tm
from .report import Suppressions, Violation

__all__ = ["analyze_project", "analyze_source", "audit_classes",
           "RULE_IDS", "CONC_ROOTS"]

RULE_IDS = ("PT501", "PT502", "PT503", "PT504", "PT505")

# the serving/observability production tree; tests/ is deliberately out
# (its fixtures create threads and races on purpose)
CONC_ROOTS = ("paddle_tpu", "tools")

EXTERNAL_ROOT = "<caller>"

# --- blocking-call classification (PT501) ---------------------------------
# tails that always block (no timeout makes them safe enough to hold a
# lock across): sleeps, process waits, sockets/HTTP, file IO
_ALWAYS_BLOCKING = {
    "sleep": "time.sleep",
    "communicate": "Popen.communicate",
    "run": None,            # subprocess.run only (see below)
    "call": None,           # subprocess.call
    "check_call": None,
    "check_output": None,
    "Popen": "process spawn",
    "urlopen": "HTTP request",
    "getresponse": "HTTP response read",
    "create_connection": "socket connect",
    "recv": "socket recv",
    "accept": "socket accept",
    "connect": "socket connect",
    "sendall": "socket send",
    "request": "HTTP request",
}
_SUBPROCESS_ONLY = {"run", "call", "check_call", "check_output"}
# tails that block only when called with NO timeout (arg or kwarg)
_TIMEOUT_BLOCKING = {"wait", "join", "get", "acquire"}


def _blocking_reason(cls: tm.ClassModel, call: tm.RawCall):
    """Why this raw call is considered blocking, or None."""
    tail, name = call.tail, call.name
    if name == "open":
        # the bare builtin only — `self.index.open()` is not file I/O
        return "file open()"
    if tail in _ALWAYS_BLOCKING:
        if tail in _SUBPROCESS_ONLY:
            return (f"subprocess.{tail}" if name.startswith("subprocess.")
                    else None)
        if tail == "sleep" and not (
                name in ("time.sleep", "sleep")
                or name.endswith(".sleep")):
            return None
        return _ALWAYS_BLOCKING[tail] or name
    if tail in _TIMEOUT_BLOCKING:
        if call.has_timeout:
            return None
        if tail == "acquire":
            # lock.acquire() is PT502's domain (ordering), not a stall
            return None
        if tail == "get":
            # q.get() blocks; d.get(k[, default]) does not — a zero-arg
            # no-kwarg .get() cannot be the dict method
            return None if call.has_args else "queue.get() without timeout"
        if tail in ("wait", "join"):
            if call.has_args:
                # wait(5.0)/join(2.0): a positional timeout
                return None
            return f".{tail}() without timeout"
    return None


def _cv_self_wait_exempt(cls: tm.ClassModel, call: tm.RawCall) -> bool:
    """`with self._cv: self._cv.wait()` releases the lock it holds —
    blocking there is the POINT.  Exempt when the only held locks are
    the cv's own identity."""
    if call.tail not in ("wait", "wait_for") or call.recv_attr is None:
        return False
    if cls.locks.get(call.recv_attr) != "cond":
        return False
    cv_id = cls.canon(call.recv_attr)
    held = cls.canon_set(call.locks)
    return held <= {cv_id}


# ---------------------------------------------------------------------------
# per-class rule passes
# ---------------------------------------------------------------------------

def _audit_pt501(cls: tm.ClassModel, out: list):
    for m in cls.methods.values():
        if m.name in tm.SKIP_METHODS or \
                m.name in cls.construction_only:
            continue
        for call in m.raw_calls:
            held = cls.held_at(m.name, call.locks)
            if not held:
                continue
            reason = _blocking_reason(cls, call)
            if reason is None or _cv_self_wait_exempt(cls, call):
                continue
            # a cv.wait under its own lock PLUS another lock still
            # stalls the other lock's waiters — keep those
            if call.tail in ("wait", "wait_for") and \
                    cls.locks.get(call.recv_attr) == "cond":
                held = held - {cls.canon(call.recv_attr)}
                if not held:
                    continue
            locks = ",".join(sorted(held))
            out.append(Violation(
                cls.file, call.line, "PT501",
                f"{cls.name}.{call.method} blocks ({reason}) while "
                f"holding `{locks}`"))
        # one level deep: a locked call into a same-class helper whose
        # body blocks (lexically lock-free there, so the body site
        # itself stays clean)
        for site in m.calls:
            held = cls.held_at(m.name, site.locks)
            if not held:
                continue
            callee = cls.methods.get(site.callee)
            if callee is None or callee.name in tm.SKIP_METHODS:
                continue
            callee_own = cls.presumed.get(callee.name, frozenset())
            if callee_own:
                continue  # the helper's body reports itself (presumed)
            for call in callee.raw_calls:
                if cls.canon_set(call.locks):
                    continue  # the helper's own locked sites report
                    # at the helper (with its own held set)
                reason = _blocking_reason(cls, call)
                if reason is None:
                    continue
                out.append(Violation(
                    cls.file, site.line, "PT501",
                    f"{cls.name}.{site.method} holds `"
                    f"{','.join(sorted(held))}` across call to "
                    f"`{site.callee}` which blocks ({reason})"))
                break  # one finding per call site, not per sleep


def _lock_node(cls: tm.ClassModel, lock: str) -> str:
    return f"{cls.name}.{cls.canon(lock)}"


def _collect_lock_edges(classes_by_name: dict, cls: tm.ClassModel,
                        edges: dict):
    """Acquisition-order edges `held -> taken`, same-class and one
    level cross-class (`self.attr.m()` with a lock-owning attr type)."""
    for m in cls.methods.values():
        for acq in m.acquires:
            held = cls.held_at(m.name, acq.held)
            taken = cls.canon(acq.lock)
            for h in held:
                if h == taken:
                    continue
                edges.setdefault(
                    (_lock_node(cls, h), _lock_node(cls, taken)),
                    (cls.file, acq.line,
                     f"{cls.name}.{acq.method}"))
        for ext in m.ext_calls:
            held = cls.held_at(m.name, ext.locks)
            if not held:
                continue
            target_cls = classes_by_name.get(
                cls.attr_types.get(ext.attr))
            if target_cls is None:
                continue
            callee = target_cls.methods.get(ext.meth)
            if callee is None:
                continue
            taken_locks = {target_cls.canon(a.lock)
                           for a in callee.acquires}
            taken_locks |= target_cls.propagated_locks(callee.name)
            for h in held:
                for t in taken_locks:
                    edges.setdefault(
                        (_lock_node(cls, h), _lock_node(target_cls, t)),
                        (cls.file, ext.line,
                         f"{cls.name}.{ext.method} -> "
                         f"{target_cls.name}.{ext.meth}"))


def _find_cycles(edges: dict) -> list:
    """Elementary cycles in the acquisition graph (DFS, deduplicated by
    rotation-normalized node set) — the graph is tiny."""
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    cycles, seen = [], set()

    def dfs(start, node, path, on_path):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                lo = path.index(min(path))
                norm = tuple(path[lo:] + path[:lo])
                if norm not in seen:
                    seen.add(norm)
                    cycles.append(list(path))
            elif nxt not in on_path and nxt > start:
                # only walk nodes > start: each cycle is found exactly
                # once, from its smallest node
                dfs(start, nxt, path + [nxt], on_path | {nxt})

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles


def _audit_pt502(classes: list, out: list):
    classes_by_name: dict = {}
    for cls in classes:
        # first definition wins; ambiguous names simply resolve to one
        # of the candidates (a lint, not a type checker)
        classes_by_name.setdefault(cls.name, cls)
    edges: dict = {}
    for cls in classes:
        _collect_lock_edges(classes_by_name, cls, edges)
    for cycle in _find_cycles(edges):
        # anchor the finding at the first edge of the cycle
        first = edges.get((cycle[0], cycle[1 % len(cycle)]))
        if first is None:
            continue
        file, line, where = first
        order = " -> ".join(cycle + [cycle[0]])
        out.append(Violation(
            file, line, "PT502",
            f"lock-order inversion: acquisition cycle {order} "
            f"(first edge in {where})"))


def _roots_reaching(cls: tm.ClassModel) -> dict:
    """method name -> set of root labels whose transitive same-class
    call closure includes it.  Public methods are additionally entries
    from the constructing/calling thread (EXTERNAL_ROOT)."""
    callees: dict = {name: {c.callee for c in m.calls}
                     for name, m in cls.methods.items()}
    reach: dict = {name: set() for name in cls.methods}

    def mark(root_label, start):
        stack, visited = [start], set()
        while stack:
            name = stack.pop()
            if name in visited or name not in reach:
                continue
            visited.add(name)
            reach[name].add(root_label)
            stack.extend(callees.get(name, ()))

    handler_only = bool(cls.thread_roots) and all(
        "HTTP handler" in why for why in cls.thread_roots.values())
    for root, why in cls.thread_roots.items():
        if "HTTP handler" in why:
            # one label for ALL handler methods: a handler instance is
            # per-request (BaseHTTPRequestHandler), so do_GET/do_POST
            # of the SAME instance never race each other — the handler
            # root only counts as concurrent against Thread roots or
            # external callers
            mark("root:<http-handler>", root)
        else:
            mark(f"root:{root}", root)
    for name, m in cls.methods.items():
        if not name.startswith("_") and not m.is_pseudo \
                and name not in cls.thread_roots:
            # a pure request-handler class has no external entry:
            # nothing but the server ever calls it
            if not handler_only:
                mark(EXTERNAL_ROOT, name)
    return reach


def _audit_pt503_pt504(cls: tm.ClassModel, sup: Suppressions,
                       out: list):
    if not cls.methods:
        return
    reach = _roots_reaching(cls)
    # gather per-attribute access facts (construction excluded by the
    # model; lock/threadsafe attrs are infrastructure, not state)
    per_attr: dict = {}
    for m in cls.methods.values():
        if m.name in tm.SKIP_METHODS or \
                m.name in cls.construction_only:
            continue
        for a in m.accesses:
            if a.attr in cls.locks or a.attr in cls.threadsafe:
                continue
            locks = cls.effective_locks(m, a)
            per_attr.setdefault(a.attr, []).append((a, m, locks))

    for attr in sorted(per_attr):
        accesses = per_attr[attr]
        locked = [(a, m, lk) for (a, m, lk) in accesses if lk]
        unlocked = [(a, m, lk) for (a, m, lk) in accesses if not lk]
        writes = [(a, m, lk) for (a, m, lk) in accesses if a.write]

        # -- PT503: shared, written, never guarded ------------------
        if cls.thread_roots and writes and not locked:
            roots = set()
            for (a, m, _lk) in accesses:
                roots |= reach.get(m.name, set())
            if len(roots) >= 2:
                a, m, _lk = writes[0]
                names = ", ".join(sorted(roots))
                out.append(Violation(
                    cls.file, a.line, "PT503",
                    f"{cls.name}.{attr} is reachable from "
                    f"{len(roots)} thread roots ({names}), written in "
                    f"{m.name}, and no lock guards any access"))
                continue  # drift questions are moot without any lock

        # -- PT504 (a): same attr under two disjoint lock sets ------
        drift = None
        for (a1, m1, lk1) in locked:
            for (a2, m2, lk2) in locked:
                if a2.line <= a1.line:
                    continue
                if lk1.isdisjoint(lk2):
                    drift = (a1, m1, lk1, a2, m2, lk2)
                    break
            if drift:
                break
        if drift:
            a1, m1, lk1, a2, m2, lk2 = drift
            out.append(Violation(
                cls.file, a2.line, "PT504",
                f"{cls.name}.{attr} guard drift: guarded by "
                f"`{','.join(sorted(lk1))}` in {m1.name} but "
                f"`{','.join(sorted(lk2))}` in {m2.name}"))
            continue

        # -- PT504 (b): read under a lock, written only lock-free ---
        locked_reads = [(a, m, lk) for (a, m, lk) in locked
                        if not a.write]
        locked_writes = [(a, m, lk) for (a, m, lk) in locked if a.write]
        unlocked_writes = [(a, m, lk) for (a, m, lk) in unlocked
                           if a.write]
        if locked_reads and unlocked_writes and not locked_writes:
            a, m, _lk = unlocked_writes[0]
            ra, rm, rlk = locked_reads[0]
            out.append(Violation(
                cls.file, a.line, "PT504",
                f"{cls.name}.{attr} guard drift: read under "
                f"`{','.join(sorted(rlk))}` in {rm.name} but written "
                f"with no lock in {m.name}"))

    # -- PT504 (c): "callers hold the lock" annotation vs inference --
    for name, m in cls.methods.items():
        if m.is_pseudo or m.name in tm.SKIP_METHODS:
            continue
        claims = sup.guard_claims(m.lineno) & {"PT101", "PT102"}
        if not claims:
            continue
        for site in cls.call_sites_of(name):
            if site.method in cls.construction_only:
                continue  # pre-sharing call — no lock needed yet
            held = cls.held_at(site.method, site.locks)
            if held:
                continue
            out.append(Violation(
                cls.file, site.line, "PT504",
                f"{cls.name}.{site.method} calls `{name}` with no "
                f"lock held, but its annotation claims callers hold "
                f"the lock — the annotation contradicts inference"))


def _audit_pt505(cls: tm.ClassModel, file_tree, out: list):
    conds = {a for a, kind in cls.locks.items() if kind == "cond"}
    if not conds:
        return
    # notify/notify_all need the cv held
    for m in cls.methods.values():
        if m.name in tm.SKIP_METHODS:
            continue
        for call in m.raw_calls:
            if call.recv_attr not in conds:
                continue
            held = cls.held_at(m.name, call.locks)
            cv_id = cls.canon(call.recv_attr)
            if call.tail in ("notify", "notify_all") and \
                    cv_id not in held:
                out.append(Violation(
                    cls.file, call.line, "PT505",
                    f"{cls.name}.{call.method} calls "
                    f"`{call.recv_attr}.{call.tail}()` without "
                    f"holding the condition"))
    # cv.wait() must sit inside a while-predicate loop (spurious
    # wakeups; an `if` checks the predicate once).  wait_for loops
    # internally and is exempt.  This needs the AST shape, not just
    # the model: find the wait calls and their enclosing statements.
    if file_tree is None:
        return
    for node in ast.walk(file_tree):
        if not isinstance(node, ast.ClassDef) or \
                node.name != cls.name or node.lineno != cls.lineno:
            continue
        _check_waits_in_while(cls, node, conds, out)
        break


def _check_waits_in_while(cls, cls_node, conds, out):
    def visit(node, in_while, func):
        for child in ast.iter_child_nodes(node):
            child_in_while = in_while
            if isinstance(child, ast.While):
                child_in_while = True
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                visit(child, False, child.name)
                continue
            elif isinstance(child, ast.ClassDef):
                continue
            if isinstance(child, ast.Call):
                f = child.func
                if isinstance(f, ast.Attribute) and f.attr == "wait" \
                        and tm.self_attr(f.value) in conds \
                        and not in_while:
                    out.append(Violation(
                        cls.file, child.lineno, "PT505",
                        f"{cls.name}.{func} calls "
                        f"`{tm.self_attr(f.value)}.wait()` outside a "
                        f"`while` predicate loop (an `if` does not "
                        f"survive spurious wakeups)"))
            visit(child, child_in_while, func)

    for fn in cls_node.body:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and fn.name not in tm.SKIP_METHODS:
            visit(fn, False, fn.name)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def audit_classes(models: list, suppressions: dict,
                  trees: dict | None = None) -> list:
    """Run PT501–PT505 over already-built ClassModels.  `suppressions`
    maps file path -> Suppressions; `trees` maps file path -> ast tree
    (for the PT505 while-shape check)."""
    out: list = []
    for cls in models:
        tm.apply_presumed_locks(cls, suppressions.get(cls.file))
    for cls in models:
        sup = suppressions.get(cls.file)
        _audit_pt501(cls, out)
        _audit_pt503_pt504(cls, sup or _EMPTY_SUP, out)
        _audit_pt505(cls, (trees or {}).get(cls.file), out)
    _audit_pt502(models, out)
    filtered = []
    for v in out:
        sup = suppressions.get(v.file)
        if sup is not None and sup.suppressed(v.line, v.rule):
            continue
        filtered.append(v)
    filtered.sort(key=Violation.sort_key)
    return filtered


class _NoSuppressions:
    @staticmethod
    def suppressed(line, rule):
        return False

    @staticmethod
    def listed_rules(line):
        return set()

    @staticmethod
    def guard_claims(line):
        return set()


_EMPTY_SUP = _NoSuppressions()


def analyze_source(source: str, path: str,
                   tree: ast.Module | None = None) -> list:
    """Single-file audit (tests and the one-file CLI path): the whole
    program IS this file."""
    if tree is None:
        tree = ast.parse(source)
    fm = tm.build_file_model(source, path, tree=tree)
    sup = Suppressions(source, tree)
    return audit_classes(fm.classes, {path: sup}, {path: tree})


def analyze_files(file_items) -> list:
    """Audit a set of (abs_path, rel_path) files as ONE program —
    cross-class PT502 edges resolve across file boundaries."""
    models, sups, trees = [], {}, {}
    out: list = []
    for abs_path, rel in file_items:
        try:
            with open(abs_path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source)
        except (OSError, SyntaxError):
            continue  # the runner's PT000 covers unparsable files
        fm = tm.build_file_model(source, rel, tree=tree)
        models.extend(fm.classes)
        sups[rel] = Suppressions(source, tree)
        trees[rel] = tree
    out.extend(audit_classes(models, sups, trees))
    return out


def analyze_project(repo_root: str, roots=CONC_ROOTS) -> list:
    """The default whole-program pass: every .py under the production
    roots (tests excluded — fixture threads race on purpose)."""
    from .runner import iter_python_files

    wanted = []
    for rel in iter_python_files(repo_root, roots=roots):
        wanted.append((os.path.join(repo_root, rel), rel))
    return analyze_files(wanted)
