"""Framework-aware static analysis for the TPU build.

Five layers, one report format (``file:line RULE message``):

  * :mod:`.trace_safety` — AST trace-safety lint (PT001–PT007): tracer
    leaks, concretization under jit, PRNG key reuse, bad static args,
    silent exception swallows, mutable defaults, unmarked slow tests.
  * :mod:`.lock_check` — lock-discipline race checker (PT101/PT102):
    attributes written under ``with self._lock:`` must not be touched
    outside it.  Consumes the guard map :mod:`.threadmodel` infers.
  * :mod:`.hlo_audit` — jaxpr/StableHLO audit (PT201–PT203): host
    transfers, silent f64 promotion, un-donated train-step buffers.

  * :mod:`.perf_audit` — static performance auditor (PT400–PT405):
    layout-tax transposes, recompile hazards, replicated big buffers,
    collective anti-patterns, hot-loop host syncs — quantified per
    representative program and held to committed per-model budgets
    (``tools/perf_budget.json``).
  * :mod:`.concurrency_audit` — whole-program concurrency auditor
    (PT501–PT505) over :mod:`.threadmodel`'s inferred thread roots and
    lock models: blocking calls under locks, lock-order inversions,
    unguarded cross-thread state, guard drift (including annotations
    that contradict inference), condition-variable misuse.

Plus :mod:`.manifest_check` (PT301): OPS_MANIFEST.json claims vs the
live module surface.

CLI: ``python tools/pt_lint.py`` (``--check`` gates against
``tools/lint_baseline.json``; ``--update-baseline`` refreshes it).
Docs: ``docs/STATIC_ANALYSIS.md`` (rule catalog, suppression syntax).

This package's fast path is stdlib-only by design: importing
``paddle_tpu.analysis`` and running the ast/lock layers must never pay
a jax import (the CLI runs pre-commit; the repo gate runs in tier-1).
"""
from .report import (  # noqa: F401
    Suppressions, Violation, baseline_counts, diff_against_baseline,
    diff_against_budget, load_baseline, load_budget,
    render_budget_diff, render_report, save_baseline, save_budget,
)
from .runner import (  # noqa: F401
    DEFAULT_ROOTS, analyze_one_file, analyze_repo, iter_python_files,
)

__all__ = [
    "Violation", "Suppressions", "load_baseline", "save_baseline",
    "baseline_counts", "diff_against_baseline", "render_report",
    "save_budget", "load_budget", "diff_against_budget",
    "render_budget_diff",
    "analyze_repo", "analyze_one_file", "iter_python_files",
    "DEFAULT_ROOTS",
]
