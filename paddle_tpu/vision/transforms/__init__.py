"""Vision transforms (parity subset of `python/paddle/vision/transforms/`),
numpy-based (HWC uint8/float inputs)."""
from __future__ import annotations

import numbers

import numpy as np

from ...core.tensor import Tensor

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "to_tensor", "normalize", "resize", "hflip", "vflip",
]


def _np_img(img):
    if isinstance(img, Tensor):
        return np.asarray(img._value)
    return np.asarray(img)


def to_tensor(img, data_format="CHW"):
    arr = _np_img(img)
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr.astype(np.float32))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _np_img(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor(out) if isinstance(img, Tensor) else out


def resize(img, size, interpolation="bilinear"):
    arr = _np_img(img)
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    import jax
    import jax.numpy as jnp

    method = {"bilinear": "linear", "nearest": "nearest",
              "bicubic": "cubic"}.get(interpolation, "linear")
    tgt = (size[0], size[1]) + arr.shape[2:]
    out = jax.image.resize(jnp.asarray(arr, jnp.float32), tgt, method=method)
    return np.asarray(out).astype(arr.dtype)


def hflip(img):
    return _np_img(img)[:, ::-1].copy()


def vflip(img):
    return _np_img(img)[::-1].copy()


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else size

    def __call__(self, img):
        arr = _np_img(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return arr[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=None, keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def __call__(self, img):
        arr = _np_img(img)
        if self.padding:
            p = self.padding
            arr = np.pad(arr, ((p, p), (p, p)) + ((0, 0),) * (arr.ndim - 2))
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, max(1, h - th + 1))
        j = np.random.randint(0, max(1, w - tw + 1))
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.random() < self.prob:
            return hflip(img)
        return _np_img(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.random() < self.prob:
            return vflip(img)
        return _np_img(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = _np_img(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        arr = _np_img(img)
        p = self.padding
        if isinstance(p, int):
            cfg = ((p, p), (p, p))
        elif len(p) == 2:
            cfg = ((p[1], p[1]), (p[0], p[0]))
        else:
            cfg = ((p[1], p[3]), (p[0], p[2]))
        cfg = cfg + ((0, 0),) * (arr.ndim - 2)
        if self.mode == "constant":
            return np.pad(arr, cfg, constant_values=self.fill)
        return np.pad(arr, cfg, mode=self.mode)


# ---- reference __all__ completion (vision/transforms/__init__.py) ----

def crop(img, top, left, height, width):
    arr = _np_img(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _np_img(img)
    th, tw = ((output_size, output_size)
              if isinstance(output_size, numbers.Number) else output_size)
    h, w = arr.shape[:2]
    return crop(arr, max(0, (h - th) // 2), max(0, (w - tw) // 2), th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)(img)


def to_grayscale(img, num_output_channels=1):
    arr = _np_img(img).astype(np.float32)
    if arr.ndim == 2:
        g = arr
    else:
        g = 0.299 * arr[..., 0] + 0.587 * arr[..., 1] + 0.114 * arr[..., 2]
    out = np.stack([g] * num_output_channels, axis=-1) \
        if num_output_channels > 1 else g[..., None]
    return out.astype(_np_img(img).dtype)


def adjust_brightness(img, brightness_factor):
    arr = _np_img(img)
    hi = 255 if arr.dtype == np.uint8 else 1.0
    out = np.clip(arr.astype(np.float32) * brightness_factor, 0, hi)
    return out.astype(arr.dtype)


def adjust_contrast(img, contrast_factor):
    arr = _np_img(img)
    hi = 255 if arr.dtype == np.uint8 else 1.0
    mean = to_grayscale(arr).astype(np.float32).mean()
    out = np.clip((arr.astype(np.float32) - mean) * contrast_factor + mean,
                  0, hi)
    return out.astype(arr.dtype)


def adjust_saturation(img, saturation_factor):
    arr = _np_img(img)
    hi = 255 if arr.dtype == np.uint8 else 1.0
    gray = to_grayscale(arr).astype(np.float32)
    out = np.clip(arr.astype(np.float32) * saturation_factor +
                  gray * (1 - saturation_factor), 0, hi)
    return out.astype(arr.dtype)


def _rgb_to_hsv(rgb):
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = np.max(rgb, axis=-1)
    minc = np.min(rgb, axis=-1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    with np.errstate(invalid="ignore", divide="ignore"):
        rc = (maxc - r) / np.maximum(delta, 1e-12)
        gc = (maxc - g) / np.maximum(delta, 1e-12)
        bc = (maxc - b) / np.maximum(delta, 1e-12)
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(delta == 0, 0.0, h / 6.0 % 1.0)
    return np.stack([h, s, v], axis=-1)


def _hsv_to_rgb(hsv):
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = (i.astype(np.int32) % 6)[..., None]
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return out


def adjust_hue(img, hue_factor):
    assert -0.5 <= hue_factor <= 0.5, "hue_factor in [-0.5, 0.5]"
    arr = _np_img(img)
    scale = 255.0 if arr.dtype == np.uint8 else 1.0
    hsv = _rgb_to_hsv(arr.astype(np.float32) / scale)
    hsv[..., 0] = (hsv[..., 0] + hue_factor) % 1.0
    out = _hsv_to_rgb(hsv) * scale
    return np.clip(out, 0, scale if arr.dtype == np.uint8 else 1.0) \
        .astype(arr.dtype)


def _affine_sample(arr, matrix, out_hw=None, interpolation="nearest",
                   fill=0):
    """Inverse-warp sampling: out(y, x) = in(M @ (x, y, 1))."""
    h, w = arr.shape[:2]
    oh, ow = out_hw or (h, w)
    ys, xs = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], axis=0).reshape(3, -1)
    m = np.asarray(matrix, np.float64).reshape(3, 3)
    src = m @ coords
    sx = src[0] / np.maximum(src[2], 1e-12)
    sy = src[1] / np.maximum(src[2], 1e-12)
    if interpolation == "bilinear":
        x0 = np.floor(sx).astype(int)
        y0 = np.floor(sy).astype(int)
        dx = (sx - x0).reshape(oh, ow, *([1] * (arr.ndim - 2)))
        dy = (sy - y0).reshape(oh, ow, *([1] * (arr.ndim - 2)))

        def at(yy, xx):
            valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            vals = arr[np.clip(yy, 0, h - 1), np.clip(xx, 0, w - 1)]
            vshape = valid.reshape(oh, ow, *([1] * (arr.ndim - 2)))
            return np.where(vshape, vals.reshape(oh, ow, *arr.shape[2:]),
                            fill).astype(np.float32)

        out = (at(y0, x0) * (1 - dx) * (1 - dy)
               + at(y0, x0 + 1) * dx * (1 - dy)
               + at(y0 + 1, x0) * (1 - dx) * dy
               + at(y0 + 1, x0 + 1) * dx * dy)
    else:
        xi = np.round(sx).astype(int)
        yi = np.round(sy).astype(int)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        vals = arr[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)]
        out = np.where(valid.reshape(oh, ow, *([1] * (arr.ndim - 2))),
                       vals.reshape(oh, ow, *arr.shape[2:]), fill)
    return out.astype(arr.dtype)


def _affine_matrix(angle, translate, scale, shear, center):
    a = np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in shear)
    cx, cy = center
    tx, ty = translate
    # forward matrix: T(center) R S Sh T(-center) + translate
    rot = np.array([
        [np.cos(a + sy) / np.cos(sy),
         -np.cos(a + sy) * np.tan(sx) / np.cos(sy) - np.sin(a), 0],
        [np.sin(a + sy) / np.cos(sy),
         -np.sin(a + sy) * np.tan(sx) / np.cos(sy) + np.cos(a), 0],
        [0, 0, 1]], np.float64)
    rot[:2, :2] *= scale
    t1 = np.array([[1, 0, cx + tx], [0, 1, cy + ty], [0, 0, 1]], np.float64)
    t2 = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]], np.float64)
    fwd = t1 @ rot @ t2
    return np.linalg.inv(fwd)


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", fill=0, center=None):
    arr = _np_img(img)
    h, w = arr.shape[:2]
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    center = center or ((w - 1) / 2.0, (h - 1) / 2.0)
    m = _affine_matrix(angle, translate, scale, shear, center)
    return _affine_sample(arr, m, interpolation=interpolation, fill=fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    return affine(img, angle=angle, interpolation=interpolation,
                  fill=fill, center=center)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Projective warp mapping startpoints -> endpoints (4 corners)."""
    arr = _np_img(img)
    a = []
    bvec = []
    for (x, y), (u, v) in zip(endpoints, startpoints):
        a.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        bvec.append(u)
        a.append([0, 0, 0, x, y, 1, -v * x, -v * y])
        bvec.append(v)
    coeffs = np.linalg.solve(np.asarray(a, np.float64),
                             np.asarray(bvec, np.float64))
    m = np.append(coeffs, 1.0).reshape(3, 3)
    return _affine_sample(arr, m, interpolation=interpolation, fill=fill)


def erase(img, i, j, h, w, v, inplace=False):
    arr = _np_img(img)
    out = arr if inplace else arr.copy()
    out[i:i + h, j:j + w] = v
    return out


class BaseTransform:
    """Keyed-transform base (reference BaseTransform): subclasses
    implement _apply_image (and friends); __call__ dispatches on keys."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _apply_image(self, img):
        return img

    def __call__(self, inputs):
        if not isinstance(inputs, (list, tuple)):
            return self._apply_image(inputs)
        outs = []
        for key, data in zip(self.keys, inputs):
            fn = getattr(self, f"_apply_{key}", None)
            outs.append(fn(data) if fn else data)
        return tuple(outs)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.n = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.n)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _np_img(img)
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _np_img(img)
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _np_img(img)
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = min(value, 0.5)

    def _apply_image(self, img):
        if self.value == 0:
            return _np_img(img)
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        for t in np.random.permutation(self.ts):
            img = t._apply_image(img)
        return img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _np_img(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                return resize(arr[i:i + ch, j:j + cw], self.size,
                              self.interpolation)
        return resize(center_crop(arr, (min(h, w), min(h, w))), self.size,
                      self.interpolation)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) \
            if isinstance(degrees, numbers.Number) else degrees
        self.interpolation = interpolation
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, center=self.center,
                      fill=self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) \
            if isinstance(degrees, numbers.Number) else degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr = _np_img(img)
        h, w = arr.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0
        if self.translate:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        sc = np.random.uniform(*self.scale) if self.scale else 1.0
        sh = (np.random.uniform(-self.shear, self.shear), 0.0) \
            if isinstance(self.shear, numbers.Number) else (0.0, 0.0)
        return affine(arr, angle, (tx, ty), sc, sh, self.interpolation,
                      self.fill, self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        arr = _np_img(img)
        if np.random.random() >= self.prob:
            return arr
        h, w = arr.shape[:2]
        dx = int(self.scale * w / 2)
        dy = int(self.scale * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(np.random.randint(0, dx + 1), np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1),
                np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1),
                h - 1 - np.random.randint(0, dy + 1)),
               (np.random.randint(0, dx + 1),
                h - 1 - np.random.randint(0, dy + 1))]
        return perspective(arr, start, end, self.interpolation, self.fill)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        arr = _np_img(img)
        if np.random.random() >= self.prob:
            return arr
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                return erase(arr, i, j, eh, ew, self.value)
        return arr


__all__ += [
    "BaseTransform", "RandomResizedCrop", "BrightnessTransform",
    "SaturationTransform", "ContrastTransform", "HueTransform",
    "ColorJitter", "RandomAffine", "RandomRotation", "RandomPerspective",
    "Grayscale", "RandomErasing", "pad", "affine", "rotate", "perspective",
    "to_grayscale", "crop", "center_crop", "adjust_brightness",
    "adjust_contrast", "adjust_saturation", "adjust_hue", "erase",
]
