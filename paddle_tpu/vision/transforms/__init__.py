"""Vision transforms (parity subset of `python/paddle/vision/transforms/`),
numpy-based (HWC uint8/float inputs)."""
from __future__ import annotations

import numbers

import numpy as np

from ...core.tensor import Tensor

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "to_tensor", "normalize", "resize", "hflip", "vflip",
]


def _np_img(img):
    if isinstance(img, Tensor):
        return np.asarray(img._value)
    return np.asarray(img)


def to_tensor(img, data_format="CHW"):
    arr = _np_img(img)
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr.astype(np.float32))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _np_img(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor(out) if isinstance(img, Tensor) else out


def resize(img, size, interpolation="bilinear"):
    arr = _np_img(img)
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    import jax
    import jax.numpy as jnp

    method = {"bilinear": "linear", "nearest": "nearest",
              "bicubic": "cubic"}.get(interpolation, "linear")
    tgt = (size[0], size[1]) + arr.shape[2:]
    out = jax.image.resize(jnp.asarray(arr, jnp.float32), tgt, method=method)
    return np.asarray(out).astype(arr.dtype)


def hflip(img):
    return _np_img(img)[:, ::-1].copy()


def vflip(img):
    return _np_img(img)[::-1].copy()


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else size

    def __call__(self, img):
        arr = _np_img(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return arr[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=None, keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def __call__(self, img):
        arr = _np_img(img)
        if self.padding:
            p = self.padding
            arr = np.pad(arr, ((p, p), (p, p)) + ((0, 0),) * (arr.ndim - 2))
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, max(1, h - th + 1))
        j = np.random.randint(0, max(1, w - tw + 1))
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.random() < self.prob:
            return hflip(img)
        return _np_img(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.random() < self.prob:
            return vflip(img)
        return _np_img(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = _np_img(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        arr = _np_img(img)
        p = self.padding
        if isinstance(p, int):
            cfg = ((p, p), (p, p))
        elif len(p) == 2:
            cfg = ((p[1], p[1]), (p[0], p[0]))
        else:
            cfg = ((p[1], p[3]), (p[0], p[2]))
        cfg = cfg + ((0, 0),) * (arr.ndim - 2)
        if self.mode == "constant":
            return np.pad(arr, cfg, constant_values=self.fill)
        return np.pad(arr, cfg, mode=self.mode)
