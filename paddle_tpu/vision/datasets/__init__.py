"""Dataset zoo (parity subset: `python/paddle/vision/datasets/`). Zero-egress
environment: loaders read local files when present; `FakeData` provides a
synthetic stand-in for smoke tests and benchmarks."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


class FakeData(Dataset):
    """Synthetic image-classification data (deterministic per index)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = np.int64(rng.randint(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class MNIST(Dataset):
    """Reads the standard IDX files from `image_path`/`label_path` if given;
    otherwise falls back to deterministic synthetic digits (no network)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        self.transform = transform
        self.mode = mode
        if image_path and label_path and os.path.exists(image_path):
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)
        else:
            n = 60000 if mode == "train" else 10000
            n = min(n, 2048)  # synthetic fallback kept small
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            self.images = (rng.rand(n, 28, 28) * 255).astype(np.uint8)

    @staticmethod
    def _read_images(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            _, num, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), np.uint8)
        return data.reshape(num, rows, cols)

    @staticmethod
    def _read_labels(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            _, num = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None] / 255.0
        return img, self.labels[idx]


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    """Reads the official cifar-python tar.gz when `data_file` is given
    (pickled batches, images [N,3072] uint8, reference
    `vision/datasets/cifar.py`); falls back to deterministic synthetic
    data with NO archive — the MNIST-style CI contract."""

    N_CLASSES = 10
    _MEMBERS = {"train": ("data_batch",), "test": ("test_batch",)}
    _LABEL_KEY = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        assert mode.lower() in ("train", "test"), mode
        self.transform = transform
        if data_file:
            self._load_archive(data_file, mode.lower())
            return
        n = min(50000 if mode == "train" else 10000, 2048)
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.labels = rng.randint(0, self.N_CLASSES, n).astype(np.int64)
        self.images = (rng.rand(n, 3, 32, 32) * 255).astype(np.uint8)

    def _load_archive(self, data_file, mode):
        import pickle
        import tarfile

        wanted = self._MEMBERS[mode]
        images, labels = [], []
        with tarfile.open(data_file) as tf:
            for member in sorted(tf.getnames()):
                base = member.rsplit("/", 1)[-1]
                if not any(base.startswith(w) for w in wanted):
                    continue
                batch = pickle.load(tf.extractfile(member),
                                    encoding="bytes")
                images.append(np.asarray(batch[b"data"], np.uint8))
                labels.extend(batch[self._LABEL_KEY])
        if not images:
            raise RuntimeError(
                f"{type(self).__name__}: no {wanted} members in "
                f"{data_file} — wrong archive?")
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        else:
            img = img.astype(np.float32) / 255.0
        return img, self.labels[idx]


class Cifar10(_CifarBase):
    N_CLASSES = 10


class Cifar100(_CifarBase):
    N_CLASSES = 100
    _MEMBERS = {"train": ("train",), "test": ("test",)}
    _LABEL_KEY = b"fine_labels"


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm",
                  ".tif", ".tiff", ".webp")


class DatasetFolder(Dataset):
    """class-per-subdirectory image dataset (paddle.vision.datasets.
    DatasetFolder): root/class_x/xxx.png → (sample, class_index)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._pil_loader
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for base, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(base, fname)
                    ok = (is_valid_file(path) if is_valid_file
                          else fname.lower().endswith(tuple(extensions)))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    @staticmethod
    def _pil_loader(path):
        from PIL import Image

        with open(path, "rb") as f:
            return np.asarray(Image.open(f).convert("RGB"))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """flat/recursive image folder without labels (paddle.vision.
    datasets.ImageFolder): returns [sample] lists like the reference."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._pil_loader
        extensions = extensions or IMG_EXTENSIONS
        self.samples = []
        for base, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(base, fname)
                ok = (is_valid_file(path) if is_valid_file
                      else fname.lower().endswith(tuple(extensions)))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


# official readme quirk kept by the reference (flowers.py:38): tstid is
# the LARGER split and serves as training data
_FLOWERS_MODE_FLAG = {"train": "tstid", "test": "trnid", "valid": "valid"}


class Flowers(Dataset):
    """Flowers-102 (paddle.vision.datasets.Flowers): images from the
    102flowers.tgz, labels from imagelabels.mat, splits from setid.mat
    (reference `vision/datasets/flowers.py`). Zero-egress build: pass
    the local archives; there is no auto-download."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None):
        assert mode.lower() in ("train", "valid", "test"), mode
        if not (data_file and label_file and setid_file):
            raise RuntimeError(
                "no network egress: place the Flowers-102 archives "
                "locally and pass data_file/label_file/setid_file")
        import tarfile
        import threading

        from scipy.io import loadmat

        self.data_file = data_file
        self.transform = transform
        # 1-based image ids for this split; labels stay 1-based 1..102
        # (reference vision/datasets/flowers.py:172 returns them raw)
        self.indexes = loadmat(setid_file)[
            _FLOWERS_MODE_FLAG[mode.lower()]].ravel().astype(int)
        self.labels = loadmat(label_file)["labels"].ravel().astype(
            np.int64)
        # one persistent handle per process, opened lazily: the .tgz has
        # no random access, so per-item reopen would re-decompress the
        # whole archive per fetch (O(N^2) per epoch); tarfile isn't
        # thread-safe -> lock. Lazy + excluded from pickling keeps the
        # dataset fork/worker-safe (each process opens its own handle).
        self._tar = None
        self._tar_lock = threading.Lock()

    def _handle(self):
        import tarfile

        if self._tar is None:
            self._tar = tarfile.open(self.data_file)
        return self._tar

    def close(self):
        # under the lock: close() racing a __getitem__ on another
        # worker thread must not yank the handle mid-extract
        with self._tar_lock:
            if self._tar is not None:
                self._tar.close()
                self._tar = None

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_tar"] = None
        state["_tar_lock"] = None
        return state

    def __setstate__(self, state):
        import threading

        self.__dict__.update(state)
        self._tar_lock = threading.Lock()

    def __getitem__(self, idx):
        from PIL import Image

        img_id = int(self.indexes[idx])
        with self._tar_lock:
            f = self._handle().extractfile(f"jpg/image_{img_id:05d}.jpg")
            img = np.asarray(Image.open(f).convert("RGB"))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[img_id - 1]])

    def __len__(self):
        return len(self.indexes)


# reference quirk (vision/datasets/voc2012.py:36): 'train' serves the
# full trainval list, 'test' the train list, 'valid' the val list
_VOC_MODE_FLAG = {"train": "trainval", "test": "train", "valid": "val"}
_VOC_SET = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
_VOC_DATA = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
_VOC_LABEL = "VOCdevkit/VOC2012/SegmentationClass/{}.png"


class VOC2012(Dataset):
    """VOC2012 segmentation (paddle.vision.datasets.VOC2012): items are
    (image HWC uint8, label HW uint8) pairs for the segmentation split
    lists; local `data_file` tar required (zero egress)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        assert mode.lower() in ("train", "valid", "test"), mode
        if not data_file:
            raise RuntimeError(
                "no network egress: pass the local VOCtrainval tar as "
                "data_file")
        import tarfile

        self.data_file = data_file
        self.transform = transform
        set_name = _VOC_SET.format(_VOC_MODE_FLAG[mode.lower()])
        with tarfile.open(data_file) as tf:
            lines = tf.extractfile(set_name).read().decode().split()
        self._ids = [l.strip() for l in lines if l.strip()]

    def __getitem__(self, idx):
        import tarfile

        from PIL import Image

        name = self._ids[idx]
        with tarfile.open(self.data_file) as tf:
            img = np.asarray(Image.open(
                tf.extractfile(_VOC_DATA.format(name))).convert("RGB"))
            label = np.asarray(Image.open(
                tf.extractfile(_VOC_LABEL.format(name))))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self._ids)
