"""Dataset zoo (parity subset: `python/paddle/vision/datasets/`). Zero-egress
environment: loaders read local files when present; `FakeData` provides a
synthetic stand-in for smoke tests and benchmarks."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


class FakeData(Dataset):
    """Synthetic image-classification data (deterministic per index)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = np.int64(rng.randint(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class MNIST(Dataset):
    """Reads the standard IDX files from `image_path`/`label_path` if given;
    otherwise falls back to deterministic synthetic digits (no network)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        self.transform = transform
        self.mode = mode
        if image_path and label_path and os.path.exists(image_path):
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)
        else:
            n = 60000 if mode == "train" else 10000
            n = min(n, 2048)  # synthetic fallback kept small
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            self.images = (rng.rand(n, 28, 28) * 255).astype(np.uint8)

    @staticmethod
    def _read_images(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            _, num, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), np.uint8)
        return data.reshape(num, rows, cols)

    @staticmethod
    def _read_labels(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            _, num = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None] / 255.0
        return img, self.labels[idx]


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    N_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.transform = transform
        n = min(50000 if mode == "train" else 10000, 2048)
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.labels = rng.randint(0, self.N_CLASSES, n).astype(np.int64)
        self.images = (rng.rand(n, 3, 32, 32) * 255).astype(np.uint8)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        else:
            img = img.astype(np.float32) / 255.0
        return img, self.labels[idx]


class Cifar10(_CifarBase):
    N_CLASSES = 10


class Cifar100(_CifarBase):
    N_CLASSES = 100
