"""paddle_tpu.vision.ops: detection operators.

Role parity: `python/paddle/vision/ops.py` (+ reference detection kernels
`paddle/fluid/operators/detection/`, SURVEY §2.8) — nms, roi_align,
box_iou, deform_conv2d and the layer wrappers.

TPU-first: roi_align is fully vectorized bilinear gather (no per-ROI host
loop — one gather over [num_rois, ph, pw, samples] index tensors that XLA
batches); nms keeps the O(n²) IoU matrix formulation with a `lax`-friendly
greedy scan (fixed shapes, masks instead of dynamic lists).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["box_iou", "nms", "roi_align", "RoIAlign", "deform_conv2d",
           "DeformConv2D"]


def _box_iou_raw(a, b):
    """a: [N,4], b: [M,4] in x1,y1,x2,y2 → [N,M] IoU."""
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-9)


def box_iou(boxes1, boxes2, name=None):
    return apply("box_iou", _box_iou_raw,
                 boxes1 if isinstance(boxes1, Tensor) else Tensor(boxes1),
                 boxes2 if isinstance(boxes2, Tensor) else Tensor(boxes2))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy NMS. Returns kept indices sorted by descending score
    (parity: paddle.vision.ops.nms; reference CUDA kernel
    `paddle/fluid/operators/detection/nms_op.cu`)."""
    bt = boxes if isinstance(boxes, Tensor) else Tensor(boxes)
    n = bt.shape[0]
    if scores is None:
        scores_v = jnp.arange(n, 0, -1, dtype=jnp.float32)
    else:
        scores_v = (scores._value if isinstance(scores, Tensor)
                    else jnp.asarray(scores))

    def f(b, s):
        order = jnp.argsort(-s)
        b_sorted = b[order]
        iou = _box_iou_raw(b_sorted, b_sorted)
        if category_idxs is not None:
            cat = (category_idxs._value
                   if isinstance(category_idxs, Tensor)
                   else jnp.asarray(category_idxs))[order]
            same = cat[:, None] == cat[None, :]
            iou = jnp.where(same, iou, 0.0)  # class-aware NMS

        def body(i, keep):
            # drop i if any higher-scoring kept box overlaps it
            sup = jnp.sum(jnp.where(jnp.arange(n) < i,
                                    (iou[:, i] > iou_threshold) & keep,
                                    False))
            return keep.at[i].set(sup == 0)

        keep = jax.lax.fori_loop(0, n, body,
                                 jnp.ones((n,), bool))
        return order, keep

    order_t, keep_t = apply("nms", f, bt, Tensor(scores_v))
    order = np.asarray(order_t.numpy())
    keep = np.asarray(keep_t.numpy(), bool)
    kept = order[keep]
    if top_k is not None:
        if category_idxs is not None:
            # paddle contract: top_k applies PER category, then merge in
            # global score order
            cats = np.asarray(
                category_idxs._value if isinstance(category_idxs, Tensor)
                else category_idxs)
            sel = []
            for c in (categories if categories is not None
                      else np.unique(cats)):
                cat_kept = kept[cats[kept] == c][:top_k]
                sel.append(cat_kept)
            kept = np.concatenate(sel) if sel else kept[:0]
            sc = np.asarray(scores_v)
            kept = kept[np.argsort(-sc[kept], kind="stable")]
        else:
            kept = kept[:top_k]
    return Tensor(kept.astype(np.int64))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """ROI Align (parity: paddle.vision.ops.roi_align; reference kernel
    `paddle/phi/kernels/gpu/roi_align_kernel.cu`).

    x: [N, C, H, W]; boxes: [R, 4] per-image concatenated; boxes_num: [N].
    """
    if isinstance(output_size, int):
        ph = pw = output_size
    else:
        ph, pw = output_size
    bn = np.asarray(boxes_num._value if isinstance(boxes_num, Tensor)
                    else boxes_num).astype(np.int64)
    batch_of_roi = np.repeat(np.arange(len(bn)), bn)
    if sampling_ratio > 0:
        ratio = sampling_ratio
    else:
        # reference: adaptive ceil(bin_size) per ROI. Per-ROI counts are
        # dynamic shapes — hostile to XLA — so use ONE grid sized for the
        # largest ROI (≥ reference's sample count for every smaller ROI)
        try:
            b_np = np.asarray(boxes._value if isinstance(boxes, Tensor)
                              else boxes)
            max_bin = max(
                float((b_np[:, 2] - b_np[:, 0]).max()) * spatial_scale / pw,
                float((b_np[:, 3] - b_np[:, 1]).max()) * spatial_scale / ph)
            ratio = max(1, int(np.ceil(max_bin)))
        except Exception:  # traced boxes: fixed default
            ratio = 2

    def f(feat, bxs):
        N, C, H, W = feat.shape
        R = bxs.shape[0]
        offset = 0.5 if aligned else 0.0
        x1 = bxs[:, 0] * spatial_scale - offset
        y1 = bxs[:, 1] * spatial_scale - offset
        x2 = bxs[:, 2] * spatial_scale - offset
        y2 = bxs[:, 3] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: [R, ph*ratio] y coords, [R, pw*ratio] x coords
        iy = (jnp.arange(ph * ratio) + 0.5) / ratio
        ix = (jnp.arange(pw * ratio) + 0.5) / ratio
        ys = y1[:, None] + bin_h[:, None] * iy[None, :]   # [R, ph*r]
        xs = x1[:, None] + bin_w[:, None] * ix[None, :]   # [R, pw*r]

        def bilinear(fm, yy, xx):
            # fm: [C, H, W]; yy: [ph*r], xx: [pw*r] → [C, ph*r, pw*r];
            # reference semantics: samples with y < -1 or y > H (x alike)
            # contribute 0; in-range samples clamp to the border pixel
            valid_y = (yy >= -1.0) & (yy <= H)
            valid_x = (xx >= -1.0) & (xx <= W)
            yy = jnp.clip(yy, 0, H - 1)
            xx = jnp.clip(xx, 0, W - 1)
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)
            wy1 = jnp.clip(yy - y0, 0, 1)
            wx1 = jnp.clip(xx - x0, 0, 1)
            wy0, wx0 = 1 - wy1, 1 - wx1
            v00 = fm[:, y0i][:, :, x0i]
            v01 = fm[:, y0i][:, :, x1i]
            v10 = fm[:, y1i][:, :, x0i]
            v11 = fm[:, y1i][:, :, x1i]
            out = (v00 * (wy0[:, None] * wx0[None, :])
                   + v01 * (wy0[:, None] * wx1[None, :])
                   + v10 * (wy1[:, None] * wx0[None, :])
                   + v11 * (wy1[:, None] * wx1[None, :]))
            return out * (valid_y[:, None] & valid_x[None, :])[None]

        def per_roi(bi, yy, xx):
            fm = feat[bi]
            vals = bilinear(fm, yy, xx)           # [C, ph*r, pw*r]
            vals = vals.reshape(C, ph, ratio, pw, ratio)
            return vals.mean(axis=(2, 4))         # [C, ph, pw]

        return jax.vmap(per_roi)(jnp.asarray(batch_of_roi), ys, xs)

    return apply("roi_align", f,
                 x if isinstance(x, Tensor) else Tensor(x),
                 boxes if isinstance(boxes, Tensor) else Tensor(boxes))


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (parity: paddle.vision.ops.deform_conv2d;
    reference `paddle/phi/kernels/gpu/deformable_conv_kernel.cu`).

    x: [N,Cin,H,W]; offset: [N, 2*dg*kh*kw, Ho, Wo];
    mask (v2): [N, dg*kh*kw, Ho, Wo]; weight: [Cout, Cin/g, kh, kw].
    Implemented as bilinear-sampled im2col (one big gather) + matmul —
    the gather/matmul split maps to TPU better than a custom kernel.
    """
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph_, pw_ = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation

    def f(xv, off, w, b, m):
        N, Cin, H, W = xv.shape
        Cout, Cin_g, kh, kw = w.shape
        Ho = (H + 2 * ph_ - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pw_ - dw * (kw - 1) - 1) // sw + 1
        xp = jnp.pad(xv, ((0, 0), (0, 0), (ph_, ph_), (pw_, pw_)))
        Hp, Wp = H + 2 * ph_, W + 2 * pw_
        # base sampling locations [kh*kw, Ho, Wo]
        base_y = (jnp.arange(Ho) * sh)[None, :, None] \
            + (jnp.arange(kh) * dh)[:, None, None]
        base_x = (jnp.arange(Wo) * sw)[None, None, :] \
            + (jnp.arange(kw) * dw)[:, None, None]
        base_y = jnp.broadcast_to(base_y[:, None, :, :],
                                  (kh, kw, Ho, Wo)).reshape(kh * kw, Ho, Wo)
        base_x = jnp.broadcast_to(base_x[None, :, :, :],
                                  (kh, kw, Ho, Wo)).reshape(kh * kw, Ho, Wo)
        off = off.reshape(N, deformable_groups, kh * kw, 2, Ho, Wo)
        # paddle offset layout: (dy, dx) interleaved per kernel point
        oy = off[:, :, :, 0]
        ox = off[:, :, :, 1]
        sy = base_y[None, None] + oy          # [N, dg, khkw, Ho, Wo]
        sx = base_x[None, None] + ox
        if m is None:
            mval = jnp.ones((N, deformable_groups, kh * kw, Ho, Wo),
                            xv.dtype)
        else:
            mval = m.reshape(N, deformable_groups, kh * kw, Ho, Wo)

        cpg = Cin // deformable_groups  # channels per deformable group

        def sample(img, yy, xx):
            # img: [cpg, Hp, Wp]; yy/xx: [khkw, Ho, Wo]
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            wy1 = yy - y0
            wx1 = xx - x0

            def gather(yi, xi):
                yi_c = jnp.clip(yi.astype(jnp.int32), 0, Hp - 1)
                xi_c = jnp.clip(xi.astype(jnp.int32), 0, Wp - 1)
                valid = ((yi >= 0) & (yi <= Hp - 1)
                         & (xi >= 0) & (xi <= Wp - 1))
                return img[:, yi_c, xi_c] * valid[None]

            v = (gather(y0, x0) * ((1 - wy1) * (1 - wx1))[None]
                 + gather(y0, x0 + 1) * ((1 - wy1) * wx1)[None]
                 + gather(y0 + 1, x0) * (wy1 * (1 - wx1))[None]
                 + gather(y0 + 1, x0 + 1) * (wy1 * wx1)[None])
            return v  # [cpg, khkw, Ho, Wo]

        def per_image(img, yy, xx, mm):
            # img: [Cin, Hp, Wp] grouped by dg
            img_g = img.reshape(deformable_groups, cpg, Hp, Wp)
            cols = jax.vmap(sample)(img_g, yy, xx)  # [dg, cpg, khkw, Ho, Wo]
            cols = cols * mm[:, None]
            return cols.reshape(Cin, kh * kw, Ho, Wo)

        cols = jax.vmap(per_image)(xp, sy, sx, mval)  # [N,Cin,khkw,Ho,Wo]
        # grouped matmul: weight [Cout, Cin/g, kh*kw]
        wg = w.reshape(groups, Cout // groups, Cin_g * kh * kw)
        cols_g = cols.reshape(N, groups, Cin_g * kh * kw, Ho * Wo)
        out = jnp.einsum("gok,ngkp->ngop", wg, cols_g)
        out = out.reshape(N, Cout, Ho, Wo)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out

    return apply("deform_conv2d", f,
                 x if isinstance(x, Tensor) else Tensor(x),
                 offset if isinstance(offset, Tensor) else Tensor(offset),
                 weight if isinstance(weight, Tensor) else Tensor(weight),
                 bias, mask)


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.deformable_groups = deformable_groups
        self.groups = groups
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, *ks])
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self.stride,
                             self.padding, self.dilation,
                             self.deformable_groups, self.groups, mask)


from .ops_detection import (  # noqa: F401,E402
    box_coder, distribute_fpn_proposals, generate_proposals, matrix_nms,
    multiclass_nms, prior_box, psroi_pool, roi_pool, yolo_box, yolo_loss,
)

__all__ += [
    "box_coder", "distribute_fpn_proposals", "generate_proposals",
    "matrix_nms", "multiclass_nms", "prior_box", "psroi_pool", "roi_pool",
    "yolo_box", "yolo_loss",
]


class RoIPool(Layer):
    """Layer over roi_pool (paddle.vision.ops.RoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        from .ops_detection import roi_pool

        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool(Layer):
    """Layer over psroi_pool (paddle.vision.ops.PSRoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        from .ops_detection import psroi_pool

        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def read_file(filename, name=None):
    """Raw file bytes as a uint8 tensor (paddle.vision.ops.read_file)."""
    import numpy as np

    from ..core.tensor import Tensor

    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(data)


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode JPEG bytes to an HWC uint8 tensor via PIL (host op —
    image IO has no TPU role; reference uses nvjpeg on GPU)."""
    import io as _io

    import numpy as np
    from PIL import Image

    from ..core.tensor import Tensor

    raw = bytes(np.asarray(x._value if hasattr(x, "_value") else x)
                .astype(np.uint8))
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return Tensor(np.transpose(arr, (2, 0, 1)))  # CHW like the reference


__all__ += ["RoIPool", "PSRoIPool", "read_file", "decode_jpeg"]
