"""Detection ops (manifest batch): prior/anchor generation, box coding,
YOLO decoding, NMS variants, RoI pooling, FPN routing.

Role parity: `paddle/fluid/operators/detection/` + phi kernels
(`box_coder`, `prior_box`, `yolo_box`, `matrix_nms`, `multiclass_nms3`,
`roi_pool`, `psroi_pool`, `generate_proposals`,
`distribute_fpn_proposals`) surfaced through `paddle.vision.ops`.

TPU-first split: the dense per-pixel decoders (`prior_box`, `box_coder`,
`yolo_box`) are jnp formulas that fuse under jit; the ragged
post-processing ops (NMS variants, proposal generation, FPN routing,
RoI pooling with data-dependent bin sizes) run host-side in numpy — they
produce variable-length outputs that cannot live inside a static-shape
XLA program, matching how the reference runs them on CPU in deployment
pipelines."""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = [
    "prior_box", "box_coder", "yolo_box", "yolo_loss", "matrix_nms",
    "multiclass_nms", "roi_pool", "psroi_pool", "generate_proposals",
    "distribute_fpn_proposals",
]


def _np(x):
    return np.asarray(x._value if isinstance(x, Tensor) else x)


# ============================ dense decoders ============================

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes per feature-map cell (paddle.vision.ops.prior_box)."""
    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    whs = []
    for ms in min_sizes:
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    whs = np.asarray(whs, np.float32)  # [P, 2]
    p = len(whs)

    cx = (np.arange(fw, dtype=np.float32) + offset) * step_w
    cy = (np.arange(fh, dtype=np.float32) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)  # [fh, fw]
    centers = np.stack([cxg, cyg], -1)[:, :, None, :]          # [fh,fw,1,2]
    half = whs[None, None, :, :] / 2.0                          # [1,1,P,2]
    mins = (centers - half) / np.asarray([iw, ih], np.float32)
    maxs = (centers + half) / np.asarray([iw, ih], np.float32)
    boxes = np.concatenate([mins, maxs], -1).astype(np.float32)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          boxes.shape).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """Encode/decode boxes against priors (paddle.vision.ops.box_coder)."""
    norm = 0.0 if box_normalized else 1.0

    def f(pb, pbv, tb):
        pw = pb[..., 2] - pb[..., 0] + norm
        ph = pb[..., 3] - pb[..., 1] + norm
        pcx = pb[..., 0] + pw * 0.5
        pcy = pb[..., 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[..., 2] - tb[..., 0] + norm
            th = tb[..., 3] - tb[..., 1] + norm
            tcx = tb[..., 0] + tw * 0.5
            tcy = tb[..., 1] + th * 0.5
            # broadcast priors [M,4] against targets [N,4] -> [N,M,4]
            out = jnp.stack([
                (tcx[:, None] - pcx[None, :]) / pw[None, :],
                (tcy[:, None] - pcy[None, :]) / ph[None, :],
                jnp.log(tw[:, None] / pw[None, :]),
                jnp.log(th[:, None] / ph[None, :]),
            ], axis=-1)
            if pbv is not None:
                out = out / pbv[None, :, :]
            return out
        # decode: target [N,M,4] deltas against priors along `axis`
        if pbv is not None:
            tb = tb * (pbv[None, :, :] if axis == 0 else pbv[:, None, :])
        exp = (lambda a: a[None, :]) if axis == 0 else (lambda a: a[:, None])
        dcx = exp(pcx) + tb[..., 0] * exp(pw)
        dcy = exp(pcy) + tb[..., 1] * exp(ph)
        dw = exp(pw) * jnp.exp(tb[..., 2])
        dh = exp(ph) * jnp.exp(tb[..., 3])
        return jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                          dcx + dw * 0.5 - norm, dcy + dh * 0.5 - norm], -1)

    return apply("box_coder", f, prior_box, prior_box_var, target_box)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head output to boxes+scores (paddle.vision.ops.
    yolo_box)."""
    na = len(anchors) // 2
    anc = jnp.asarray(np.asarray(anchors, np.float32).reshape(na, 2))

    def f(xv, imgs):
        import jax as _jax

        b, c, h, w = xv.shape
        v = xv.reshape(b, na, -1, h, w)  # attrs: x,y,w,h,obj,cls...
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        sx = _jax.nn.sigmoid(v[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2
        sy = _jax.nn.sigmoid(v[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2
        bx = (gx + sx) / w
        by = (gy + sy) / h
        bw = jnp.exp(v[:, :, 2]) * anc[None, :, 0, None, None] / (
            downsample_ratio * w)
        bh = jnp.exp(v[:, :, 3]) * anc[None, :, 1, None, None] / (
            downsample_ratio * h)
        obj = _jax.nn.sigmoid(v[:, :, 4])
        if iou_aware:
            obj = obj  # iou channel layout not modeled; plain objness
        cls = _jax.nn.sigmoid(v[:, :, 5:5 + class_num])
        score = obj[:, :, None] * cls
        imgh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        imgw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imgw
        y1 = (by - bh / 2) * imgh
        x2 = (bx + bw / 2) * imgw
        y2 = (by + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0)
            y1 = jnp.clip(y1, 0)
            x2 = jnp.minimum(x2, imgw - 1)
            y2 = jnp.minimum(y2, imgh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(b, -1, 4)
        mask = (obj > conf_thresh).reshape(b, -1, 1)
        boxes = jnp.where(mask, boxes, 0.0)
        scores = score.transpose(0, 1, 3, 4, 2).reshape(b, -1, class_num)
        return boxes, scores

    return apply("yolo_box", f, x, img_size)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 training loss (paddle.vision.ops.yolo_loss; reference
    kernel `paddle/fluid/operators/detection/yolov3_loss_op.h`).

    Composed jnp implementation of the reference semantics (vectorized,
    static shapes — no per-gt Python loops, so it jits on TPU):
      * a gt is assigned to the anchor (over ALL `anchors`) with best
        wh-IoU; this level supervises it only when that anchor is in
        `anchor_mask`, at the gt's center cell;
      * xy use sigmoid-BCE, wh use squared error, both weighted by
        (2 - gw*gh) box-size scale;
      * objectness BCE everywhere, except predictions whose best IoU
        against any gt exceeds `ignore_thresh` (no-obj loss masked);
      * class BCE with one-hot targets (uniform label smoothing when
        `use_label_smooth`), positives weighted by `gt_score` (mixup).
    Returns the per-image loss `[N]` like the reference.

    x: [N, A*(5+C), H, W]; gt_box: [N, B, 4] (cx, cy, w, h, normalized);
    gt_label: [N, B] int; anchors: flat pixel pairs; anchor_mask: indices
    of this level's anchors within `anchors`.
    """
    na_all = len(anchors) // 2
    anc_all = np.asarray(anchors, np.float32).reshape(na_all, 2)
    mask = list(anchor_mask)
    anc = jnp.asarray(anc_all[mask])          # [A, 2] this level (pixels)
    anc_all_j = jnp.asarray(anc_all)          # [A_all, 2]

    def _bce(logit, target):
        # numerically-stable sigmoid cross entropy
        return jnp.maximum(logit, 0) - logit * target + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def f(xv, gb, gl, gs):
        import jax

        n, c, h, w = xv.shape
        a = len(mask)
        v = xv.reshape(n, a, 5 + class_num, h, w)
        in_w = float(downsample_ratio * w)
        in_h = float(downsample_ratio * h)

        valid = (gb[..., 2] > 0) & (gb[..., 3] > 0)      # [N, B]
        # ---- anchor assignment over ALL anchors by wh-IoU at origin ----
        gw_pix = gb[..., 2] * in_w                        # [N, B]
        gh_pix = gb[..., 3] * in_h
        inter = jnp.minimum(gw_pix[..., None], anc_all_j[:, 0]) * \
            jnp.minimum(gh_pix[..., None], anc_all_j[:, 1])
        union = gw_pix[..., None] * gh_pix[..., None] + \
            anc_all_j[:, 0] * anc_all_j[:, 1] - inter
        best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-9), -1)
        # which of THIS level's anchor slots (or -1)
        level_slot = jnp.full_like(best_anchor, -1)
        for slot, am in enumerate(mask):
            level_slot = jnp.where(best_anchor == am, slot, level_slot)
        pos = valid & (level_slot >= 0)                   # [N, B]

        gi = jnp.clip((gb[..., 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gb[..., 1] * h).astype(jnp.int32), 0, h - 1)
        slot = jnp.clip(level_slot, 0, a - 1)

        # ---- scatter per-gt targets onto the [N, A, H, W] lattice ----
        tx = gb[..., 0] * w - gi                          # in-cell offset
        ty = gb[..., 1] * h - gj
        tw = jnp.log(jnp.maximum(
            gw_pix / jnp.maximum(anc[slot][..., 0], 1e-9), 1e-9))
        th = jnp.log(jnp.maximum(
            gh_pix / jnp.maximum(anc[slot][..., 1], 1e-9), 1e-9))
        box_scale = 2.0 - gb[..., 2] * gb[..., 3]
        score = gs if gs is not None else jnp.ones_like(tx)

        nb = gb.shape[1]
        batch_ix = jnp.broadcast_to(jnp.arange(n)[:, None], slot.shape)
        flat_idx = ((batch_ix * a + slot) * h + gj) * w + gi  # [N, B]
        size = n * a * h * w

        def scat(vals):
            return jnp.zeros((size,), jnp.float32).at[
                flat_idx.reshape(-1)].add(
                    jnp.where(pos, vals, 0.0).reshape(-1)
                ).reshape(n, a, h, w)

        t_obj = scat(jnp.ones_like(tx))
        # a cell can host at most one gt in practice; scatter-add keeps
        # the math well-defined if two collide
        t_mask = jnp.minimum(t_obj, 1.0)
        t_x = scat(tx)
        t_y = scat(ty)
        t_w = scat(tw)
        t_h = scat(th)
        t_scale = scat(box_scale)
        t_score = scat(score)

        cls_hot = jax.nn.one_hot(gl, class_num, dtype=jnp.float32)
        if use_label_smooth:
            delta = 1.0 / max(class_num, 1)
            cls_hot = cls_hot * (1.0 - delta) + delta * 0.5
        t_cls = jnp.zeros((size, class_num), jnp.float32).at[
            flat_idx.reshape(-1)].add(
                jnp.where(pos[..., None], cls_hot, 0.0)
                .reshape(-1, class_num)).reshape(n, a, h, w, class_num)

        # ---- ignore mask: decoded preds vs gts, IoU > thresh ----
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        px = (gx + jax.nn.sigmoid(v[:, :, 0])) / w
        py = (gy + jax.nn.sigmoid(v[:, :, 1])) / h
        pw = jnp.exp(jnp.clip(v[:, :, 2], -10, 10)) * \
            anc[None, :, 0, None, None] / in_w
        ph = jnp.exp(jnp.clip(v[:, :, 3], -10, 10)) * \
            anc[None, :, 1, None, None] / in_h
        # IoU of every pred [N,A,H,W] against every gt [N,B]
        def corners(cx, cy, ww, hh):
            return cx - ww / 2, cy - hh / 2, cx + ww / 2, cy + hh / 2

        px1, py1, px2, py2 = corners(px[..., None], py[..., None],
                                     pw[..., None], ph[..., None])
        gx1, gy1, gx2, gy2 = corners(
            gb[:, None, None, None, :, 0], gb[:, None, None, None, :, 1],
            gb[:, None, None, None, :, 2], gb[:, None, None, None, :, 3])
        iw = jnp.maximum(jnp.minimum(px2, gx2) - jnp.maximum(px1, gx1), 0)
        ih = jnp.maximum(jnp.minimum(py2, gy2) - jnp.maximum(py1, gy1), 0)
        inter_p = iw * ih
        union_p = pw[..., None] * ph[..., None] + \
            gb[:, None, None, None, :, 2] * gb[:, None, None, None, :, 3] \
            - inter_p
        iou = jnp.where(valid[:, None, None, None, :],
                        inter_p / jnp.maximum(union_p, 1e-9), 0.0)
        best_iou = jnp.max(iou, axis=-1)                 # [N, A, H, W]
        noobj_mask = (best_iou <= ignore_thresh).astype(jnp.float32) * \
            (1.0 - t_mask)

        # ---- losses ----
        wpos = t_mask * t_scale * t_score
        loss_xy = wpos * (_bce(v[:, :, 0], t_x) + _bce(v[:, :, 1], t_y))
        loss_wh = 0.5 * wpos * ((v[:, :, 2] - t_w) ** 2 +
                                (v[:, :, 3] - t_h) ** 2)
        loss_obj = t_mask * t_score * _bce(v[:, :, 4], jnp.ones_like(t_obj)) \
            + noobj_mask * _bce(v[:, :, 4], jnp.zeros_like(t_obj))
        loss_cls = (t_mask * t_score)[..., None] * _bce(
            jnp.moveaxis(v[:, :, 5:5 + class_num], 2, -1), t_cls)
        per_image = (loss_xy + loss_wh + loss_obj).sum((1, 2, 3)) + \
            loss_cls.sum((1, 2, 3, 4))
        return per_image

    if gt_score is None:
        return apply("yolo_loss", lambda xv, gb, gl: f(
            xv, gb, gl.astype(jnp.int32), None), x, gt_box, gt_label)
    return apply("yolo_loss", lambda xv, gb, gl, gs: f(
        xv, gb, gl.astype(jnp.int32), gs), x, gt_box, gt_label, gt_score)


# ======================= host-side post-processing =======================

def _iou_matrix(a, b):
    area_a = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(
        a[:, 3] - a[:, 1], 0)
    area_b = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(
        b[:, 3] - b[:, 1], 0)
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter,
                              1e-10)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2 decay formulation; paddle.vision.ops.matrix_nms)."""
    bv = _np(bboxes)
    sv = _np(scores)
    all_out, all_idx, rois_num = [], [], []
    n, c = sv.shape[0], sv.shape[1]
    for i in range(n):
        dets, idxs = [], []
        for cl in range(c):
            if cl == background_label:
                continue
            sc = sv[i, cl]
            keep = np.where(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[keep])][:nms_top_k]
            boxes = bv[i, order]
            s = sc[order].copy()
            iou = _iou_matrix(boxes, boxes)
            iou = np.triu(iou, 1)
            iou_cmax = iou.max(0)
            if use_gaussian:
                decay = np.exp((iou_cmax ** 2 - iou ** 2) / gaussian_sigma)
            else:
                decay = (1 - iou) / np.maximum(1 - iou_cmax, 1e-10)
            s = s * decay.min(0)
            sel = np.where(s > post_threshold)[0]
            for j in sel:
                dets.append([cl, s[j], *boxes[j]])
                idxs.append(i * sv.shape[2] + order[j])
        dets = np.asarray(dets, np.float32).reshape(-1, 6)
        idxs = np.asarray(idxs, np.int64)
        if dets.shape[0] > keep_top_k > 0:
            top = np.argsort(-dets[:, 1])[:keep_top_k]
            dets, idxs = dets[top], idxs[top]
        all_out.append(dets)
        all_idx.append(idxs)
        rois_num.append(dets.shape[0])
    out = Tensor(np.concatenate(all_out) if all_out else
                 np.zeros((0, 6), np.float32))
    res = [out]
    if return_index:
        res.append(Tensor(np.concatenate(all_idx) if all_idx else
                          np.zeros(0, np.int64)))
    if return_rois_num:
        res.append(Tensor(np.asarray(rois_num, np.int32)))
    return tuple(res) if len(res) > 1 else out


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, return_index=False,
                   return_rois_num=True, rois_num=None, name=None):
    """Hard-NMS per class (phi `multiclass_nms3` role)."""
    bv = _np(bboxes)
    sv = _np(scores)
    all_out, all_idx, out_num = [], [], []
    n, c = sv.shape[0], sv.shape[1]
    for i in range(n):
        dets, idxs = [], []
        for cl in range(c):
            if cl == background_label:
                continue
            sc = sv[i, cl]
            keep = np.where(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[keep])][:nms_top_k]
            boxes = bv[i, order]
            s = sc[order]
            suppressed = np.zeros(len(order), bool)
            thresh = nms_threshold
            for j in range(len(order)):
                if suppressed[j]:
                    continue
                dets.append([cl, s[j], *boxes[j]])
                idxs.append(i * sv.shape[2] + order[j])
                iou = _iou_matrix(boxes[j:j + 1], boxes)[0]
                suppressed |= iou > thresh
                suppressed[j] = True
                if nms_eta < 1.0 and thresh > 0.5:
                    thresh *= nms_eta
        dets = np.asarray(dets, np.float32).reshape(-1, 6)
        idxs = np.asarray(idxs, np.int64)
        if dets.shape[0] > keep_top_k > 0:
            top = np.argsort(-dets[:, 1])[:keep_top_k]
            dets, idxs = dets[top], idxs[top]
        all_out.append(dets)
        all_idx.append(idxs)
        out_num.append(dets.shape[0])
    out = Tensor(np.concatenate(all_out) if all_out else
                 np.zeros((0, 6), np.float32))
    res = [out]
    if return_index:
        res.append(Tensor(np.concatenate(all_idx) if all_idx else
                          np.zeros(0, np.int64)))
    if return_rois_num:
        res.append(Tensor(np.asarray(out_num, np.int32)))
    return tuple(res) if len(res) > 1 else out


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Quantized max RoI pooling (paddle.vision.ops.roi_pool)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    xv = _np(x)
    rois = _np(boxes)
    nums = _np(boxes_num)
    out = np.zeros((rois.shape[0], xv.shape[1], ph, pw), np.float32)
    ri = 0
    for img, cnt in enumerate(nums):
        for _ in range(int(cnt)):
            x1, y1, x2, y2 = np.round(rois[ri] * spatial_scale).astype(int)
            rh = max(y2 - y1 + 1, 1)
            rw = max(x2 - x1 + 1, 1)
            for i in range(ph):
                for j in range(pw):
                    hs = y1 + int(np.floor(i * rh / ph))
                    he = y1 + int(np.ceil((i + 1) * rh / ph))
                    ws = x1 + int(np.floor(j * rw / pw))
                    we = x1 + int(np.ceil((j + 1) * rw / pw))
                    hs, he = np.clip([hs, he], 0, xv.shape[2])
                    ws, we = np.clip([ws, we], 0, xv.shape[3])
                    if he > hs and we > ws:
                        out[ri, :, i, j] = xv[img, :, hs:he, ws:we].max(
                            axis=(1, 2))
            ri += 1
    return Tensor(out)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (paddle.vision.ops.psroi_pool):
    channel group (i,j) feeds output bin (i,j), average-pooled."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    xv = _np(x)
    rois = _np(boxes)
    nums = _np(boxes_num)
    c_out = xv.shape[1] // (ph * pw)
    out = np.zeros((rois.shape[0], c_out, ph, pw), np.float32)
    ri = 0
    for img, cnt in enumerate(nums):
        for _ in range(int(cnt)):
            x1, y1, x2, y2 = rois[ri] * spatial_scale
            rh = max(y2 - y1, 0.1)
            rw = max(x2 - x1, 0.1)
            for i in range(ph):
                for j in range(pw):
                    hs = int(np.floor(y1 + i * rh / ph))
                    he = int(np.ceil(y1 + (i + 1) * rh / ph))
                    ws = int(np.floor(x1 + j * rw / pw))
                    we = int(np.ceil(x1 + (j + 1) * rw / pw))
                    hs, he = np.clip([hs, he], 0, xv.shape[2])
                    ws, we = np.clip([ws, we], 0, xv.shape[3])
                    if he > hs and we > ws:
                        grp = (i * pw + j)
                        for co in range(c_out):
                            ch = grp * c_out + co
                            out[ri, co, i, j] = xv[
                                img, ch, hs:he, ws:we].mean()
            ri += 1
    return Tensor(out)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True, name=None):
    """RPN proposal generation (paddle.vision.ops.generate_proposals)."""
    sv = _np(scores)
    dv = _np(bbox_deltas)
    iv = _np(img_size)
    av = _np(anchors).reshape(-1, 4)
    vv = _np(variances).reshape(-1, 4)
    n = sv.shape[0]
    offset = 1.0 if pixel_offset else 0.0
    rois_all, scores_all, counts = [], [], []
    for i in range(n):
        sc = sv[i].transpose(1, 2, 0).reshape(-1)
        dl = dv[i].transpose(1, 2, 0).reshape(-1, 4)
        order = np.argsort(-sc)[:pre_nms_top_n]
        sc, dl, anc, var = sc[order], dl[order], av[order], vv[order]
        aw = anc[:, 2] - anc[:, 0] + offset
        ah = anc[:, 3] - anc[:, 1] + offset
        acx = anc[:, 0] + aw * 0.5
        acy = anc[:, 1] + ah * 0.5
        cx = var[:, 0] * dl[:, 0] * aw + acx
        cy = var[:, 1] * dl[:, 1] * ah + acy
        w = np.exp(np.minimum(var[:, 2] * dl[:, 2], np.log(1000 / 16))) * aw
        h = np.exp(np.minimum(var[:, 3] * dl[:, 3], np.log(1000 / 16))) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - offset, cy + h / 2 - offset], -1)
        ih, iw = iv[i, 0], iv[i, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - offset)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - offset)
        ws = boxes[:, 2] - boxes[:, 0] + offset
        hs = boxes[:, 3] - boxes[:, 1] + offset
        keep = np.where((ws >= min_size) & (hs >= min_size))[0]
        boxes, sc = boxes[keep], sc[keep]
        suppressed = np.zeros(len(boxes), bool)
        picked = []
        for j in range(len(boxes)):
            if suppressed[j]:
                continue
            picked.append(j)
            if len(picked) >= post_nms_top_n:
                break
            iou = _iou_matrix(boxes[j:j + 1], boxes)[0]
            suppressed |= iou > nms_thresh
            suppressed[j] = True
        rois_all.append(boxes[picked])
        scores_all.append(sc[picked])
        counts.append(len(picked))
    rois = Tensor(np.concatenate(rois_all).astype(np.float32) if rois_all
                  else np.zeros((0, 4), np.float32))
    rscores = Tensor(np.concatenate(scores_all).astype(np.float32)
                     if scores_all else np.zeros(0, np.float32))
    if return_rois_num:
        return rois, rscores, Tensor(np.asarray(counts, np.int32))
    return rois, rscores


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Route RoIs to FPN levels by scale (paddle.vision.ops.
    distribute_fpn_proposals)."""
    rois = _np(fpn_rois)
    offset = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + offset
    h = rois[:, 3] - rois[:, 1] + offset
    scale = np.sqrt(np.maximum(w * h, 1e-10))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(int)
    num_lvls = max_level - min_level + 1
    multi_rois, restore_parts, lvl_nums = [], [], []
    for li in range(num_lvls):
        idx = np.where(lvl == min_level + li)[0]
        multi_rois.append(Tensor(rois[idx]))
        restore_parts.append(idx)
        lvl_nums.append(Tensor(np.asarray([len(idx)], np.int32)))
    order = np.concatenate(restore_parts) if restore_parts else \
        np.zeros(0, int)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    out = (multi_rois, Tensor(restore.reshape(-1, 1).astype(np.int32)))
    if rois_num is not None:
        return out[0], out[1], lvl_nums
    return out