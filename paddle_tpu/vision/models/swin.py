"""Swin Transformer (BASELINE config 5 companion to ViT).

Role parity: the Swin family the reference ecosystem trains through its
fused attention stack. TPU-first notes: window partition/reverse are pure
reshape+transpose (free under XLA); the shifted-window roll is `jnp.roll`
(a static rotate XLA lowers to two slices+concat); window attention runs
as one batched matmul over [num_windows*B, tokens, C] — MXU-shaped.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ... import nn
from ...core.dispatch import apply
from ...core.tensor import Tensor
from ...nn import functional as F

__all__ = ["SwinTransformer", "swin_t", "swin_s", "swin_b"]


def _window_partition(x, ws):
    # x: [B, H, W, C] → [B*nH*nW, ws*ws, C]
    def f(v):
        B, H, W, C = v.shape
        v = v.reshape(B, H // ws, ws, W // ws, ws, C)
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(-1, ws * ws, C)

    return apply("window_partition", f, x)


def _window_reverse(windows, ws, H, W):
    def f(v):
        B = v.shape[0] // ((H // ws) * (W // ws))
        v = v.reshape(B, H // ws, W // ws, ws, ws, -1)
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(B, H, W, -1)

    return apply("window_reverse", f, windows)


class WindowAttention(nn.Layer):
    def __init__(self, dim, window_size, num_heads, attn_drop=0.0,
                 proj_drop=0.0):
        super().__init__()
        self.dim = dim
        self.ws = window_size
        self.num_heads = num_heads
        self.scale = (dim // num_heads) ** -0.5
        self.qkv = nn.Linear(dim, 3 * dim)
        self.proj = nn.Linear(dim, dim)
        self.attn_drop = attn_drop
        self.proj_drop = proj_drop
        # relative position bias table [(2w-1)^2, heads]
        self.rel_bias = self.create_parameter(
            [(2 * window_size - 1) ** 2, num_heads])
        coords = np.stack(np.meshgrid(np.arange(window_size),
                                      np.arange(window_size),
                                      indexing="ij"))  # [2, w, w]
        flat = coords.reshape(2, -1)
        rel = flat[:, :, None] - flat[:, None, :]       # [2, n, n]
        rel = rel.transpose(1, 2, 0) + window_size - 1
        self._rel_index = (rel[..., 0] * (2 * window_size - 1)
                           + rel[..., 1])               # [n, n]

    def forward(self, x, mask=None):
        n_tok = self.ws * self.ws
        heads = self.num_heads
        hd = self.dim // heads
        rel_idx = self._rel_index

        qkv = self.qkv(x)

        def f(qkv_v, bias_tab, mask_v):
            Bw = qkv_v.shape[0]
            qkv_ = qkv_v.reshape(Bw, n_tok, 3, heads, hd)
            q, k, v = (qkv_[:, :, i].transpose(0, 2, 1, 3)
                       for i in range(3))               # [Bw, h, n, hd]
            attn = (q * self.scale) @ k.transpose(0, 1, 3, 2)
            bias = bias_tab[rel_idx.reshape(-1)].reshape(
                n_tok, n_tok, heads).transpose(2, 0, 1)
            attn = attn + bias[None]
            if mask_v is not None:
                nw = mask_v.shape[0]
                attn = attn.reshape(Bw // nw, nw, heads, n_tok, n_tok) \
                    + mask_v[None, :, None]
                attn = attn.reshape(Bw, heads, n_tok, n_tok)
            attn = jax.nn.softmax(attn, axis=-1)
            out = (attn @ v).transpose(0, 2, 1, 3).reshape(Bw, n_tok,
                                                           self.dim)
            return out

        out = apply("window_attention", f, qkv, self.rel_bias, mask)
        if self.attn_drop and self.training:
            # post-softmax dropout folded onto the attention output (the
            # per-prob variant needs the mask inside f; output dropout is
            # the common simplification)
            out = F.dropout(out, self.attn_drop, training=True)
        out = self.proj(out)
        if self.proj_drop and self.training:
            out = F.dropout(out, self.proj_drop, training=True)
        return out


class SwinBlock(nn.Layer):
    def __init__(self, dim, input_resolution, num_heads, window_size=7,
                 shift_size=0, mlp_ratio=4.0, drop=0.0):
        super().__init__()
        self.dim = dim
        self.resolution = input_resolution
        self.ws = min(window_size, *input_resolution)
        # a window covering the whole feature map needs no shift
        self.shift = 0 if min(input_resolution) <= self.ws else shift_size
        self.norm1 = nn.LayerNorm(dim)
        self.attn = WindowAttention(dim, self.ws, num_heads,
                                    attn_drop=drop, proj_drop=drop)
        self.norm2 = nn.LayerNorm(dim)
        hidden = int(dim * mlp_ratio)
        self.mlp = nn.Sequential(nn.Linear(dim, hidden), nn.GELU(),
                                 nn.Dropout(drop),
                                 nn.Linear(hidden, dim), nn.Dropout(drop))
        if self.shift > 0:
            H, W = input_resolution
            img_mask = np.zeros((1, H, W, 1))
            slices = (slice(0, -self.ws), slice(-self.ws, -self.shift),
                      slice(-self.shift, None))
            cnt = 0
            for hs in slices:
                for ws_ in slices:
                    img_mask[:, hs, ws_, :] = cnt
                    cnt += 1
            m = img_mask.reshape(1, H // self.ws, self.ws, W // self.ws,
                                 self.ws, 1).transpose(0, 1, 3, 2, 4, 5)
            m = m.reshape(-1, self.ws * self.ws)
            diff = m[:, None, :] - m[:, :, None]
            self._attn_mask = Tensor(
                np.where(diff != 0, -100.0, 0.0).astype(np.float32))
        else:
            self._attn_mask = None

    def forward(self, x):
        from ... import ops

        H, W = self.resolution
        b, L, c = x.shape
        shortcut = x
        x = self.norm1(x)
        x = ops.reshape(x, [b, H, W, c])
        if self.shift > 0:
            x = apply("swin_roll",
                      lambda v: jnp.roll(v, (-self.shift, -self.shift),
                                         axis=(1, 2)), x)
        windows = _window_partition(x, self.ws)
        attn_out = self.attn(windows, self._attn_mask)
        x = _window_reverse(attn_out, self.ws, H, W)
        if self.shift > 0:
            x = apply("swin_unroll",
                      lambda v: jnp.roll(v, (self.shift, self.shift),
                                         axis=(1, 2)), x)
        x = ops.reshape(x, [b, L, c])
        x = ops.add(shortcut, x)
        return ops.add(x, self.mlp(self.norm2(x)))


class PatchMerging(nn.Layer):
    def __init__(self, input_resolution, dim):
        super().__init__()
        self.resolution = input_resolution
        self.dim = dim
        self.norm = nn.LayerNorm(4 * dim)
        self.reduction = nn.Linear(4 * dim, 2 * dim, bias_attr=False)

    def forward(self, x):
        from ... import ops

        H, W = self.resolution
        b, L, c = x.shape
        x = ops.reshape(x, [b, H, W, c])
        x = apply("patch_merge", lambda v: jnp.concatenate(
            [v[:, 0::2, 0::2], v[:, 1::2, 0::2],
             v[:, 0::2, 1::2], v[:, 1::2, 1::2]], axis=-1), x)
        x = ops.reshape(x, [b, (H // 2) * (W // 2), 4 * c])
        return self.reduction(self.norm(x))


class SwinTransformer(nn.Layer):
    def __init__(self, img_size=224, patch_size=4, in_ch=3, num_classes=1000,
                 embed_dim=96, depths=(2, 2, 6, 2), num_heads=(3, 6, 12, 24),
                 window_size=7, mlp_ratio=4.0, drop_rate=0.0):
        super().__init__()
        self.patch_embed = nn.Conv2D(in_ch, embed_dim, patch_size,
                                     stride=patch_size)
        res = img_size // patch_size
        self.num_layers = len(depths)
        layers = []
        dim = embed_dim
        for i, (depth, heads) in enumerate(zip(depths, num_heads)):
            for d in range(depth):
                layers.append(SwinBlock(
                    dim, (res, res), heads, window_size,
                    shift_size=0 if d % 2 == 0 else window_size // 2,
                    mlp_ratio=mlp_ratio, drop=drop_rate))
            if i != self.num_layers - 1:
                layers.append(PatchMerging((res, res), dim))
                dim *= 2
                res //= 2
        self.blocks = nn.LayerList(layers)
        self.norm = nn.LayerNorm(dim)
        self.head = nn.Linear(dim, num_classes)

    def forward(self, x):
        from ... import ops

        x = self.patch_embed(x)                  # [B, E, H', W']
        b, e = x.shape[0], x.shape[1]
        x = ops.transpose(ops.reshape(x, [b, e, -1]), [0, 2, 1])
        for blk in self.blocks:
            x = blk(x)
        x = self.norm(x)
        x = ops.mean(x, axis=1)
        return self.head(x)


def swin_t(**kw):
    return SwinTransformer(embed_dim=96, depths=(2, 2, 6, 2),
                           num_heads=(3, 6, 12, 24), **kw)


def swin_s(**kw):
    return SwinTransformer(embed_dim=96, depths=(2, 2, 18, 2),
                           num_heads=(3, 6, 12, 24), **kw)


def swin_b(**kw):
    return SwinTransformer(embed_dim=128, depths=(2, 2, 18, 2),
                           num_heads=(4, 8, 16, 32), **kw)
