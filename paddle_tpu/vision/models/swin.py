"""Swin Transformer (BASELINE config 5 companion to ViT).

Role parity: the Swin family the reference ecosystem trains through its
fused attention stack. TPU-first notes (ISSUE 10 — the PERF.md round-5
Swin ablation put the windowed-attention machinery at ~43% of
achievable step rate, Swin-T at 7.5% of baseline):

  * Windowed attention runs through ONE fused entry
    (`ops.pallas.window_attention.swin_window_attention`): on TPU a
    Pallas kernel owns cyclic shift + window partition + per-head
    attention with the dense rel-pos bias + reverse over image-layout
    blocks — the 6-D partition/reverse transposes and the roll never
    reach XLA. Off-TPU (and on gate rejects) the jnp reference runs the
    identical math, so CPU tests and TPU serve the same numerics.
  * The relative-position bias is densified WITHOUT a per-forward
    gather: `__init__` precomputes a constant one-hot scatter matrix
    [ws⁴, (2w-1)²]; the dense [num_heads, ws², ws²] table is then one
    MXU matmul from the trainable table (gradients flow — the old
    gather/reshape/transpose chain per forward was pure overhead per
    the ablation). Both the fused kernel and the fallback consume the
    same dense buffer.
  * The qkv projection is applied in image layout BEFORE partitioning
    (a per-token matmul commutes with the partition permutation), which
    is what lets the kernel read q/k/v as lane slices of one block.
"""
from __future__ import annotations

import functools

import numpy as np

import jax.numpy as jnp

from ... import nn
from ...core.dispatch import apply
from ...core.tensor import Tensor
from ...nn import functional as F

__all__ = ["SwinTransformer", "swin_t", "swin_s", "swin_b"]


def _window_partition(x, ws):
    # x: [B, H, W, C] → [B*nH*nW, ws*ws, C]  (kept for callers/tests;
    # the attention path itself goes through the fused entry)
    from ...ops.pallas.window_attention import window_partition

    return apply("window_partition", lambda v: window_partition(v, ws), x)


def _window_reverse(windows, ws, H, W):
    from ...ops.pallas.window_attention import window_reverse

    return apply("window_reverse",
                 lambda v: window_reverse(v, ws, H, W), windows)


@functools.lru_cache(maxsize=None)
def _rel_bias_constants(window_size):
    """(rel_index [n,n] int, onehot [ws^4,(2w-1)^2] f32) for one window
    size — the dense-bias scatter matrix that turns the per-forward
    gather chain into a single MXU matmul (differentiable — the table
    still trains; PERF.md ablation: the gather was pure overhead).
    Module-level cached: the constants depend only on window_size, so
    every WindowAttention instance (12 blocks in Swin-T, ~1.6 MB each
    at ws=7) shares ONE copy instead of baking a fresh one into each
    block's closure and traced program."""
    coords = np.stack(np.meshgrid(np.arange(window_size),
                                  np.arange(window_size),
                                  indexing="ij"))      # [2, w, w]
    flat = coords.reshape(2, -1)
    rel = flat[:, :, None] - flat[:, None, :]          # [2, n, n]
    rel = rel.transpose(1, 2, 0) + window_size - 1
    rel_index = (rel[..., 0] * (2 * window_size - 1)
                 + rel[..., 1])                        # [n, n]
    n_tok = window_size * window_size
    n_tab = (2 * window_size - 1) ** 2
    onehot = np.zeros((n_tok * n_tok, n_tab), np.float32)
    onehot[np.arange(n_tok * n_tok), rel_index.reshape(-1)] = 1.0
    return rel_index, onehot


class WindowAttention(nn.Layer):
    """Window multi-head self-attention over image-layout inputs.

    `forward(x_img, mask, shift)` takes the NORMED features in
    [B, H, W, C] image layout and returns [B, H, W, C]: qkv projection,
    then the fused windowed-attention entry (shift/partition/bias/
    reverse all inside), then the output projection."""

    def __init__(self, dim, window_size, num_heads, attn_drop=0.0,
                 proj_drop=0.0):
        super().__init__()
        self.dim = dim
        self.ws = window_size
        self.num_heads = num_heads
        self.scale = (dim // num_heads) ** -0.5
        self.qkv = nn.Linear(dim, 3 * dim)
        self.proj = nn.Linear(dim, dim)
        self.attn_drop = attn_drop
        self.proj_drop = proj_drop
        # relative position bias table [(2w-1)^2, heads] (trainable,
        # tied across window positions — reference parameterization)
        self.rel_bias = self.create_parameter(
            [(2 * window_size - 1) ** 2, num_heads])
        self._rel_index, self._bias_onehot = _rel_bias_constants(
            window_size)

    def dense_bias(self):
        """Dense [num_heads, ws², ws²] rel-pos bias from the trainable
        table — one matmul against the precomputed one-hot, no gather."""
        n_tok = self.ws * self.ws
        onehot = self._bias_onehot

        def f(tab):
            # lhs [T, h] x rhs one-hot [P, T] contract T -> [h, P]
            # (natural dot order: no output transpose)
            dense = jnp.einsum("th,pt->hp", tab.astype(jnp.float32),
                               onehot)
            return dense.reshape(self.num_heads, n_tok, n_tok)

        return apply("swin_rel_bias_dense", f, self.rel_bias)

    def forward(self, x_img, mask=None, shift=0):
        from ...ops.pallas.window_attention import swin_window_attention

        qkv = self.qkv(x_img)                       # [B, H, W, 3C]
        bias = self.dense_bias()
        fn = functools.partial(swin_window_attention,
                               window_size=self.ws, shift=int(shift),
                               num_heads=self.num_heads)
        if mask is None:
            out = apply("swin_window_attention",
                        lambda qv, bv: fn(qv, bv, None), qkv, bias)
        else:
            out = apply("swin_window_attention", fn, qkv, bias, mask)
        if self.attn_drop and self.training:
            # post-softmax dropout folded onto the attention output (the
            # per-prob variant needs the mask inside the kernel; output
            # dropout is the common simplification)
            out = F.dropout(out, self.attn_drop, training=True)
        out = self.proj(out)
        if self.proj_drop and self.training:
            out = F.dropout(out, self.proj_drop, training=True)
        return out


class SwinBlock(nn.Layer):
    def __init__(self, dim, input_resolution, num_heads, window_size=7,
                 shift_size=0, mlp_ratio=4.0, drop=0.0):
        super().__init__()
        self.dim = dim
        self.resolution = input_resolution
        self.ws = min(window_size, *input_resolution)
        # a window covering the whole feature map needs no shift
        self.shift = 0 if min(input_resolution) <= self.ws else shift_size
        self.norm1 = nn.LayerNorm(dim)
        self.attn = WindowAttention(dim, self.ws, num_heads,
                                    attn_drop=drop, proj_drop=drop)
        self.norm2 = nn.LayerNorm(dim)
        hidden = int(dim * mlp_ratio)
        self.mlp = nn.Sequential(nn.Linear(dim, hidden), nn.GELU(),
                                 nn.Dropout(drop),
                                 nn.Linear(hidden, dim), nn.Dropout(drop))
        if self.shift > 0:
            H, W = input_resolution
            img_mask = np.zeros((1, H, W, 1))
            slices = (slice(0, -self.ws), slice(-self.ws, -self.shift),
                      slice(-self.shift, None))
            cnt = 0
            for hs in slices:
                for ws_ in slices:
                    img_mask[:, hs, ws_, :] = cnt
                    cnt += 1
            m = img_mask.reshape(1, H // self.ws, self.ws, W // self.ws,
                                 self.ws, 1).transpose(0, 1, 3, 2, 4, 5)
            m = m.reshape(-1, self.ws * self.ws)
            diff = m[:, None, :] - m[:, :, None]
            self._attn_mask = Tensor(
                np.where(diff != 0, -100.0, 0.0).astype(np.float32))
        else:
            self._attn_mask = None

    def forward(self, x):
        from ... import ops

        H, W = self.resolution
        b, L, c = x.shape
        shortcut = x
        x = self.norm1(x)
        x = ops.reshape(x, [b, H, W, c])
        # shift + partition + attention + bias + reverse all live behind
        # the fused entry (Pallas on TPU, jnp reference elsewhere)
        x = self.attn(x, self._attn_mask, shift=self.shift)
        x = ops.reshape(x, [b, L, c])
        x = ops.add(shortcut, x)
        return ops.add(x, self.mlp(self.norm2(x)))


class PatchMerging(nn.Layer):
    def __init__(self, input_resolution, dim):
        super().__init__()
        self.resolution = input_resolution
        self.dim = dim
        self.norm = nn.LayerNorm(4 * dim)
        self.reduction = nn.Linear(4 * dim, 2 * dim, bias_attr=False)

    def forward(self, x):
        from ... import ops

        H, W = self.resolution
        b, L, c = x.shape
        x = ops.reshape(x, [b, H, W, c])
        x = apply("patch_merge", lambda v: jnp.concatenate(
            [v[:, 0::2, 0::2], v[:, 1::2, 0::2],
             v[:, 0::2, 1::2], v[:, 1::2, 1::2]], axis=-1), x)
        x = ops.reshape(x, [b, (H // 2) * (W // 2), 4 * c])
        return self.reduction(self.norm(x))


class SwinTransformer(nn.Layer):
    def __init__(self, img_size=224, patch_size=4, in_ch=3, num_classes=1000,
                 embed_dim=96, depths=(2, 2, 6, 2), num_heads=(3, 6, 12, 24),
                 window_size=7, mlp_ratio=4.0, drop_rate=0.0):
        super().__init__()
        self.patch_embed = nn.Conv2D(in_ch, embed_dim, patch_size,
                                     stride=patch_size)
        res = img_size // patch_size
        self.num_layers = len(depths)
        layers = []
        dim = embed_dim
        for i, (depth, heads) in enumerate(zip(depths, num_heads)):
            for d in range(depth):
                layers.append(SwinBlock(
                    dim, (res, res), heads, window_size,
                    shift_size=0 if d % 2 == 0 else window_size // 2,
                    mlp_ratio=mlp_ratio, drop=drop_rate))
            if i != self.num_layers - 1:
                layers.append(PatchMerging((res, res), dim))
                dim *= 2
                res //= 2
        self.blocks = nn.LayerList(layers)
        self.norm = nn.LayerNorm(dim)
        self.head = nn.Linear(dim, num_classes)

    def forward(self, x):
        from ... import ops

        x = self.patch_embed(x)                  # [B, E, H', W']
        b, e = x.shape[0], x.shape[1]
        x = ops.transpose(ops.reshape(x, [b, e, -1]), [0, 2, 1])
        for blk in self.blocks:
            x = blk(x)
        x = self.norm(x)
        x = ops.mean(x, axis=1)
        return self.head(x)


def swin_t(**kw):
    return SwinTransformer(embed_dim=96, depths=(2, 2, 6, 2),
                           num_heads=(3, 6, 12, 24), **kw)


def swin_s(**kw):
    return SwinTransformer(embed_dim=96, depths=(2, 2, 18, 2),
                           num_heads=(3, 6, 12, 24), **kw)


def swin_b(**kw):
    return SwinTransformer(embed_dim=128, depths=(2, 2, 18, 2),
                           num_heads=(4, 8, 16, 32), **kw)
