"""AlexNet / SqueezeNet / DenseNet / ShuffleNetV2 / GoogLeNet.

Role parity: the rest of the reference vision zoo
(`python/paddle/vision/models/{alexnet,squeezenet,densenet,shufflenetv2,
googlenet}.py`). Compact TPU-friendly implementations (NCHW like the
reference; XLA transposes to its preferred layout internally).
"""
from __future__ import annotations

from ... import nn

__all__ = ["AlexNet", "alexnet", "SqueezeNet", "squeezenet1_0",
           "squeezenet1_1", "DenseNet", "densenet121", "ShuffleNetV2",
           "shufflenet_v2_x1_0", "GoogLeNet", "googlenet"]


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(dropout), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(dropout), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        from ... import ops

        x = self.avgpool(self.features(x))
        return self.classifier(ops.flatten(x, start_axis=1))


def alexnet(**kw):
    return AlexNet(**kw)


class _Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(in_ch, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(
            nn.Conv2D(squeeze, e3, 3, padding=1), nn.ReLU())

    def forward(self, x):
        from ... import ops

        s = self.squeeze(x)
        return ops.concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000):
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        from ... import ops

        return ops.flatten(self.classifier(self.features(x)), start_axis=1)


def squeezenet1_0(**kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw):
    return SqueezeNet("1.1", **kw)


class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth, bn_size):
        super().__init__()
        self.fn = nn.Sequential(
            nn.BatchNorm2D(in_ch), nn.ReLU(),
            nn.Conv2D(in_ch, bn_size * growth, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                      bias_attr=False))

    def forward(self, x):
        from ... import ops

        return ops.concat([x, self.fn(x)], axis=1)


class DenseNet(nn.Layer):
    def __init__(self, layers_per_block=(6, 12, 24, 16), growth=32,
                 bn_size=4, num_classes=1000, init_ch=64):
        super().__init__()
        feats = [nn.Conv2D(3, init_ch, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_ch), nn.ReLU(),
                 nn.MaxPool2D(3, stride=2, padding=1)]
        ch = init_ch
        for i, n in enumerate(layers_per_block):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size))
                ch += growth
            if i != len(layers_per_block) - 1:
                feats += [nn.BatchNorm2D(ch), nn.ReLU(),
                          nn.Conv2D(ch, ch // 2, 1, bias_attr=False),
                          nn.AvgPool2D(2, stride=2)]
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        from ... import ops

        return self.classifier(
            ops.flatten(self.pool(self.features(x)), start_axis=1))


def densenet121(**kw):
    return DenseNet((6, 12, 24, 16), **kw)


def _channel_shuffle(x, groups):
    from ... import ops

    b, c, h, w = x.shape
    x = ops.reshape(x, [b, groups, c // groups, h, w])
    x = ops.transpose(x, [0, 2, 1, 3, 4])
    return ops.reshape(x, [b, c, h, w])


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_ch, out_ch, stride):
        super().__init__()
        self.stride = stride
        branch_ch = out_ch // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                          groups=in_ch, bias_attr=False),
                nn.BatchNorm2D(in_ch),
                nn.Conv2D(in_ch, branch_ch, 1, bias_attr=False),
                nn.BatchNorm2D(branch_ch), nn.ReLU())
            b2_in = in_ch
        else:
            self.branch1 = None
            b2_in = in_ch // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_ch, 1, bias_attr=False),
            nn.BatchNorm2D(branch_ch), nn.ReLU(),
            nn.Conv2D(branch_ch, branch_ch, 3, stride=stride, padding=1,
                      groups=branch_ch, bias_attr=False),
            nn.BatchNorm2D(branch_ch),
            nn.Conv2D(branch_ch, branch_ch, 1, bias_attr=False),
            nn.BatchNorm2D(branch_ch), nn.ReLU())

    def forward(self, x):
        from ... import ops

        if self.stride > 1:
            out = ops.concat([self.branch1(x), self.branch2(x)], axis=1)
        else:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = ops.concat([x1, self.branch2(x2)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__()
        stage_out = {0.5: [48, 96, 192, 1024], 1.0: [116, 232, 464, 1024],
                     1.5: [176, 352, 704, 1024],
                     2.0: [244, 488, 976, 2048]}[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, 24, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(24), nn.ReLU())
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_ch = 24
        for i, reps in enumerate([4, 8, 4]):
            out_ch = stage_out[i]
            units = [_ShuffleUnit(in_ch, out_ch, 2)]
            units += [_ShuffleUnit(out_ch, out_ch, 1)
                      for _ in range(reps - 1)]
            stages.append(nn.Sequential(*units))
            in_ch = out_ch
        self.stages = nn.LayerList(stages)
        self.conv5 = nn.Sequential(
            nn.Conv2D(in_ch, stage_out[3], 1, bias_attr=False),
            nn.BatchNorm2D(stage_out[3]), nn.ReLU())
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(stage_out[3], num_classes)

    def forward(self, x):
        from ... import ops

        x = self.maxpool(self.conv1(x))
        for s in self.stages:
            x = s(x)
        x = self.pool(self.conv5(x))
        return self.fc(ops.flatten(x, start_axis=1))


def shufflenet_v2_x1_0(**kw):
    return ShuffleNetV2(1.0, **kw)


class _Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, pool_proj):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_ch, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(in_ch, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(in_ch, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                nn.Conv2D(in_ch, pool_proj, 1), nn.ReLU())

    def forward(self, x):
        from ... import ops

        return ops.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                          axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.inc3 = nn.Sequential(
            _Inception(192, 64, 96, 128, 16, 32, 32),
            _Inception(256, 128, 128, 192, 32, 96, 64),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.inc4 = nn.Sequential(
            _Inception(480, 192, 96, 208, 16, 48, 64),
            _Inception(512, 160, 112, 224, 24, 64, 64),
            _Inception(512, 128, 128, 256, 24, 64, 64),
            _Inception(512, 112, 144, 288, 32, 64, 64),
            _Inception(528, 256, 160, 320, 32, 128, 128),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.inc5 = nn.Sequential(
            _Inception(832, 256, 160, 320, 32, 128, 128),
            _Inception(832, 384, 192, 384, 48, 128, 128))
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.4)
        self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        from ... import ops

        x = self.inc5(self.inc4(self.inc3(self.stem(x))))
        x = self.dropout(self.pool(x))
        return self.fc(ops.flatten(x, start_axis=1))


def googlenet(**kw):
    return GoogLeNet(**kw)
