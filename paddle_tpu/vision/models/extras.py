"""AlexNet / SqueezeNet / DenseNet / ShuffleNetV2 / GoogLeNet.

Role parity: the rest of the reference vision zoo
(`python/paddle/vision/models/{alexnet,squeezenet,densenet,shufflenetv2,
googlenet}.py`). Compact TPU-friendly implementations (NCHW like the
reference; XLA transposes to its preferred layout internally).
"""
from __future__ import annotations

from ... import nn

__all__ = ["AlexNet", "alexnet", "SqueezeNet", "squeezenet1_0",
           "squeezenet1_1", "DenseNet", "densenet121", "ShuffleNetV2",
           "shufflenet_v2_x1_0", "GoogLeNet", "googlenet"]


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(dropout), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(dropout), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        from ... import ops

        x = self.avgpool(self.features(x))
        return self.classifier(ops.flatten(x, start_axis=1))


def alexnet(**kw):
    return AlexNet(**kw)


class _Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(in_ch, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(
            nn.Conv2D(squeeze, e3, 3, padding=1), nn.ReLU())

    def forward(self, x):
        from ... import ops

        s = self.squeeze(x)
        return ops.concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000):
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        from ... import ops

        return ops.flatten(self.classifier(self.features(x)), start_axis=1)


def squeezenet1_0(**kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw):
    return SqueezeNet("1.1", **kw)


class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth, bn_size):
        super().__init__()
        self.fn = nn.Sequential(
            nn.BatchNorm2D(in_ch), nn.ReLU(),
            nn.Conv2D(in_ch, bn_size * growth, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                      bias_attr=False))

    def forward(self, x):
        from ... import ops

        return ops.concat([x, self.fn(x)], axis=1)


class DenseNet(nn.Layer):
    def __init__(self, layers_per_block=(6, 12, 24, 16), growth=32,
                 bn_size=4, num_classes=1000, init_ch=64):
        super().__init__()
        feats = [nn.Conv2D(3, init_ch, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_ch), nn.ReLU(),
                 nn.MaxPool2D(3, stride=2, padding=1)]
        ch = init_ch
        for i, n in enumerate(layers_per_block):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size))
                ch += growth
            if i != len(layers_per_block) - 1:
                feats += [nn.BatchNorm2D(ch), nn.ReLU(),
                          nn.Conv2D(ch, ch // 2, 1, bias_attr=False),
                          nn.AvgPool2D(2, stride=2)]
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        from ... import ops

        return self.classifier(
            ops.flatten(self.pool(self.features(x)), start_axis=1))


def densenet121(**kw):
    return DenseNet((6, 12, 24, 16), **kw)


def _channel_shuffle(x, groups):
    from ... import ops

    b, c, h, w = x.shape
    x = ops.reshape(x, [b, groups, c // groups, h, w])
    x = ops.transpose(x, [0, 2, 1, 3, 4])
    return ops.reshape(x, [b, c, h, w])


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_ch, out_ch, stride):
        super().__init__()
        self.stride = stride
        branch_ch = out_ch // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                          groups=in_ch, bias_attr=False),
                nn.BatchNorm2D(in_ch),
                nn.Conv2D(in_ch, branch_ch, 1, bias_attr=False),
                nn.BatchNorm2D(branch_ch), nn.ReLU())
            b2_in = in_ch
        else:
            self.branch1 = None
            b2_in = in_ch // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_ch, 1, bias_attr=False),
            nn.BatchNorm2D(branch_ch), nn.ReLU(),
            nn.Conv2D(branch_ch, branch_ch, 3, stride=stride, padding=1,
                      groups=branch_ch, bias_attr=False),
            nn.BatchNorm2D(branch_ch),
            nn.Conv2D(branch_ch, branch_ch, 1, bias_attr=False),
            nn.BatchNorm2D(branch_ch), nn.ReLU())

    def forward(self, x):
        from ... import ops

        if self.stride > 1:
            out = ops.concat([self.branch1(x), self.branch2(x)], axis=1)
        else:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = ops.concat([x1, self.branch2(x2)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__()
        stage_out = {0.5: [48, 96, 192, 1024], 1.0: [116, 232, 464, 1024],
                     1.5: [176, 352, 704, 1024],
                     2.0: [244, 488, 976, 2048]}[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, 24, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(24), nn.ReLU())
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_ch = 24
        for i, reps in enumerate([4, 8, 4]):
            out_ch = stage_out[i]
            units = [_ShuffleUnit(in_ch, out_ch, 2)]
            units += [_ShuffleUnit(out_ch, out_ch, 1)
                      for _ in range(reps - 1)]
            stages.append(nn.Sequential(*units))
            in_ch = out_ch
        self.stages = nn.LayerList(stages)
        self.conv5 = nn.Sequential(
            nn.Conv2D(in_ch, stage_out[3], 1, bias_attr=False),
            nn.BatchNorm2D(stage_out[3]), nn.ReLU())
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(stage_out[3], num_classes)

    def forward(self, x):
        from ... import ops

        x = self.maxpool(self.conv1(x))
        for s in self.stages:
            x = s(x)
        x = self.pool(self.conv5(x))
        return self.fc(ops.flatten(x, start_axis=1))


def shufflenet_v2_x1_0(**kw):
    return ShuffleNetV2(1.0, **kw)


class _Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, pool_proj):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_ch, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(in_ch, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(in_ch, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                nn.Conv2D(in_ch, pool_proj, 1), nn.ReLU())

    def forward(self, x):
        from ... import ops

        return ops.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                          axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.inc3 = nn.Sequential(
            _Inception(192, 64, 96, 128, 16, 32, 32),
            _Inception(256, 128, 128, 192, 32, 96, 64),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.inc4 = nn.Sequential(
            _Inception(480, 192, 96, 208, 16, 48, 64),
            _Inception(512, 160, 112, 224, 24, 64, 64),
            _Inception(512, 128, 128, 256, 24, 64, 64),
            _Inception(512, 112, 144, 288, 32, 64, 64),
            _Inception(528, 256, 160, 320, 32, 128, 128),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.inc5 = nn.Sequential(
            _Inception(832, 256, 160, 320, 32, 128, 128),
            _Inception(832, 384, 192, 384, 48, 128, 128))
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.4)
        self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        from ... import ops

        x = self.inc5(self.inc4(self.inc3(self.stem(x))))
        x = self.dropout(self.pool(x))
        return self.fc(ops.flatten(x, start_axis=1))


def googlenet(**kw):
    return GoogLeNet(**kw)


def densenet161(**kw):
    return DenseNet(layers_per_block=(6, 12, 36, 24), growth=48,
                    init_ch=96, **kw)


def densenet169(**kw):
    return DenseNet(layers_per_block=(6, 12, 32, 32), **kw)


def densenet201(**kw):
    return DenseNet(layers_per_block=(6, 12, 48, 32), **kw)


def densenet264(**kw):
    return DenseNet(layers_per_block=(6, 12, 64, 48), **kw)


def shufflenet_v2_x0_25(**kw):
    return ShuffleNetV2(scale=0.25, **kw)


def shufflenet_v2_x0_33(**kw):
    return ShuffleNetV2(scale=0.33, **kw)


def shufflenet_v2_x0_5(**kw):
    return ShuffleNetV2(scale=0.5, **kw)


def shufflenet_v2_x1_5(**kw):
    return ShuffleNetV2(scale=1.5, **kw)


def shufflenet_v2_x2_0(**kw):
    return ShuffleNetV2(scale=2.0, **kw)


class MobileNetV1(nn.Layer):
    """MobileNetV1 (parity: `python/paddle/vision/models/mobilenetv1.py`):
    depthwise-separable conv stack."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(8, int(ch * scale))

        def dw_sep(inp, out, stride=1):
            return nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride=stride, padding=1,
                          groups=inp, bias_attr=False),
                nn.BatchNorm2D(inp), nn.ReLU(),
                nn.Conv2D(inp, out, 1, bias_attr=False),
                nn.BatchNorm2D(out), nn.ReLU())

        cfg = [(c(32), c(64), 1), (c(64), c(128), 2), (c(128), c(128), 1),
               (c(128), c(256), 2), (c(256), c(256), 1),
               (c(256), c(512), 2)] + [(c(512), c(512), 1)] * 5 + \
              [(c(512), c(1024), 2), (c(1024), c(1024), 1)]
        feats = [nn.Conv2D(3, c(32), 3, stride=2, padding=1,
                           bias_attr=False),
                 nn.BatchNorm2D(c(32)), nn.ReLU()]
        for inp, out, s in cfg:
            feats.append(dw_sep(inp, out, s))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        from ... import ops

        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, start_axis=1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


class _HSigmoid(nn.Layer):
    def forward(self, x):
        from ...nn import functional as F

        return F.hardsigmoid(x, slope=1 / 6.0, offset=0.5)


class _SEBlock(nn.Layer):
    def __init__(self, ch, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, ch // r, 1)
        self.fc2 = nn.Conv2D(ch // r, ch, 1)
        self.hs = _HSigmoid()

    def forward(self, x):
        from ...nn import functional as F

        s = self.hs(self.fc2(F.relu(self.fc1(self.pool(x)))))
        return x * s


class _MNV3Block(nn.Layer):
    def __init__(self, inp, exp, out, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == out
        layers = []
        if exp != inp:
            layers += [nn.Conv2D(inp, exp, 1, bias_attr=False),
                       nn.BatchNorm2D(exp), act()]
        layers += [nn.Conv2D(exp, exp, k, stride=stride, padding=k // 2,
                             groups=exp, bias_attr=False),
                   nn.BatchNorm2D(exp)]
        if use_se:
            layers.append(_SEBlock(exp))
        layers += [act(),
                   nn.Conv2D(exp, out, 1, bias_attr=False),
                   nn.BatchNorm2D(out)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class _MobileNetV3(nn.Layer):
    """MobileNetV3 (parity: `python/paddle/vision/models/mobilenetv3.py`)."""

    def __init__(self, cfg, last_exp, last_ch, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(8, int(ch * scale + 4) // 8 * 8)

        hs = nn.Hardswish
        feats = [nn.Conv2D(3, c(16), 3, stride=2, padding=1,
                           bias_attr=False),
                 nn.BatchNorm2D(c(16)), hs()]
        inp = c(16)
        for k, exp, out, use_se, act, s in cfg:
            feats.append(_MNV3Block(inp, c(exp), c(out), k, s, use_se,
                                    hs if act == "HS" else nn.ReLU))
            inp = c(out)
        feats += [nn.Conv2D(inp, c(last_exp), 1, bias_attr=False),
                  nn.BatchNorm2D(c(last_exp)), hs()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(c(last_exp), last_ch), hs(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        from ... import ops

        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(ops.flatten(x, start_axis=1))
        return x


_MNV3_SMALL = [
    # k, exp, out, SE, act, stride
    (3, 16, 16, True, "RE", 2), (3, 72, 24, False, "RE", 2),
    (3, 88, 24, False, "RE", 1), (5, 96, 40, True, "HS", 2),
    (5, 240, 40, True, "HS", 1), (5, 240, 40, True, "HS", 1),
    (5, 120, 48, True, "HS", 1), (5, 144, 48, True, "HS", 1),
    (5, 288, 96, True, "HS", 2), (5, 576, 96, True, "HS", 1),
    (5, 576, 96, True, "HS", 1),
]

_MNV3_LARGE = [
    (3, 16, 16, False, "RE", 1), (3, 64, 24, False, "RE", 2),
    (3, 72, 24, False, "RE", 1), (5, 72, 40, True, "RE", 2),
    (5, 120, 40, True, "RE", 1), (5, 120, 40, True, "RE", 1),
    (3, 240, 80, False, "HS", 2), (3, 200, 80, False, "HS", 1),
    (3, 184, 80, False, "HS", 1), (3, 184, 80, False, "HS", 1),
    (3, 480, 112, True, "HS", 1), (3, 672, 112, True, "HS", 1),
    (5, 672, 160, True, "HS", 2), (5, 960, 160, True, "HS", 1),
    (5, 960, 160, True, "HS", 1),
]


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_MNV3_SMALL, last_exp=576, last_ch=1024,
                         scale=scale, num_classes=num_classes,
                         with_pool=with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_MNV3_LARGE, last_exp=960, last_ch=1280,
                         scale=scale, num_classes=num_classes,
                         with_pool=with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    return MobileNetV3Small(scale=scale, **kw)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    return MobileNetV3Large(scale=scale, **kw)


class _InceptionStem(nn.Layer):
    def __init__(self):
        super().__init__()

        def cbr(i, o, k, s=1, p=0):
            return nn.Sequential(nn.Conv2D(i, o, k, stride=s, padding=p,
                                           bias_attr=False),
                                 nn.BatchNorm2D(o), nn.ReLU())

        self.stem = nn.Sequential(
            cbr(3, 32, 3, 2), cbr(32, 32, 3), cbr(32, 64, 3, 1, 1),
            nn.MaxPool2D(3, stride=2), cbr(64, 80, 1), cbr(80, 192, 3),
            nn.MaxPool2D(3, stride=2))

    def forward(self, x):
        return self.stem(x)


def _cbr(i, o, k, s=1, p=0):
    return nn.Sequential(nn.Conv2D(i, o, k, stride=s, padding=p,
                                   bias_attr=False),
                         nn.BatchNorm2D(o), nn.ReLU())


class _InceptionA(nn.Layer):
    def __init__(self, inp, pool_ch):
        super().__init__()
        self.b1 = _cbr(inp, 64, 1)
        self.b5 = nn.Sequential(_cbr(inp, 48, 1), _cbr(48, 64, 5, 1, 2))
        self.b3 = nn.Sequential(_cbr(inp, 64, 1), _cbr(64, 96, 3, 1, 1),
                                _cbr(96, 96, 3, 1, 1))
        self.pool = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                  _cbr(inp, pool_ch, 1))

    def forward(self, x):
        from ... import ops

        return ops.concat([self.b1(x), self.b5(x), self.b3(x),
                           self.pool(x)], axis=1)


class InceptionV3(nn.Layer):
    """InceptionV3 (parity: `python/paddle/vision/models/inceptionv3.py`;
    the A-block tower + grid reductions condensed — the full B/C towers
    follow the same concat-of-branches pattern)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = _InceptionStem()
        self.inc_a1 = _InceptionA(192, 32)
        self.inc_a2 = _InceptionA(256, 64)
        self.inc_a3 = _InceptionA(288, 64)
        self.red1 = nn.Sequential(_cbr(288, 384, 3, 2))
        self.inc_b = _InceptionA(384, 64)
        self.red2 = nn.Sequential(_cbr(288, 768, 3, 2))
        self.inc_c = _InceptionA(768, 128)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(352, num_classes)

    def forward(self, x):
        from ... import ops

        x = self.stem(x)
        x = self.inc_a3(self.inc_a2(self.inc_a1(x)))
        x = self.inc_b(self.red1(x))
        x = self.inc_c(self.red2(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(ops.flatten(x, start_axis=1)))
        return x


def inception_v3(pretrained=False, **kw):
    return InceptionV3(**kw)
