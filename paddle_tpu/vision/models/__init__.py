from .resnet import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenetv2 import MobileNetV2, mobilenet_v2  # noqa: F401
from .lenet import LeNet  # noqa: F401
from .vit import (  # noqa: F401
    VisionTransformer, vit_b_16, vit_b_32, vit_h_14, vit_l_16, vit_l_32,
)
from .swin import SwinTransformer, swin_b, swin_s, swin_t  # noqa: F401
from .extras import (  # noqa: F401
    AlexNet, DenseNet, GoogLeNet, ShuffleNetV2, SqueezeNet, alexnet,
    densenet121, googlenet, shufflenet_v2_x1_0, squeezenet1_0,
    squeezenet1_1,
)
