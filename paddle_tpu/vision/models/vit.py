"""Vision Transformer (BASELINE config 5: ViT-L flash-attn on the Pallas
fused-attention path).

Role parity: the ViT family the reference serves through its model zoo +
`nn.functional.flash_attention` (`python/paddle/nn/functional/
flash_attention.py:146`); attention here routes through
`F.scaled_dot_product_attention`, which picks the Pallas flash kernel on
TPU ([B, S, H, D] layout, MXU-tiled).
"""
from __future__ import annotations


from ... import nn
from ...nn import functional as F

__all__ = ["VisionTransformer", "vit_b_16", "vit_b_32", "vit_l_16",
           "vit_l_32", "vit_h_14"]


class PatchEmbed(nn.Layer):
    def __init__(self, img_size=224, patch_size=16, in_ch=3, embed_dim=768):
        super().__init__()
        self.num_patches = (img_size // patch_size) ** 2
        self.proj = nn.Conv2D(in_ch, embed_dim, patch_size,
                              stride=patch_size)

    def forward(self, x):
        from ... import ops

        x = self.proj(x)                       # [B, E, H/P, W/P]
        b, e = x.shape[0], x.shape[1]
        x = ops.reshape(x, [b, e, -1])
        return ops.transpose(x, [0, 2, 1])     # [B, N, E]


class MHSA(nn.Layer):
    def __init__(self, dim, num_heads, attn_drop=0.0, proj_drop=0.0):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.qkv = nn.Linear(dim, 3 * dim)
        self.proj = nn.Linear(dim, dim)
        self.attn_drop = attn_drop
        self.proj_drop = proj_drop

    def forward(self, x):
        from ... import ops

        b, n, d = x.shape
        qkv = self.qkv(x).reshape([b, n, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(axis=2)           # [B, N, H, hd]
        out = F.scaled_dot_product_attention(
            q, k, v, dropout_p=self.attn_drop if self.training else 0.0,
            training=self.training)
        out = self.proj(out.reshape([b, n, d]))
        if self.proj_drop:
            out = F.dropout(out, self.proj_drop, training=self.training)
        return out


class Block(nn.Layer):
    def __init__(self, dim, num_heads, mlp_ratio=4.0, drop=0.0,
                 attn_drop=0.0, eps=1e-6):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim, epsilon=eps)
        self.attn = MHSA(dim, num_heads, attn_drop, drop)
        self.norm2 = nn.LayerNorm(dim, epsilon=eps)
        hidden = int(dim * mlp_ratio)
        self.mlp = nn.Sequential(
            nn.Linear(dim, hidden), nn.GELU(), nn.Dropout(drop),
            nn.Linear(hidden, dim), nn.Dropout(drop))

    def forward(self, x):
        x = x + self.attn(self.norm1(x))
        return x + self.mlp(self.norm2(x))


class VisionTransformer(nn.Layer):
    def __init__(self, img_size=224, patch_size=16, in_ch=3, num_classes=1000,
                 embed_dim=768, depth=12, num_heads=12, mlp_ratio=4.0,
                 drop_rate=0.0, attn_drop_rate=0.0, eps=1e-6):
        super().__init__()
        self.patch_embed = PatchEmbed(img_size, patch_size, in_ch, embed_dim)
        n = self.patch_embed.num_patches
        self.cls_token = self.create_parameter([1, 1, embed_dim])
        self.pos_embed = self.create_parameter([1, n + 1, embed_dim])
        self.pos_drop = nn.Dropout(drop_rate)
        self.blocks = nn.LayerList([
            Block(embed_dim, num_heads, mlp_ratio, drop_rate,
                  attn_drop_rate, eps) for _ in range(depth)])
        self.norm = nn.LayerNorm(embed_dim, epsilon=eps)
        self.head = nn.Linear(embed_dim, num_classes) \
            if num_classes > 0 else None

    def forward(self, x):
        from ... import ops

        x = self.patch_embed(x)
        b = x.shape[0]
        cls = ops.expand(self.cls_token, [b, 1, x.shape[-1]])
        x = ops.concat([cls, x], axis=1)
        x = self.pos_drop(ops.add(x, self.pos_embed))
        for blk in self.blocks:
            x = blk(x)
        x = self.norm(x)
        cls_out = x[:, 0]
        return self.head(cls_out) if self.head is not None else cls_out


def _vit(patch, dim, depth, heads, **kw):
    d = dict(patch_size=patch, embed_dim=dim, depth=depth, num_heads=heads)
    d.update(kw)
    return VisionTransformer(**d)


def vit_b_16(**kw):
    return _vit(16, 768, 12, 12, **kw)


def vit_b_32(**kw):
    return _vit(32, 768, 12, 12, **kw)


def vit_l_16(**kw):
    return _vit(16, 1024, 24, 16, **kw)


def vit_l_32(**kw):
    return _vit(32, 1024, 24, 16, **kw)


def vit_h_14(**kw):
    return _vit(14, 1280, 32, 16, **kw)
