"""Fused conv+norm+act dispatch for the vision models (ISSUE 10).

One helper owns the `act(bn(conv(x)))` pattern that dominates the
ResNet/MobileNet stem and blocks:

  * Inference (eval mode), dense or depthwise groups, dilation 1, int
    padding: the Pallas `fused_conv_bn_act` kernel runs the conv, the
    FOLDED batch-norm affine (`scale = gamma*rsqrt(var+eps)`,
    `shift = beta + (conv_bias - mean)*scale`) and the activation in
    one VMEM pass — the pre-activation conv output never reaches HBM.
    (On CPU the same entry runs its lax.conv reference — one code
    path, two tiers, `conv_norm.dispatch` counters tell them apart.)
  * Training-mode BN / unsupported shapes: the composed ops run exactly
    as before — batch norm needs live batch stats in training mode, so
    the fused tier requires frozen (eval) norm stats. Gradients DO flow
    through the fused tier (custom VJP = reference composed backward),
    so frozen-BN fine-tuning works either way.

The helper takes the MODULES (conv, bn), not raw arrays, so the models
keep their parameter/state_dict layout byte-for-byte.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...nn import functional as F

__all__ = ["conv_bn_act"]


def _int2(v):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v) if len(v) == 2 else None
    if isinstance(v, int):
        return (v, v)
    return None


def _fusable(conv, bn, act):
    # frozen norm stats are the only mode constraint: the fused tier is
    # differentiable (custom VJP replays the reference backward), so
    # frozen-BN fine-tuning and input-gradient probes route fused too
    if bn.training:
        return False
    if act not in ("relu", "relu6", None):
        return False
    if _int2(conv.stride) is None or _int2(conv.padding) is None:
        return False
    if _int2(conv.dilation) != (1, 1):
        return False
    groups = conv.groups
    cin = conv.weight.shape[1] * groups
    cout = conv.weight.shape[0]
    return groups == 1 or (groups == cin and cout == cin)


def conv_bn_act(x, conv, bn, act="relu"):
    """`act(bn(conv(x)))` with the fused inference tier when eligible.

    x: Tensor [B, Cin, H, W]; conv: nn.Conv2D; bn: nn.BatchNorm2D;
    act: 'relu' | 'relu6' | None."""
    if not _fusable(conv, bn, act):
        out = bn(conv(x))
        if act == "relu":
            out = F.relu(out)
        elif act == "relu6":
            out = F.relu6(out)
        return out

    from ...ops.pallas.conv_norm import fused_conv_bn_act

    stride = _int2(conv.stride)
    padding = _int2(conv.padding)
    eps = bn.epsilon

    def f(xv, wv, gamma, beta, mean, var, cbias):
        scale = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
        if gamma is not None:
            scale = scale * gamma.astype(jnp.float32)
        shift = -mean.astype(jnp.float32) * scale
        if beta is not None:
            shift = shift + beta.astype(jnp.float32)
        if cbias is not None:
            shift = shift + cbias.astype(jnp.float32) * scale
        return fused_conv_bn_act(xv, wv, scale, shift, stride=stride,
                                 padding=padding, act=act)

    return apply("fused_conv_bn_act", f, x, conv.weight, bn.weight,
                 bn.bias, bn._mean, bn._variance, conv.bias)
