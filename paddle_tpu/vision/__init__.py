from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401



_image_backend = "pil"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {backend!r}")
    if backend == "cv2":
        raise ValueError("cv2 is not available in this image; use 'pil'")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file (paddle.vision.image_load): PIL-backed."""
    import numpy as np
    from PIL import Image

    b = backend or _image_backend
    img = Image.open(path)
    if b == "tensor":
        from ..core.tensor import Tensor

        return Tensor(np.asarray(img))
    return img
