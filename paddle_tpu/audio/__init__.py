"""paddle_tpu.audio: audio feature extraction.

Role parity: `paddle.audio` (`python/paddle/audio/`) — functional window/
mel utilities and the Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC
feature layers built on the stft stack (which lives in
`paddle_tpu.signal`/`paddle_tpu.fft`, the pocketfft analog).

TPU-first: features are pure jnp pipelines (frame → window → rFFT → mel
matmul) that fuse under jit; the mel filterbank is a precomputed dense
matrix so the projection is an MXU matmul.
"""
from __future__ import annotations

import math

import numpy as np

import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["functional", "features"]


class functional:
    """paddle.audio.functional parity."""

    @staticmethod
    def hz_to_mel(freq, htk=False):
        if htk:
            return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
        f = np.asarray(freq, np.float64)
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        return np.where(f >= min_log_hz,
                        min_log_mel + np.log(f / min_log_hz) / logstep,
                        mels)

    @staticmethod
    def mel_to_hz(mel, htk=False):
        if htk:
            return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
        m = np.asarray(mel, np.float64)
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        return np.where(m >= min_log_mel,
                        min_log_hz * np.exp(logstep * (m - min_log_mel)),
                        freqs)

    @staticmethod
    def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
        lo = functional.hz_to_mel(f_min, htk)
        hi = functional.hz_to_mel(f_max, htk)
        return functional.mel_to_hz(np.linspace(lo, hi, n_mels), htk)

    @staticmethod
    def fft_frequencies(sr, n_fft):
        return np.linspace(0, sr / 2, n_fft // 2 + 1)

    @staticmethod
    def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                             htk=False, norm="slaney", dtype="float32"):
        f_max = f_max or sr / 2.0
        fft_freqs = functional.fft_frequencies(sr, n_fft)
        mel_f = functional.mel_frequencies(n_mels + 2, f_min, f_max, htk)
        fdiff = np.diff(mel_f)
        ramps = mel_f[:, None] - fft_freqs[None, :]
        weights = np.zeros((n_mels, len(fft_freqs)))
        for i in range(n_mels):
            lower = -ramps[i] / fdiff[i]
            upper = ramps[i + 2] / fdiff[i + 1]
            weights[i] = np.maximum(0, np.minimum(lower, upper))
        if norm == "slaney":
            enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
            weights *= enorm[:, None]
        return Tensor(weights.astype(dtype))

    @staticmethod
    def get_window(window, win_length, fftbins=True, dtype="float32"):
        n = win_length
        if isinstance(window, (tuple, list)):
            name, *params = window
        else:
            name, params = window, []
        periodic = fftbins
        m = n + 1 if periodic else n
        k = np.arange(m)
        if name in ("hann", "hanning"):
            w = 0.5 - 0.5 * np.cos(2 * np.pi * k / (m - 1))
        elif name == "hamming":
            w = 0.54 - 0.46 * np.cos(2 * np.pi * k / (m - 1))
        elif name == "blackman":
            w = (0.42 - 0.5 * np.cos(2 * np.pi * k / (m - 1))
                 + 0.08 * np.cos(4 * np.pi * k / (m - 1)))
        elif name == "bartlett":
            w = 1.0 - np.abs(2 * k / (m - 1) - 1.0)
        elif name in ("rect", "rectangular", "boxcar", "ones"):
            w = np.ones(m)
        elif name == "gaussian":
            std = params[0] if params else 0.4 * (m - 1) / 2
            w = np.exp(-0.5 * ((k - (m - 1) / 2) / std) ** 2)
        else:
            raise ValueError(f"unknown window {name!r}")
        if periodic:
            w = w[:-1]
        return Tensor(w.astype(dtype))

    @staticmethod
    def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
        def f(s):
            log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
            log_spec = log_spec - 10.0 * jnp.log10(
                jnp.maximum(amin, ref_value))
            if top_db is not None:
                log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
            return log_spec

        return apply("power_to_db", f,
                     spect if isinstance(spect, Tensor) else Tensor(spect))

    @staticmethod
    def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
        n = np.arange(n_mels)
        k = np.arange(n_mfcc)[:, None]
        dct = np.cos(np.pi / n_mels * (n + 0.5) * k)
        if norm == "ortho":
            dct[0] *= 1.0 / np.sqrt(2)
            dct *= np.sqrt(2.0 / n_mels)
        else:
            dct *= 2.0
        return Tensor(dct.T.astype(dtype))


class _Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window_t = functional.get_window(window, self.win_length,
                                              dtype=dtype)

    def forward(self, x):
        from .. import signal

        spec = signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                           self.window_t, center=self.center,
                           pad_mode=self.pad_mode)

        def mag(s):
            return jnp.abs(s) ** self.power

        return apply("spectrogram_mag", mag, spec)


class _MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = _Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.fbank = functional.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype)

    def forward(self, x):
        from .. import ops

        spec = self.spectrogram(x)  # [..., freq, time]
        return ops.matmul(self.fbank, spec)


class _LogMelSpectrogram(Layer):
    def __init__(self, *args, ref_value=1.0, amin=1e-10, top_db=None, **kw):
        super().__init__()
        self.mel = _MelSpectrogram(*args, **kw)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return functional.power_to_db(self.mel(x), self.ref_value,
                                      self.amin, self.top_db)


class _MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_mels=64, **kw):
        super().__init__()
        self.log_mel = _LogMelSpectrogram(sr=sr, n_mels=n_mels, **kw)
        self.dct = functional.create_dct(n_mfcc, n_mels)

    def forward(self, x):
        from .. import ops

        lm = self.log_mel(x)  # [..., n_mels, T]
        # dct: [n_mels, n_mfcc] → project the mel axis: [..., n_mfcc, T]
        perm = list(range(lm.ndim))
        perm[-2], perm[-1] = perm[-1], perm[-2]
        t = ops.transpose(lm, perm)           # [..., T, n_mels]
        proj = ops.matmul(t, self.dct)        # [..., T, n_mfcc]
        return ops.transpose(proj, perm)      # [..., n_mfcc, T]


class features:
    Spectrogram = _Spectrogram
    MelSpectrogram = _MelSpectrogram
    LogMelSpectrogram = _LogMelSpectrogram
    MFCC = _MFCC

from . import datasets  # noqa: F401,E402


from . import backends  # noqa: F401,E402
from .backends import info, load, save  # noqa: F401,E402
