"""paddle.audio.datasets parity (`python/paddle/audio/datasets/`):
TESS and ESC-50. Zero-egress build: both read LOCAL copies of the
official archives/folders (the reference downloads them); `download=True`
raises with instructions."""
from __future__ import annotations

import os

import numpy as np

from ...io import Dataset

__all__ = ["TESS", "ESC50"]


def _load_wav(path, sample_rate=None):
    import wave

    with wave.open(path, "rb") as w:
        sr = w.getframerate()
        n = w.getnframes()
        raw = w.readframes(n)
        width = w.getsampwidth()
        ch = w.getnchannels()
    dtype = {1: np.int8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dtype).astype(np.float32)
    data /= float(np.iinfo(dtype).max)
    if ch > 1:
        data = data.reshape(-1, ch).mean(axis=1)
    return data, sr


class TESS(Dataset):
    """Toronto Emotional Speech Set: seven emotions from folder names
    (reference audio/datasets/tess.py). Point `data_dir` at the local
    extracted dataset."""

    EMOTIONS = ("angry", "disgust", "fear", "happy", "neutral", "ps",
                "sad")

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 data_dir=None, archive=None, download=False, **kwargs):
        if download or not data_dir:
            raise RuntimeError(
                "no network egress: extract TESS locally and pass "
                "data_dir=")
        self.files = []
        self.labels = []
        for base, _, files in sorted(os.walk(data_dir)):
            for f in sorted(files):
                if not f.lower().endswith(".wav"):
                    continue
                for i, emo in enumerate(self.EMOTIONS):
                    if emo in f.lower() or emo in base.lower():
                        self.files.append(os.path.join(base, f))
                        self.labels.append(i)
                        break
        if not self.files:
            raise RuntimeError(f"no TESS wav files under {data_dir}")
        fold = np.arange(len(self.files)) % n_folds + 1
        keep = (fold != split) if mode == "train" else (fold == split)
        self.files = [f for f, k in zip(self.files, keep) if k]
        self.labels = [l for l, k in zip(self.labels, keep) if k]

    def __getitem__(self, idx):
        data, sr = _load_wav(self.files[idx])
        return data, self.labels[idx]

    def __len__(self):
        return len(self.files)


class ESC50(Dataset):
    """ESC-50 environmental sounds (reference audio/datasets/esc50.py):
    labels parsed from the official `{fold}-{src}-{take}-{target}.wav`
    naming. Point `data_dir` at the local audio folder."""

    def __init__(self, mode="train", split=1, feat_type="raw",
                 data_dir=None, download=False, **kwargs):
        if download or not data_dir:
            raise RuntimeError(
                "no network egress: extract ESC-50 locally and pass "
                "data_dir=")
        self.files = []
        self.labels = []
        for base, _, files in sorted(os.walk(data_dir)):
            for f in sorted(files):
                if not f.lower().endswith(".wav"):
                    continue
                parts = os.path.splitext(f)[0].split("-")
                if len(parts) != 4:
                    continue
                fold, target = int(parts[0]), int(parts[3])
                if (mode == "train") == (fold != split):
                    self.files.append(os.path.join(base, f))
                    self.labels.append(target)
        if not self.files:
            raise RuntimeError(f"no ESC-50 wav files under {data_dir}")

    def __getitem__(self, idx):
        data, sr = _load_wav(self.files[idx])
        return data, self.labels[idx]

    def __len__(self):
        return len(self.files)
