"""paddle.audio.backends parity: wave-format IO via the stdlib (the
reference's default 'wave_backend'); soundfile is optional-absent here."""
from __future__ import annotations

import wave as _wave

import numpy as np

__all__ = ["list_available_backends", "get_current_backend",
           "set_backend", "info", "load", "save", "AudioInfo"]


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name):
    if backend_name != "wave_backend":
        raise ValueError(
            "only the stdlib wave_backend is available in this image")


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_frames = num_samples
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath):
    with _wave.open(filepath, "rb") as w:
        return AudioInfo(w.getframerate(), w.getnframes(),
                         w.getnchannels(), w.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    from ...core.tensor import Tensor

    with _wave.open(filepath, "rb") as w:
        sr = w.getframerate()
        w.setpos(frame_offset)
        n = w.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = w.readframes(n)
        width = w.getsampwidth()
        ch = w.getnchannels()
    dtype = {1: np.int8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dtype).reshape(-1, ch)
    if normalize:
        data = data.astype(np.float32) / float(np.iinfo(dtype).max)
    if channels_first:
        data = data.T
    return Tensor(np.ascontiguousarray(data)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    arr = np.asarray(src._value if hasattr(src, "_value") else src)
    if channels_first:
        arr = arr.T
    if arr.dtype.kind == "f":
        arr = (np.clip(arr, -1, 1) * 32767).astype(np.int16)
    with _wave.open(filepath, "wb") as w:
        w.setnchannels(arr.shape[1] if arr.ndim > 1 else 1)
        w.setsampwidth(bits_per_sample // 8)
        w.setframerate(sample_rate)
        w.writeframes(arr.astype(np.int16).tobytes())
