"""TCPStore (parity: `paddle/phi/core/distributed/store/tcp_store.h:121`) —
framework-level rendezvous KV over the native C++ server/client."""
from __future__ import annotations

import time

from .. import native
from ..observability import metrics as _metrics


class TCPStore:
    def __init__(self, host="127.0.0.1", port=8577, is_master=False,
                 world_size=1, timeout=120.0):
        self.lib = native.load()
        self.host = host
        self.port = port
        self.world_size = world_size
        self.timeout = timeout
        self._server = None
        if is_master:
            self._server = self.lib.tcp_store_server_start(port)
            if not self._server:
                raise OSError(f"TCPStore server failed to bind :{port}")
        self._fd = self.lib.tcp_store_connect(host.encode(), port,
                                              float(timeout))
        if self._fd < 0:
            raise ConnectionError(f"TCPStore connect to {host}:{port} failed")

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        rc = self.lib.tcp_store_set(self._fd, key.encode(), len(key),
                                    value, len(value))
        if rc != 0:
            raise ConnectionError("TCPStore set failed")

    def get(self, key, timeout=None):
        import ctypes

        # the native GET blocks server-side with no deadline; apply the
        # store timeout by polling CHECK first, then doing the (now
        # immediate) blocking GET
        self.wait([key], timeout=timeout)
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        n = self.lib.tcp_store_get(self._fd, key.encode(), len(key), buf, cap)
        if n < 0:
            raise ConnectionError(f"TCPStore get({key!r}) failed: {n}")
        return buf.raw[:n]

    def add(self, key, amount):
        v = self.lib.tcp_store_add(self._fd, key.encode(), len(key),
                                   int(amount))
        if v == -(2 ** 63):
            raise ConnectionError("TCPStore add failed")
        return int(v)

    def check(self, key):
        return bool(self.lib.tcp_store_check(self._fd, key.encode(),
                                             len(key)))

    def wait(self, keys, timeout=None):
        deadline = time.time() + (timeout or self.timeout)
        for k in keys if isinstance(keys, (list, tuple)) else [keys]:
            while not self.check(k):
                if time.time() > deadline:
                    raise TimeoutError(f"TCPStore wait timeout on {k!r}")
                time.sleep(0.05)

    def barrier(self, key="_barrier", world_size=None):
        # reusable barrier with the round derived SERVER-side from one
        # global arrival counter: this caller's position in the global
        # arrival order fixes its round, so a relaunched rank (elastic
        # rejoin) continues at the cluster's current round instead of
        # restarting at 0 and desynchronizing
        n = world_size or self.world_size
        seq = self.add(f"{key}/seq", 1)
        r = (seq - 1) // n
        if seq == (r + 1) * n:
            self.set(f"{key}/go/{r}", b"1")
        self.wait([f"{key}/go/{r}"])

    def __del__(self):
        try:
            if getattr(self, "_fd", -1) >= 0:
                self.lib.tcp_store_disconnect(self._fd)
            if getattr(self, "_server", None):
                self.lib.tcp_store_server_stop(self._server)
        except Exception:
            # module-top import on purpose: importing inside a __del__
            # handler can itself raise at interpreter shutdown
            _metrics.inc("store.del_errors")
