"""paddle.distributed.io parity (`python/paddle/distributed/io.py`):
persistables save/load helpers for distributed programs. On this runtime
persistables are the state dicts the checkpoint package already shards;
these helpers cover the reference's single-file program-level entry
points."""
from __future__ import annotations

import os

__all__ = ["save_persistables", "load_persistables",
           "is_persistable", "save_inference_model", "load_inference_model"]


def is_persistable(var):
    from ..core.tensor import Parameter

    return isinstance(var, Parameter) or getattr(var, "persistable", False)


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    from ..framework.io_utils import save as _save
    from ..static import default_main_program

    prog = main_program or default_main_program()
    state = {}
    for p in prog.all_parameters():
        state[getattr(p, "name", f"param_{id(p)}")] = p
    os.makedirs(dirname, exist_ok=True)
    _save(state, os.path.join(dirname, filename or "persistables.pdparams"))


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    from ..framework.io_utils import load as _load
    from ..static import default_main_program

    state = _load(os.path.join(dirname,
                               filename or "persistables.pdparams"))
    prog = main_program or default_main_program()
    for p in prog.all_parameters():
        name = getattr(p, "name", None)
        if name in state:
            p.set_value(state[name]._value
                        if hasattr(state[name], "_value") else state[name])
    return state


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         **kw):
    from ..static.io import save_inference_model as _sim

    return _sim(os.path.join(dirname, "model"), feeded_var_names,
                target_vars, executor)


def load_inference_model(dirname, executor, **kw):
    from ..static.io import load_inference_model as _lim

    return _lim(os.path.join(dirname, "model"), executor)
