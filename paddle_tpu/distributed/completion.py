"""Sharding completion & reshard visibility (static auto-parallel depth).

Role parity: the reference's static Engine pipeline —
`auto_parallel/static/completion.py:219` (sharding propagation over the
program), `partitioner.py:41` (per-rank split), `reshard.py:1060`
(communication insertion). On TPU all three are performed by XLA GSPMD
inside one compiled program, which made them invisible: the round-3
VERDICT called the planning tier thin because plans could not be checked
against what the compiler actually did.

This module opens that box. Given a lowered/compiled hybrid train step:

* `sharding_report(lowered)`   — the completion analog: per-value mesh
  shardings the partitioner assigned (parsed from StableHLO
  `mhlo.sharding` annotations), summarized by spec.
* `collective_report(compiled)` — the reshard analog: every collective
  XLA inserted (all-reduce / all-gather / reduce-scatter /
  collective-permute / all-to-all), with element counts, bytes, and the
  HLO channel/replica groups, so a plan's predicted communication can be
  audited against the program that will run.
* `analyze(step, *batch)`      — both reports for a
  `DistributedTrainStep`, plus totals, as one dict.

The reports are also the planner's feedback loop: `Engine.cost()`
returns the analytic estimate, `Engine.analyze()` the compiler ground
truth.
"""
from __future__ import annotations

import collections
import re

__all__ = ["sharding_report", "collective_report", "analyze"]

_COLLECTIVE_KIND_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|collective-permute"
    r"|all-to-all)(-start|-done)?\(")
_HLO_COMMENT_RE = re.compile(r"/\*.*?\*/")

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16,
}

# jax lowers through Shardy (`sdy.sharding = #sdy.sharding<@mesh,
# [{"mp"}, {}]>`) on current versions and GSPMD (`mhlo.sharding = "..."`)
# on older ones — accept both
_SHARDING_ATTR_RE = re.compile(
    r'sdy\.sharding\s*=\s*#sdy\.sharding<@[\w.]+,\s*(\[[^>]*\])>'
    r'|mhlo\.sharding\s*=\s*"([^"]+)"')


def _shape_bytes(shapes_str, largest_only=False):
    """Elements/bytes across the result shapes of one HLO op.

    largest_only: async `-start` ops carry tuple shapes of
    (operand(s), result(s)[, context buffers]) — summing every component
    would double-count the transfer, so only the largest component (the
    payload) is charged."""
    per_shape = []
    for dtype, dims in _SHAPE_RE.findall(shapes_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        per_shape.append((n, n * _DTYPE_BYTES.get(dtype, 4)))
    if not per_shape:
        return 0, 0
    if largest_only:
        return max(per_shape, key=lambda x: x[1])
    return (sum(e for e, _ in per_shape), sum(b for _, b in per_shape))


def collective_report(compiled_text: str) -> dict:
    """Parse optimized HLO for the collectives GSPMD inserted.

    Returns {"ops": [{kind, elems, bytes}...], "totals": {kind: bytes},
    "total_bytes": int}. `-start`/`-done` async pairs are counted once,
    on the start, charging only the largest tuple component (payload
    approximation — the start tuple aliases operand+result+context)."""
    ops = []
    totals = collections.defaultdict(int)
    for line in compiled_text.splitlines():
        m = _COLLECTIVE_KIND_RE.search(line)
        if m is None:
            continue
        kind, phase = m.group(1), m.group(2)
        if phase == "-done":
            continue
        eq = line.find("=")
        if eq < 0 or eq > m.start():
            continue  # an operand reference, not a defining instruction
        # result shapes sit between the `=` and the op name; long tuples
        # carry `/*index=N*/` comments that must be stripped before the
        # shape scan (r5 fix: the old regex stopped at the first `=` and
        # silently dropped every bundled multi-operand collective — the
        # grad all-reduce is exactly such a bundle)
        shapes_str = _HLO_COMMENT_RE.sub("", line[eq + 1:m.start()])
        elems, bytes_ = _shape_bytes(shapes_str,
                                     largest_only=phase == "-start")
        ops.append({"kind": kind, "elems": elems, "bytes": bytes_})
        totals[kind] += bytes_
    return {"ops": ops, "totals": dict(totals),
            "total_bytes": sum(totals.values())}


# post-propagation sharding attrs in optimized HLO: `sharding={devices=
# [2,1,4]<=[8]}`, `sharding={replicated}`, …
_HLO_SHARDING_RE = re.compile(r"\bsharding=\{([^}]+)\}")


def sharding_report(stablehlo_text: str, compiled_text: str = "") -> dict:
    """Summarize sharding annotations.

    From the LOWERED StableHLO: the framework's own input annotations
    (in_shardings / with_sharding_constraint) — what the planner asked
    for. From the COMPILED HLO (pass `compiled_text`): the shardings the
    partitioner actually assigned after propagation — the completion
    ground truth. Returns {"by_spec", "n_annotated", "propagated_by_spec",
    "n_propagated"}."""
    counts = collections.Counter(
        a or b for a, b in _SHARDING_ATTR_RE.findall(stablehlo_text))
    prop = collections.Counter(_HLO_SHARDING_RE.findall(compiled_text))
    return {"by_spec": dict(counts), "n_annotated": sum(counts.values()),
            "propagated_by_spec": dict(prop),
            "n_propagated": sum(prop.values())}


def analyze(step, *batch) -> dict:
    """Completion + reshard ground truth for a DistributedTrainStep.

    Lowers (and XLA-compiles) the step for `batch` and returns
    {"shardings": sharding_report, "collectives": collective_report,
     "mesh": axis sizes}."""
    lowered = step.lower(*batch)
    compiled = lowered.compile()
    compiled_text = compiled.as_text()
    shard = sharding_report(lowered.as_text(), compiled_text)
    coll = collective_report(compiled_text)
    mesh = dict(step.topo.spmd_mesh.shape)
    return {"mesh": mesh, "shardings": shard, "collectives": coll}


def format_report(report: dict) -> str:
    """Human-readable dump (Engine.analyze(verbose=True))."""
    lines = [f"mesh: {report['mesh']}"]
    sh = report["shardings"]
    lines.append(f"requested sharding annotations: {sh['n_annotated']}")
    for spec, n in sorted(sh["by_spec"].items(), key=lambda x: -x[1]):
        lines.append(f"  {n:5d} x {spec}")
    if sh.get("n_propagated"):
        lines.append(
            f"compiler-propagated shardings: {sh['n_propagated']}")
        for spec, n in sorted(sh["propagated_by_spec"].items(),
                              key=lambda x: -x[1])[:8]:
            lines.append(f"  {n:5d} x {{{spec}}}")
    co = report["collectives"]
    lines.append(
        f"collectives inserted: {len(co['ops'])} "
        f"({co['total_bytes'] / 2**20:.1f} MiB total)")
    for kind, b in sorted(co["totals"].items(), key=lambda x: -x[1]):
        n = sum(1 for o in co["ops"] if o["kind"] == kind)
        lines.append(f"  {kind}: {n} ops, {b / 2**20:.1f} MiB")
    return "\n".join(lines)
