"""Collective communication API.

Role parity: `paddle.distributed.{all_reduce,all_gather,...}`
(`python/paddle/distributed/communication/`) over ProcessGroup
(`paddle/fluid/distributed/collective/process_group.h:47`).

TPU-first semantics (SURVEY §5 backend note): there is one backend — XLA
collectives over ICI/DCN. A "group" is a mesh axis. Two operating modes:

* **SPMD (inside jit/shard_map)** — the functions lower to `lax.psum` /
  `all_gather` / `ppermute` / `all_to_all` on the named axis: this is the
  performance path, the analog of collective ops compiled into the program.
* **Eager (single-controller)** — the input Tensor holds a global jax.Array
  (possibly sharded over the group axis); the collective is executed as a
  tiny shard_map program over the topology mesh. This gives ProcessGroup-
  style imperative collectives without NCCL ring management; `Task.wait`
  becomes jax's async dispatch (returned arrays are futures already).
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import flags
from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..observability import metrics as _metrics
from ..observability import trace as _trace
from ..resilience import faults as _faults
from . import topology as topo_mod

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "all_reduce", "all_gather",
    "all_gather_object", "reduce", "reduce_scatter", "alltoall",
    "alltoall_single", "broadcast", "scatter", "send", "recv", "isend",
    "irecv", "barrier", "stream",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group = a mesh axis of the hybrid topology."""

    def __init__(self, axis="dp", topo=None, name=None):
        self.axis = axis
        self._topo = topo
        self.name = name or f"group_{axis}"

    @property
    def topo(self):
        return self._topo or topo_mod.get_topology()

    @property
    def mesh(self):
        return self.topo.spmd_mesh

    def get_world_size(self):
        return int(self.mesh.shape[self.axis])

    @property
    def nranks(self):
        return self.get_world_size()

    def get_rank(self):
        # single-controller: the calling process sees all shards; axis index
        # is only meaningful inside shard_map (lax.axis_index)
        return 0

    @property
    def rank(self):
        return self.get_rank()

    def get_group_rank(self, rank):
        return rank

    def __repr__(self):
        return f"<Group axis={self.axis} size={self.get_world_size()}>"


_groups = {}


def new_group(ranks=None, backend=None, timeout=None, axis="dp"):
    g = Group(axis=axis)
    _groups[g.name] = g
    return g


def get_group(gid=None):
    return Group("dp")


def _default_group(group):
    return group if group is not None else Group("dp")


def _in_spmd():
    """True when called inside shard_map/jit tracing with named axes bound."""
    try:
        import jax.core as jcore

        frame = jcore.get_axis_env() if hasattr(jcore, "get_axis_env") else None
    except Exception:
        frame = None
    try:
        # jax>=0.4: axis names visible via jax.interpreters context
        from jax._src.core import trace_ctx

        return bool(getattr(trace_ctx, "axis_env", None) and
                    trace_ctx.axis_env.axis_sizes)
    except Exception:
        return False


def _axis_bound(axis):
    try:
        jax.lax.axis_index(axis)  # cheap probe: raises if not bound
        return True
    except Exception:
        return False


def _collective_retry():
    """Retry policy for eager collectives: a host-dispatched collective
    that dies on a transient fault (tunnel drop, preempted slice,
    injected collective.call) is re-issued with backoff before the
    error surfaces — "retry then raise" (EQuARX-class collective
    faults, ISSUE 3).  PADDLE_TPU_COLLECTIVE_RETRIES tunes attempts."""
    from ..resilience.retry import env_policy

    return env_policy(
        "collective", "PADDLE_TPU_COLLECTIVE_RETRIES", 3,
        base_delay=0.02, max_delay=0.5,
        # shape/dtype/spec mistakes are deterministic — only
        # runtime-class failures (infra, injected) are transient
        give_up_on=(TypeError, ValueError, KeyError, AttributeError,
                    IndexError))


def _eager_collective(name, x, group, per_shard_fn, out_sharding_spec=None):
    """Run `per_shard_fn` under shard_map over the group axis."""
    g = _default_group(group)
    mesh = g.mesh
    axis = g.axis
    val = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    try:  # jax>=0.5 exports shard_map at top level
        from jax import shard_map
    except ImportError:  # jax 0.4.x: experimental namespace
        from jax.experimental.shard_map import shard_map

    in_spec = _infer_spec(val, mesh, axis)
    out_spec = out_sharding_spec if out_sharding_spec is not None else in_spec

    try:
        fn = shard_map(per_shard_fn, mesh=mesh, in_specs=(in_spec,),
                       out_specs=out_spec, check_vma=False)
    except TypeError:  # jax 0.4.x spells the replication check check_rep
        fn = shard_map(per_shard_fn, mesh=mesh, in_specs=(in_spec,),
                       out_specs=out_spec, check_rep=False)
    # span wrapper (timeline correlation): the eager collective is a
    # host-dispatched program, so its wall is a real slice on the trace;
    # the SPMD path compiles into the surrounding program and is covered
    # by that program's compile span instead
    with _trace.span(name, cat="collective", axis=axis,
                     shape=list(getattr(val, "shape", ()))):
        def _dispatch():
            # fault point INSIDE the retried callable: an armed
            # collective.call rule with times=N fails the first N
            # dispatches, then the retry succeeds — exactly the
            # transient-fault shape the policy exists for
            _faults.fire("collective.call", op=name, axis=axis)
            return apply(name, fn,
                         x if isinstance(x, Tensor) else Tensor(val))

        return _collective_retry().call(_dispatch)


def _infer_spec(val, mesh, axis):
    """Sharding spec of val w.r.t. mesh: preserve existing sharding if the
    array is placed on this mesh, else treat as replicated."""
    sh = getattr(val, "sharding", None)
    if isinstance(sh, NamedSharding) and sh.mesh.shape == mesh.shape:
        return sh.spec
    return P()


def _resolve_precision(op, precision):
    """The EQuARX tier applies to additive reductions only (sum/avg —
    the gradient-sync ops); max/min/prod stay exact.  Resolution
    happens per call so the env knob can flip between eager steps.
    Validation runs for EVERY op — a typo'd tier on a max/min sync must
    fail loudly, not silently run exact."""
    from . import quantized as _quantized

    prec = _quantized.collective_precision(precision)
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        return None
    return prec


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               precision=None):
    """All-reduce over the group axis.  ``precision`` (or the
    ``PADDLE_TPU_COLLECTIVE_PRECISION`` env knob) selects the quantized
    wire tier for sum/avg: per-chunk-scaled int8 (int32-accumulated) or
    bf16 payloads — docs/SHARDING.md "Precision knob"."""
    _metrics.inc("collective.calls", kind="all_reduce")
    g = _default_group(group)
    axis = g.axis
    prec = _resolve_precision(op, precision)
    if prec is not None:
        from . import quantized as _quantized

        val = tensor._value if isinstance(tensor, Tensor) else tensor
        if _quantized._quantizable(val):
            # count only payloads that actually ride the lossy codec —
            # integer syncs reduce exactly (quantized._quantizable)
            _metrics.inc("collective.quantized", kind="all_reduce",
                         precision=prec)

        def red_q(v, a):
            out = _quantized.psum(v, a, prec)
            if op == ReduceOp.AVG:
                out = out / g.get_world_size()
            return out

    if flags.in_trace():
        # SPMD path: lower directly to the named-axis collective
        red = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin,
               "avg": lambda v, a: jax.lax.pmean(v, a)}[op]
        if prec is not None:
            red = red_q
        out = apply("all_reduce", lambda v: red(v, axis), tensor)
        tensor._rebind(out) if isinstance(tensor, Tensor) else None
        return tensor

    def body(v):
        red = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin,
               "avg": lambda t, a: jax.lax.pmean(t, a),
               "prod": lambda t, a: jnp.exp(jax.lax.psum(jnp.log(t), a))}[op]
        if prec is not None:
            red = red_q
        return red(v, axis)

    out = _eager_collective("all_reduce", tensor, g, body)
    if isinstance(tensor, Tensor):
        tensor._rebind(out)
        return tensor
    return out


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    _metrics.inc("collective.calls", kind="all_gather")
    g = _default_group(group)
    ax = g.axis

    def body(v):
        return jax.lax.all_gather(v, ax)

    if flags.in_trace():
        out = apply("all_gather", body, tensor)
    else:
        out = _eager_collective("all_gather", tensor, g, body,
                                out_sharding_spec=P())
    if tensor_list is not None:
        n = g.get_world_size()
        for i in range(n):
            tensor_list.append(out[i])
        return tensor_list
    return out


def all_gather_object(object_list, obj, group=None):
    # single-controller: every "rank" sees the same object
    g = _default_group(group)
    object_list.extend([obj] * g.get_world_size())
    return object_list


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # on ICI a reduce is an all_reduce whose non-root results are ignored
    return all_reduce(tensor, op, group, sync_op)


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True, precision=None):
    """Reduce-scatter over the group axis (the ZeRO-1 grad-sync shape:
    every replica receives its 1/N summed slice, moving 1/N the bytes an
    all-reduce would).  ``precision`` / the env knob select the
    quantized wire tier — chunks are laid out per destination slice so
    each replica dequantizes its slice with pmax-shared scales."""
    _metrics.inc("collective.calls", kind="reduce_scatter")
    g = _default_group(group)
    ax = g.axis
    # the quantized tier applies to SUM only here: this function's
    # non-sum ops have always reduced as SUM (pre-existing psum_scatter
    # semantics), and the knob must never make AVG/MAX behave
    # differently from the exact path
    prec = _resolve_precision(op, precision)
    if op != ReduceOp.SUM:
        prec = None
    if prec is not None:
        from . import quantized as _quantized

        src0 = tensor_or_tensor_list
        if isinstance(src0, (list, tuple)):
            src0 = src0[0]
        if _quantized._quantizable(
                src0._value if isinstance(src0, Tensor) else src0):
            _metrics.inc("collective.quantized", kind="reduce_scatter",
                         precision=prec)
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        from .. import ops

        src = ops.concat(list(src), axis=0)

    def body(v):
        if prec is not None:
            return _quantized.psum_scatter(v, ax, g.get_world_size(), prec)
        return jax.lax.psum_scatter(v, ax, scatter_dimension=0, tiled=True)

    if flags.in_trace():
        out = apply("reduce_scatter", body, src)
    else:
        out = _eager_collective("reduce_scatter", src, g, body,
                                out_sharding_spec=P(ax))
    if isinstance(tensor, Tensor):
        tensor._rebind(out)
        return tensor
    return out


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    _metrics.inc("collective.calls", kind="alltoall")
    g = _default_group(group)
    ax = g.axis
    from .. import ops

    stacked = ops.stack(list(in_tensor_list), axis=0)

    def body(v):
        # v: [world, ...local] per shard -> exchange leading dim
        return jax.lax.all_to_all(v, ax, split_axis=0, concat_axis=0,
                                  tiled=False)

    if flags.in_trace():
        out = apply("alltoall", body, stacked)
    else:
        out = _eager_collective("alltoall", stacked, g, body)
    n = g.get_world_size()
    if out_tensor_list is not None:
        for i in range(n):
            out_tensor_list.append(out[i])
        return out_tensor_list
    return out


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    _metrics.inc("collective.calls", kind="alltoall_single")
    g = _default_group(group)
    ax = g.axis

    def body(v):
        return jax.lax.all_to_all(v, ax, split_axis=0, concat_axis=0,
                                  tiled=True)

    if flags.in_trace():
        out = apply("alltoall_single", body, in_tensor)
    else:
        out = _eager_collective("alltoall_single", in_tensor, g, body)
    if isinstance(out_tensor, Tensor):
        out_tensor._rebind(out)
        return out_tensor
    return out


def broadcast(tensor, src=0, group=None, sync_op=True):
    # single-controller: values are already consistent; inside shard_map the
    # source shard's value is selected
    _metrics.inc("collective.calls", kind="broadcast")
    g = _default_group(group)
    ax = g.axis
    if flags.in_trace() or _axis_bound(ax):
        def body(v):
            return jax.lax.all_gather(v, ax)[src]

        out = apply("broadcast", body, tensor)
        if isinstance(tensor, Tensor):
            tensor._rebind(out)
            return tensor
        return out
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = _default_group(group)
    if tensor_list is not None:
        # single-controller eager: take the src rank's piece for this process
        tensor._rebind(tensor_list[src] if isinstance(tensor, Tensor)
                       else tensor)
    return tensor


def send(tensor, dst=0, group=None, sync_op=True):
    """Point-to-point on TPU = ppermute along the pp/mesh axis; outside SPMD
    tracing this is the pipeline runner's device_put (see parallel/pipeline)."""
    _metrics.inc("collective.calls", kind="send")
    g = _default_group(group)
    if flags.in_trace():
        ax = g.axis
        n = g.get_world_size()
        perm = [(i, (i + 1) % n) for i in range(n)]
        return apply("send", lambda v: jax.lax.ppermute(v, ax, perm), tensor)
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    return tensor


isend = send
irecv = recv


def barrier(group=None):
    _metrics.inc("collective.calls", kind="barrier")
    for d in jax.local_devices():
        try:
            jax.device_put(0, d).block_until_ready()
        except Exception:
            _metrics.inc("collective.barrier_sync_errors")


class stream:
    """paddle.distributed.stream.* parity: on TPU the compiler owns streams,
    so these are the same collectives (kept for API compatibility)."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(alltoall)
    broadcast = staticmethod(broadcast)
    send = staticmethod(send)
    recv = staticmethod(recv)
