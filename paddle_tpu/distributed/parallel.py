"""Data-parallel entry points (parity: `python/paddle/distributed/parallel.py`
— init_parallel_env :943, DataParallel :202).

TPU-first: on the single-controller runtime, DataParallel's bucketed
EagerReducer is unnecessary — the compiled train step syncs grads via
compiler-inserted all-reduce (see train_step.py). The eager wrapper keeps the
reference API (no_sync, scale_loss) and performs mesh-based grad averaging
when parameters hold dp-sharded grads.
"""
from __future__ import annotations

import contextlib

import jax

from ..nn.layer_base import Layer
from . import topology as topo_mod
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401


def init_parallel_env():
    """Initialize the distributed runtime. Multi-host: jax.distributed is
    initialized from env (coordination service = the TCPStore role)."""
    import os

    if "PADDLE_MASTER" in os.environ or "COORDINATOR_ADDRESS" in os.environ:
        addr = os.environ.get("COORDINATOR_ADDRESS",
                              os.environ.get("PADDLE_MASTER"))
        try:
            jax.distributed.initialize(
                coordinator_address=addr,
                num_processes=int(os.environ.get("PADDLE_TRAINERS_NUM", 1)),
                process_id=int(os.environ.get("PADDLE_TRAINER_ID", 0)))
        except RuntimeError as e:
            # re-init in the same process is fine (jax 0.9 raises
            # "distributed.initialize should only be called once.");
            # anything else (bad coordinator, rank clash, timeout) must
            # surface — silently proceeding single-process would train
            # on 1/N of the data
            msg = str(e).lower()
            if "already" not in msg and "only be called once" not in msg:
                raise
    topo_mod.get_topology()
    return ParallelEnv()


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self._grad_sync_enabled = True

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        old = self._grad_sync_enabled
        self._grad_sync_enabled = False
        try:
            yield
        finally:
            self._grad_sync_enabled = old

    def scale_loss(self, loss):
        return loss

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__.get("_sub_layers", {}).get(
                "_layers") or object.__getattribute__(self, "_layers"), name)
