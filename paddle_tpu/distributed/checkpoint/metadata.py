"""Checkpoint metadata (parity: `python/paddle/distributed/checkpoint/
metadata.py` — global shape/placement records enabling reshard-on-load).

Integrity format (v2, docs/RESILIENCE.md): every storage entry carries a
per-shard CRC32 (`crc32` over the raw shard bytes, computed as the bytes
stream to disk) which the loader verifies before handing data to the
resharder — bit-rot, torn writes, and truncation surface as
`CheckpointCorruptionError` instead of silently-wrong weights.  v1
checkpoints (no `crc32` key) still load; they simply skip verification.
"""
from __future__ import annotations

import dataclasses
import zlib
from typing import Tuple

# v1: no integrity records. v2: per-shard crc32 in storage_metadata.
METADATA_VERSION = 2


class CheckpointCorruptionError(RuntimeError):
    """A shard's stored bytes fail integrity verification (CRC mismatch
    or byte-range truncation).  Recovery path: CheckpointManager falls
    back to the previous checkpoint in the rotation."""

    def __init__(self, message, key=None, file=None):
        self.key = key
        self.file = file
        super().__init__(message)


def shard_checksum(raw: bytes, running: int = 0) -> int:
    """CRC32 of one shard's raw bytes (chainable via `running` so the
    writer checksums as it streams)."""
    return zlib.crc32(raw, running) & 0xFFFFFFFF


@dataclasses.dataclass(frozen=True)
class LocalTensorMetadata:
    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str


@dataclasses.dataclass(frozen=True)
class LocalTensorIndex:
    tensor_key: str
    global_offset: Tuple[int, ...]


@dataclasses.dataclass
class Metadata:
    state_dict_metadata: dict = dataclasses.field(default_factory=dict)
    storage_metadata: dict = dataclasses.field(default_factory=dict)
    flat_mapping: dict = dataclasses.field(default_factory=dict)
    version: int = METADATA_VERSION
