"""Checkpoint metadata (parity: `python/paddle/distributed/checkpoint/
metadata.py` — global shape/placement records enabling reshard-on-load)."""
from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class LocalTensorMetadata:
    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str


@dataclasses.dataclass(frozen=True)
class LocalTensorIndex:
    tensor_key: str
    global_offset: Tuple[int, ...]


@dataclasses.dataclass
class Metadata:
    state_dict_metadata: dict = dataclasses.field(default_factory=dict)
    storage_metadata: dict = dataclasses.field(default_factory=dict)
    flat_mapping: dict = dataclasses.field(default_factory=dict)
