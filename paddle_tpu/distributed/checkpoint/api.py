"""Distributed checkpoint save/load with reshard-on-load.

Role parity: `python/paddle/distributed/checkpoint/save_state_dict.py:104` /
`load_state_dict.py:65` — every rank writes its local shards + merged
metadata; load reshards arbitrary source↔target placements, reading only
the saved shards that intersect each target shard (the reference's
point-to-point load model, `load_state_dict.py:65 get_rank_to_files`).

TPU-first: on the single-controller runtime each *host process* writes the
shards it owns (addressable shards of the global jax.Array) as raw bytes at
recorded offsets in one `.distcp` file; metadata records (global shape,
per-shard offsets, byte ranges). Load never materializes a full global
tensor for sharded targets: `jax.make_array_from_callback` asks for each
target device's block and the loader assembles just that block from the
intersecting saved byte ranges. dtypes round-trip bit-exactly (bfloat16 is
read back via ml_dtypes, never via a float32 detour).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

import jax

from ...core.tensor import Tensor
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata

# introspection for tests: peak block size (elements) assembled by the last
# load, and which keys fell back to full-tensor materialization
last_load_stats = {"max_block_elems": 0, "full_materialized": []}


def _proc_id():
    try:
        return jax.process_index()
    except Exception:
        return 0


def _np_dtype(name):
    """Resolve a dtype string to numpy, via ml_dtypes for bf16/fp8 names."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


_async_save_thread = None


def _snapshot_host(state_dict):
    """Device→host snapshot: list of (key, global_shape, dtype_str,
    [(offset, np_array), ...]) with replicated shards deduped (reference
    dedups replicated tensors across dp, save_state_dict.py:76)."""
    snap = []
    for key, t in state_dict.items():
        v = t._value if isinstance(t, Tensor) else t
        if not hasattr(v, "addressable_shards"):
            import jax.numpy as jnp

            v = jnp.asarray(v)
        shards = []
        seen_offsets = set()
        for sh in v.addressable_shards:
            offset = tuple(
                int(idx.start) if idx.start is not None else 0
                for idx in sh.index) if sh.index else (0,) * v.ndim
            if offset in seen_offsets:
                continue
            seen_offsets.add(offset)
            shards.append((offset, np.asarray(sh.data)))
        snap.append((key, tuple(v.shape), str(v.dtype), shards))
    return snap


def _write_snapshot(snap, path, pid, coordinator_rank):
    meta = Metadata()
    fname = f"{pid}.distcp"
    pos = 0
    with open(os.path.join(path, fname), "wb") as f:
        for key, gshape, dtype_str, shards in snap:
            entries = []
            for offset, arr in shards:
                raw = arr.tobytes()
                f.write(raw)
                entries.append(LocalTensorMetadata(
                    offset, tuple(arr.shape), dtype_str))
                meta.storage_metadata[LocalTensorIndex(key, offset)] = {
                    "file": fname, "byte_offset": pos, "nbytes": len(raw),
                }
                pos += len(raw)
            meta.state_dict_metadata[key] = {
                "global_shape": gshape,
                "dtype": dtype_str,
                "shards": entries,
            }
    if pid == coordinator_rank:
        with open(os.path.join(path, f"{pid}.metadata"), "wb") as f:
            pickle.dump(meta, f, protocol=4)


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    """Write each process's addressable shards + metadata.

    `async_save=True` (reference async-save semantics, SURVEY §5
    checkpoint row): the device→host copy happens synchronously — the
    snapshot is consistent even if training immediately mutates/donates
    the state — then file IO runs on a background thread. Overlapping
    saves are serialized; `wait_async_save()` is the completion barrier
    (also called automatically by the next save/load).
    """
    os.makedirs(path, exist_ok=True)
    pid = _proc_id()
    wait_async_save()  # serialize with any in-flight save
    snap = _snapshot_host(state_dict)
    if async_save:
        global _async_save_thread
        import threading

        _async_save_thread = threading.Thread(
            target=_write_snapshot, args=(snap, path, pid, coordinator_rank),
            daemon=False, name="distcp-async-save")
        _async_save_thread.start()
        return
    _write_snapshot(snap, path, pid, coordinator_rank)


def wait_async_save():
    """Block until the last `save_state_dict(..., async_save=True)` has
    fully hit disk (completion barrier; no-op when nothing is in flight)."""
    global _async_save_thread
    t = _async_save_thread
    if t is not None:
        t.join()
        _async_save_thread = None


def _load_metadata(path):
    metas = []
    for name in sorted(os.listdir(path)):
        if name.endswith(".metadata"):
            with open(os.path.join(path, name), "rb") as f:
                metas.append(pickle.load(f))
    if not metas:
        return None
    # multi-host: coordinator wrote one file; merge defensively if several
    meta = metas[0]
    for extra in metas[1:]:
        meta.state_dict_metadata.update(extra.state_dict_metadata)
        meta.storage_metadata.update(extra.storage_metadata)
    return meta


class _ShardReader:
    """Reads saved shard byte-ranges on demand; caches open file handles,
    never whole files."""

    def __init__(self, path, meta):
        self.path = path
        self.meta = meta
        self._files = {}

    def read(self, key, entry):
        loc = self.meta.storage_metadata.get(
            LocalTensorIndex(key, tuple(entry.global_offset)))
        if loc is None:
            return None
        if isinstance(loc, str):  # legacy layout: whole-file pickle
            cached = self._files.get(("pickle", loc))
            if cached is None:
                with open(os.path.join(self.path, loc), "rb") as f:
                    cached = pickle.load(f)
                self._files[("pickle", loc)] = cached
            return cached[
                f"{key}@{'_'.join(map(str, entry.global_offset))}"]
        f = self._files.get(loc["file"])
        if f is None:
            f = open(os.path.join(self.path, loc["file"]), "rb")
            self._files[loc["file"]] = f
        f.seek(loc["byte_offset"])
        raw = f.read(loc["nbytes"])
        dt = _np_dtype(entry.dtype)
        return np.frombuffer(raw, dtype=dt).reshape(entry.local_shape)

    def close(self):
        for f in self._files.values():
            if hasattr(f, "close"):
                f.close()
        self._files.clear()


def _assemble_block(key, info, reader, block_index):
    """Assemble one target block (tuple of slices into the global tensor)
    from the saved shards that intersect it."""
    gshape = info["global_shape"]
    dt = _np_dtype(info["dtype"])
    starts = [s.start or 0 for s in block_index]
    stops = [s.stop if s.stop is not None else dim
             for s, dim in zip(block_index, gshape)]
    bshape = tuple(b - a for a, b in zip(starts, stops))
    if not bshape:  # scalar
        entry = info["shards"][0]
        return reader.read(key, entry).reshape(())
    # zeros, not empty: a region no readable shard covers (missing file,
    # stale metadata) must not surface uninitialized memory as weights
    block = np.zeros(bshape, dtype=dt)
    last_load_stats["max_block_elems"] = max(
        last_load_stats["max_block_elems"], int(np.prod(bshape) or 1))
    for entry in info["shards"]:
        e_lo = list(entry.global_offset)
        e_hi = [o + s for o, s in zip(entry.global_offset, entry.local_shape)]
        lo = [max(a, b) for a, b in zip(starts, e_lo)]
        hi = [min(a, b) for a, b in zip(stops, e_hi)]
        if any(a >= b for a, b in zip(lo, hi)):
            continue
        src = reader.read(key, entry)
        if src is None:
            continue
        src_sl = tuple(slice(a - o, b - o)
                       for a, b, o in zip(lo, hi, e_lo))
        dst_sl = tuple(slice(a - s, b - s)
                       for a, b, s in zip(lo, hi, starts))
        block[dst_sl] = src[src_sl]
    return block


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None,
                    offload=False):
    """Fill `state_dict`'s tensors in-place from the checkpoint, resharding
    from the saved partitioning to each target tensor's current sharding.

    Sharded targets are assembled block-by-block via
    `jax.make_array_from_callback` — no full global tensor is ever
    materialized on the host for them (scales to multi-B-param states).
    """
    wait_async_save()  # a just-issued async save of `path` must land first
    meta = _load_metadata(path)
    assert meta is not None, f"no metadata found under {path}"
    last_load_stats["max_block_elems"] = 0
    last_load_stats["full_materialized"] = []
    reader = _ShardReader(path, meta)
    try:
        for key, t in state_dict.items():
            if key not in meta.state_dict_metadata:
                continue
            info = meta.state_dict_metadata[key]
            gshape = tuple(info["global_shape"])
            dt = _np_dtype(info["dtype"])
            if not isinstance(t, Tensor):
                continue
            tgt_sharding = getattr(t._value, "sharding", None)
            is_sharded = (
                tgt_sharding is not None
                and hasattr(tgt_sharding, "is_fully_replicated")
                and not tgt_sharding.is_fully_replicated
                and gshape != ())
            if is_sharded:
                t._value = jax.make_array_from_callback(
                    gshape, tgt_sharding,
                    lambda idx, _k=key, _i=info: np.ascontiguousarray(
                        _assemble_block(_k, _i, reader, idx)).astype(
                            dt, copy=False))
                continue
            # replicated / unsharded target: the full array IS the target
            full = _assemble_block(
                key, info, reader, tuple(slice(0, d) for d in gshape))
            last_load_stats["full_materialized"].append(key)
            import jax.numpy as jnp

            val = jnp.asarray(full, dtype=dt)
            if tgt_sharding is not None:
                try:
                    val = jax.device_put(val, tgt_sharding)
                except Exception:
                    pass
            t._value = val
    finally:
        reader.close()
    return state_dict
