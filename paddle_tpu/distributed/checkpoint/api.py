"""Distributed checkpoint save/load with reshard-on-load.

Role parity: `python/paddle/distributed/checkpoint/save_state_dict.py:104` /
`load_state_dict.py:65` — every rank writes its local shards + merged
metadata; load reshards arbitrary source↔target placements.

TPU-first: on the single-controller runtime each *host process* writes the
shards it owns (addressable shards of the global jax.Array); metadata records
(global shape, per-shard offsets). Load assembles requested shards from any
saved partitioning and `device_put`s them under the target sharding — the
reshard engine role falls out of global-view arrays. Multi-host: each process
writes only its addressable shards, so the directory aggregates the full
state exactly like the reference's per-rank files.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

import jax

from ...core.tensor import Tensor
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata


def _proc_id():
    try:
        return jax.process_index()
    except Exception:
        return 0


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    os.makedirs(path, exist_ok=True)
    pid = _proc_id()
    meta = Metadata()
    shards = {}
    for key, t in state_dict.items():
        v = t._value if isinstance(t, Tensor) else t
        if not hasattr(v, "addressable_shards"):
            import jax.numpy as jnp

            v = jnp.asarray(v)
        entries = []
        seen_offsets = set()
        for sh in v.addressable_shards:
            # dedup replicated shards (reference dedups replicated tensors)
            offset = tuple(
                int(idx.start) if idx.start is not None else 0
                for idx in sh.index) if sh.index else (0,) * v.ndim
            if offset in seen_offsets:
                continue
            seen_offsets.add(offset)
            arr = np.asarray(sh.data)
            storage_key = f"{key}@{'_'.join(map(str, offset))}"
            shards[storage_key] = arr
            entries.append(LocalTensorMetadata(
                offset, tuple(arr.shape), str(v.dtype)))
            meta.storage_metadata[LocalTensorIndex(key, offset)] = \
                f"{pid}.distcp"
        meta.state_dict_metadata[key] = {
            "global_shape": tuple(v.shape),
            "dtype": str(v.dtype),
            "shards": entries,
        }
    with open(os.path.join(path, f"{pid}.distcp"), "wb") as f:
        pickle.dump(shards, f, protocol=4)
    if pid == coordinator_rank:
        with open(os.path.join(path, f"{pid}.metadata"), "wb") as f:
            pickle.dump(meta, f, protocol=4)


def _load_all_shards(path):
    shards = {}
    meta = None
    for name in sorted(os.listdir(path)):
        full = os.path.join(path, name)
        if name.endswith(".distcp"):
            with open(full, "rb") as f:
                shards.update(pickle.load(f))
        elif name.endswith(".metadata"):
            with open(full, "rb") as f:
                meta = pickle.load(f)
    return meta, shards


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None,
                    offload=False):
    """Fill `state_dict`'s tensors in-place from the checkpoint, resharding
    from the saved partitioning to each target tensor's current sharding."""
    meta, shards = _load_all_shards(path)
    assert meta is not None, f"no metadata found under {path}"
    for key, t in state_dict.items():
        if key not in meta.state_dict_metadata:
            continue
        info = meta.state_dict_metadata[key]
        gshape = info["global_shape"]
        full = np.zeros(gshape, dtype=np.dtype(
            info["dtype"].replace("bfloat16", "float32")))
        for entry in info["shards"]:
            skey = f"{key}@{'_'.join(map(str, entry.global_offset))}"
            if skey not in shards:
                continue
            sl = tuple(slice(o, o + s) for o, s in
                       zip(entry.global_offset, entry.local_shape))
            arr = shards[skey]
            if info["dtype"] == "bfloat16":
                arr = arr.astype(np.float32)
            full[sl] = arr
        if isinstance(t, Tensor):
            tgt_sharding = getattr(t._value, "sharding", None)
            import jax.numpy as jnp

            val = jnp.asarray(full, dtype=info["dtype"])
            if tgt_sharding is not None:
                try:
                    val = jax.device_put(val, tgt_sharding)
                except Exception:
                    pass
            t._value = val
    return state_dict
