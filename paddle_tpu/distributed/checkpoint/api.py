"""Distributed checkpoint save/load with reshard-on-load.

Role parity: `python/paddle/distributed/checkpoint/save_state_dict.py:104` /
`load_state_dict.py:65` — every rank writes its local shards + merged
metadata; load reshards arbitrary source↔target placements, reading only
the saved shards that intersect each target shard (the reference's
point-to-point load model, `load_state_dict.py:65 get_rank_to_files`).

TPU-first: on the single-controller runtime each *host process* writes the
shards it owns (addressable shards of the global jax.Array) as raw bytes at
recorded offsets in one `.distcp` file; metadata records (global shape,
per-shard offsets, byte ranges). Load never materializes a full global
tensor for sharded targets: `jax.make_array_from_callback` asks for each
target device's block and the loader assembles just that block from the
intersecting saved byte ranges. dtypes round-trip bit-exactly (bfloat16 is
read back via ml_dtypes, never via a float32 detour).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

import jax

from ...core.tensor import Tensor
from .metadata import (
    CheckpointCorruptionError, LocalTensorIndex, LocalTensorMetadata,
    Metadata, shard_checksum,
)

# introspection for tests: peak block size (elements) assembled by the last
# load, and which keys fell back to full-tensor materialization
last_load_stats = {"max_block_elems": 0, "full_materialized": []}


def _proc_id():
    try:
        return jax.process_index()
    except Exception:
        return 0


def _np_dtype(name):
    """Resolve a dtype string to numpy, via ml_dtypes for bf16/fp8 names."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


_async_save_thread = None
_async_save_error = None  # exception raised inside the async save thread


def _fire_fault(point, **ctx):
    """Resilience fault-point hook (None when the harness is idle)."""
    try:
        from ...resilience import faults as _faults
    except ImportError:
        return None
    return _faults.fire(point, **ctx)


def _fsync_and_rename(tmp_path, final_path):
    """Commit one file atomically: the tmp is already fsync'd; rename
    over the final name, then fsync the directory so the rename itself
    is durable."""
    os.rename(tmp_path, final_path)
    try:
        dfd = os.open(os.path.dirname(final_path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # directory fsync is best-effort (not all filesystems)


def _snapshot_host(state_dict):
    """Device→host snapshot: list of (key, global_shape, dtype_str,
    [(offset, np_array), ...]) with replicated shards deduped (reference
    dedups replicated tensors across dp, save_state_dict.py:76)."""
    snap = []
    for key, t in state_dict.items():
        v = t._value if isinstance(t, Tensor) else t
        if not hasattr(v, "addressable_shards"):
            import jax.numpy as jnp

            v = jnp.asarray(v)
        shards = []
        seen_offsets = set()
        for sh in v.addressable_shards:
            offset = tuple(
                int(idx.start) if idx.start is not None else 0
                for idx in sh.index) if sh.index else (0,) * v.ndim
            if offset in seen_offsets:
                continue
            seen_offsets.add(offset)
            shards.append((offset, np.asarray(sh.data)))
        snap.append((key, tuple(v.shape), str(v.dtype), shards))
    return snap


def _write_snapshot(snap, path, pid, coordinator_rank):
    """Atomic, checksummed write of one process's shards + metadata.

    Torn-write hardening (docs/RESILIENCE.md): all bytes land in
    `*.tmp` files that are fsync'd then renamed into place; the
    metadata file is committed LAST, so a kill at any point leaves
    either the complete previous checkpoint or a loadable new one —
    never a half-written state a loader would trust.  Each stored
    byte-range records its CRC32 for verification on load.
    """
    action = _fire_fault("checkpoint.write", path=path, pid=pid)
    meta = Metadata()
    fname = f"{pid}.distcp"
    tmp_data = os.path.join(path, fname + ".tmp")
    total = sum(arr.nbytes for _k, _g, _d, shards in snap
                for _o, arr in shards)
    pos = 0
    with open(tmp_data, "wb") as f:
        for key, gshape, dtype_str, shards in snap:
            entries = []
            for offset, arr in shards:
                raw = arr.tobytes()
                if action is not None and action.kind == "torn" and \
                        pos + len(raw) > total // 2:
                    # simulated mid-write kill: half the bytes are down,
                    # no rename, no metadata — the previous checkpoint
                    # must remain the loadable one
                    f.write(raw[:max(1, len(raw) // 2)])
                    f.flush()
                    from ...resilience.faults import InjectedFault

                    raise InjectedFault("checkpoint.write", kind="torn",
                                        call=action.call, file=fname)
                f.write(raw)
                entries.append(LocalTensorMetadata(
                    offset, tuple(arr.shape), dtype_str))
                meta.storage_metadata[LocalTensorIndex(key, offset)] = {
                    "file": fname, "byte_offset": pos, "nbytes": len(raw),
                    "crc32": shard_checksum(raw),
                }
                pos += len(raw)
            meta.state_dict_metadata[key] = {
                "global_shape": gshape,
                "dtype": dtype_str,
                "shards": entries,
            }
        f.flush()
        os.fsync(f.fileno())
    _fsync_and_rename(tmp_data, os.path.join(path, fname))
    if action is not None and action.kind == "corrupt":
        # simulated bit-rot AFTER a clean commit: the CRCs recorded in
        # the metadata no longer match the bytes on disk
        from ...resilience.faults import corrupt_file

        corrupt_file(os.path.join(path, fname),
                     seed=action.payload.get("seed", 0))
    if pid == coordinator_rank:
        tmp_meta = os.path.join(path, f"{pid}.metadata.tmp")
        with open(tmp_meta, "wb") as f:
            pickle.dump(meta, f, protocol=4)
            f.flush()
            os.fsync(f.fileno())
        _fsync_and_rename(tmp_meta, os.path.join(path, f"{pid}.metadata"))


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    """Write each process's addressable shards + metadata.

    `async_save=True` (reference async-save semantics, SURVEY §5
    checkpoint row): the device→host copy happens synchronously — the
    snapshot is consistent even if training immediately mutates/donates
    the state — then file IO runs on a background thread. Overlapping
    saves are serialized; `wait_async_save()` is the completion barrier
    (also called automatically by the next save/load).
    """
    os.makedirs(path, exist_ok=True)
    pid = _proc_id()
    wait_async_save()  # serialize with (and surface errors from) any
    # in-flight save
    snap = _snapshot_host(state_dict)
    if async_save:
        global _async_save_thread
        import threading

        def _run():
            global _async_save_error
            try:
                _write_snapshot(snap, path, pid, coordinator_rank)
            except BaseException as e:  # captured, re-raised on wait
                _async_save_error = e

        _async_save_thread = threading.Thread(
            target=_run, daemon=False, name="distcp-async-save")
        _async_save_thread.start()
        _register_atexit_join()
        return
    _write_snapshot(snap, path, pid, coordinator_rank)


def wait_async_save():
    """Block until the last `save_state_dict(..., async_save=True)` has
    fully hit disk (completion barrier; no-op when nothing is in flight).

    An exception raised inside the save thread is captured there and
    RE-RAISED here — the first save/load/wait after the failure sees it
    (a silently-lost async checkpoint is a checkpoint you discover is
    missing only when restoring from a crash)."""
    global _async_save_thread, _async_save_error
    t = _async_save_thread
    if t is not None:
        t.join()
        _async_save_thread = None
    err = _async_save_error
    if err is not None:
        _async_save_error = None
        raise err


_atexit_registered = False


def _register_atexit_join():
    """Join a still-running async save at interpreter exit (a clean
    process teardown must not truncate a checkpoint mid-write); a
    captured failure is reported, not raised (atexit can't propagate)."""
    global _atexit_registered
    if _atexit_registered:
        return
    _atexit_registered = True
    import atexit

    def _drain():
        global _async_save_thread, _async_save_error
        t = _async_save_thread
        if t is not None:
            t.join()
            _async_save_thread = None
        if _async_save_error is not None:
            import sys

            print(f"[checkpoint] async save failed: "
                  f"{type(_async_save_error).__name__}: "
                  f"{_async_save_error}", file=sys.stderr)
            _async_save_error = None

    atexit.register(_drain)


def _load_metadata(path):
    metas = []
    for name in sorted(os.listdir(path)):
        if name.endswith(".metadata"):
            with open(os.path.join(path, name), "rb") as f:
                metas.append(pickle.load(f))
    if not metas:
        return None
    # multi-host: coordinator wrote one file; merge defensively if several
    meta = metas[0]
    for extra in metas[1:]:
        meta.state_dict_metadata.update(extra.state_dict_metadata)
        meta.storage_metadata.update(extra.storage_metadata)
    return meta


class _ShardReader:
    """Reads saved shard byte-ranges on demand; caches open file handles,
    never whole files."""

    def __init__(self, path, meta):
        self.path = path
        self.meta = meta
        self._files = {}

    def read(self, key, entry):
        loc = self.meta.storage_metadata.get(
            LocalTensorIndex(key, tuple(entry.global_offset)))
        if loc is None:
            return None
        if isinstance(loc, str):  # legacy layout: whole-file pickle
            cached = self._files.get(("pickle", loc))
            if cached is None:
                with open(os.path.join(self.path, loc), "rb") as f:
                    cached = pickle.load(f)
                self._files[("pickle", loc)] = cached
            return cached[
                f"{key}@{'_'.join(map(str, entry.global_offset))}"]
        f = self._files.get(loc["file"])
        if f is None:
            f = open(os.path.join(self.path, loc["file"]), "rb")
            self._files[loc["file"]] = f
        f.seek(loc["byte_offset"])
        raw = f.read(loc["nbytes"])
        if len(raw) != loc["nbytes"]:
            raise CheckpointCorruptionError(
                f"checkpoint shard {key!r}@{entry.global_offset} in "
                f"{loc['file']} truncated: wanted {loc['nbytes']} bytes, "
                f"got {len(raw)}", key=key, file=loc["file"])
        want = loc.get("crc32")
        if want is not None and shard_checksum(raw) != want:
            raise CheckpointCorruptionError(
                f"checkpoint shard {key!r}@{entry.global_offset} in "
                f"{loc['file']} failed CRC32 verification (stored "
                f"{want:#010x}, computed {shard_checksum(raw):#010x})",
                key=key, file=loc["file"])
        dt = _np_dtype(entry.dtype)
        return np.frombuffer(raw, dtype=dt).reshape(entry.local_shape)

    def close(self):
        for f in self._files.values():
            if hasattr(f, "close"):
                f.close()
        self._files.clear()


def _assemble_block(key, info, reader, block_index):
    """Assemble one target block (tuple of slices into the global tensor)
    from the saved shards that intersect it."""
    gshape = info["global_shape"]
    dt = _np_dtype(info["dtype"])
    starts = [s.start or 0 for s in block_index]
    stops = [s.stop if s.stop is not None else dim
             for s, dim in zip(block_index, gshape)]
    bshape = tuple(b - a for a, b in zip(starts, stops))
    if not bshape:  # scalar
        entry = info["shards"][0]
        return reader.read(key, entry).reshape(())
    # zeros, not empty: a region no readable shard covers (missing file,
    # stale metadata) must not surface uninitialized memory as weights
    block = np.zeros(bshape, dtype=dt)
    last_load_stats["max_block_elems"] = max(
        last_load_stats["max_block_elems"], int(np.prod(bshape) or 1))
    for entry in info["shards"]:
        e_lo = list(entry.global_offset)
        e_hi = [o + s for o, s in zip(entry.global_offset, entry.local_shape)]
        lo = [max(a, b) for a, b in zip(starts, e_lo)]
        hi = [min(a, b) for a, b in zip(stops, e_hi)]
        if any(a >= b for a, b in zip(lo, hi)):
            continue
        src = reader.read(key, entry)
        if src is None:
            continue
        src_sl = tuple(slice(a - o, b - o)
                       for a, b, o in zip(lo, hi, e_lo))
        dst_sl = tuple(slice(a - s, b - s)
                       for a, b, s in zip(lo, hi, starts))
        block[dst_sl] = src[src_sl]
    return block


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None,
                    offload=False):
    """Fill `state_dict`'s tensors in-place from the checkpoint, resharding
    from the saved partitioning to each target tensor's current sharding.

    Sharded targets are assembled block-by-block via
    `jax.make_array_from_callback` — no full global tensor is ever
    materialized on the host for them (scales to multi-B-param states).
    """
    wait_async_save()  # a just-issued async save of `path` must land first
    meta = _load_metadata(path)
    assert meta is not None, f"no metadata found under {path}"
    last_load_stats["max_block_elems"] = 0
    last_load_stats["full_materialized"] = []
    reader = _ShardReader(path, meta)
    try:
        for key, t in state_dict.items():
            if key not in meta.state_dict_metadata:
                continue
            info = meta.state_dict_metadata[key]
            gshape = tuple(info["global_shape"])
            dt = _np_dtype(info["dtype"])
            if not isinstance(t, Tensor):
                continue
            tgt_sharding = getattr(t._value, "sharding", None)
            is_sharded = (
                tgt_sharding is not None
                and hasattr(tgt_sharding, "is_fully_replicated")
                and not tgt_sharding.is_fully_replicated
                and gshape != ())
            if is_sharded:
                t._value = _owned_copy(jax.make_array_from_callback(
                    gshape, tgt_sharding,
                    lambda idx, _k=key, _i=info: np.ascontiguousarray(
                        _assemble_block(_k, _i, reader, idx)).astype(
                            dt, copy=False)))
                continue
            # replicated / unsharded target: the full array IS the target
            full = _assemble_block(
                key, info, reader, tuple(slice(0, d) for d in gshape))
            last_load_stats["full_materialized"].append(key)
            import jax.numpy as jnp

            val = jnp.asarray(full, dtype=dt)
            if tgt_sharding is not None:
                try:
                    val = jax.device_put(val, tgt_sharding)
                except Exception as e:
                    # the value is still correct, just not laid out on
                    # the target sharding — a silent fallback here
                    # becomes an OOM or a cross-host transfer storm at
                    # first use, so leave a flight breadcrumb
                    try:
                        from ...observability import flight as _flight

                        _flight.record(
                            "checkpoint.resharding_failed", key=key,
                            error=f"{type(e).__name__}: {e}")
                    except Exception:  # pt-lint: ok[PT005]
                        pass           # (observability fan-out guard)
            t._value = _owned_copy(val)
    finally:
        reader.close()
    return state_dict


# one jit object: executables cache per (shape, dtype, sharding) inside
_owned_copy_jit = jax.jit(lambda a: jax.lax.optimization_barrier(a))


def _owned_copy(val):
    """An XLA-owned, bit-exact copy of `val`, preserving its sharding.

    jax/jaxlib 0.4.3x on CPU zero-copy *adopts* suitably-aligned host
    numpy buffers in `device_put`/`make_array_from_callback`.  DONATING
    such an adopted buffer into a compiled program makes XLA free/reuse
    memory it does not own — glibc heap corruption (`corrupted
    double-linked list`, random segfaults) in exactly the restore flow:
    load a checkpoint, then dispatch the already-compiled donated train
    step.  (The init path never hits it: its state is built from jax
    arrays, which device_put copies on device.)  Routing every loaded
    leaf through a real computation forces an XLA-allocated result
    buffer; `optimization_barrier` is the one identity the algebraic
    simplifier will not fold away into a pass-through alias, and it is
    bit-exact for every dtype."""
    return _owned_copy_jit(val)


def verify_checkpoint(path, deep=True):
    """Integrity-check the checkpoint at `path`.

    deep=True (tools/tests): read every stored byte range and check it
    against its recorded CRC32 — full bit-rot detection without
    materializing tensors.  deep=False (the restore hot path): only
    structural checks — metadata present, shard files exist, every
    recorded byte range fits the file — leaving CRC verification to the
    shard reader, which checksums each range as it streams it anyway
    (so a restore pays ONE read+CRC pass, not two).

    Returns {"files", "shards", "bytes", "unverified"} on success
    (`unverified` counts v1 entries with no CRC); raises
    `CheckpointCorruptionError` on any failure.
    """
    meta = _load_metadata(path)
    if meta is None:
        raise CheckpointCorruptionError(
            f"no checkpoint metadata found under {path!r}")
    files, shards, nbytes, unverified = set(), 0, 0, 0
    handles = {}
    sizes = {}
    try:
        for idx, loc in meta.storage_metadata.items():
            if isinstance(loc, str):  # legacy whole-file pickle layout
                unverified += 1
                continue
            fpath = os.path.join(path, loc["file"])
            if deep:
                f = handles.get(fpath)
                if f is None:
                    try:
                        f = handles[fpath] = open(fpath, "rb")
                    except OSError as e:
                        raise CheckpointCorruptionError(
                            f"checkpoint shard file {loc['file']!r} missing "
                            f"under {path!r}: {e}", key=idx.tensor_key,
                            file=loc["file"]) from e
                f.seek(loc["byte_offset"])
                raw = f.read(loc["nbytes"])
                if len(raw) != loc["nbytes"]:
                    raise CheckpointCorruptionError(
                        f"shard {idx.tensor_key!r}@{idx.global_offset} "
                        f"truncated in {loc['file']}", key=idx.tensor_key,
                        file=loc["file"])
                want = loc.get("crc32")
                if want is None:
                    unverified += 1
                elif shard_checksum(raw) != want:
                    raise CheckpointCorruptionError(
                        f"shard {idx.tensor_key!r}@{idx.global_offset} "
                        f"failed CRC32 in {loc['file']}",
                        key=idx.tensor_key, file=loc["file"])
            else:
                size = sizes.get(fpath)
                if size is None:
                    try:
                        size = sizes[fpath] = os.path.getsize(fpath)
                    except OSError as e:
                        raise CheckpointCorruptionError(
                            f"checkpoint shard file {loc['file']!r} missing "
                            f"under {path!r}: {e}", key=idx.tensor_key,
                            file=loc["file"]) from e
                if loc["byte_offset"] + loc["nbytes"] > size:
                    raise CheckpointCorruptionError(
                        f"shard {idx.tensor_key!r}@{idx.global_offset} "
                        f"extends past {loc['file']} ({size} bytes)",
                        key=idx.tensor_key, file=loc["file"])
                if loc.get("crc32") is None:
                    unverified += 1
            files.add(loc["file"])
            shards += 1
            nbytes += loc["nbytes"]
    finally:
        for f in handles.values():
            f.close()
    return {"files": len(files), "shards": shards, "bytes": nbytes,
            "unverified": unverified}


class CheckpointManager:
    """Keep-last-K checkpoint rotation with a `latest` pointer and
    verify-then-rollback restore — the recovery target the guard
    escalation and the elastic restart path load through.

    Layout under `root`:
        ckpt_00000007/          one save_state_dict checkpoint each
        ckpt_00000008/
        latest                  text file: basename of the newest commit
    Saves are atomic end-to-end (shard files and metadata commit via
    tmp+fsync+rename inside save_state_dict; the pointer file commits
    the same way).  With `async_save=True` the pointer is written
    optimistically before the background write lands — safe because
    `latest_step()`/`restore()` only ever trust COMMITTED checkpoints
    (metadata present, CRCs verified) and fall back otherwise.
    Pruning keeps the newest `keep_last_k` directories,
    and `restore()` walks newest → oldest, quarantining any checkpoint
    that fails CRC verification (renamed to `<dir>.corrupt`) until one
    verifies — a torn/corrupted latest falls back to the previous one
    instead of killing the run.
    """

    LATEST = "latest"

    def __init__(self, root, keep_last_k=3):
        self.root = str(root)
        self.keep_last_k = max(1, int(keep_last_k))
        self._inflight_step = None  # step a possibly-async save targets
        os.makedirs(self.root, exist_ok=True)

    # --- naming -------------------------------------------------------------
    def _dir(self, step):
        return os.path.join(self.root, f"ckpt_{int(step):08d}")

    def _step_of(self, name):
        try:
            return int(name.split("_", 1)[1])
        except (IndexError, ValueError):
            return None

    def checkpoints(self):
        """Committed checkpoint steps, oldest → newest (a checkpoint is
        committed iff its metadata file exists)."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            step = None
            if name.startswith("ckpt_") and not name.endswith(".corrupt"):
                step = self._step_of(name)
            if step is None:
                continue
            d = os.path.join(self.root, name)
            if any(n.endswith(".metadata") for n in
                   (os.listdir(d) if os.path.isdir(d) else ())):
                out.append(step)
        return sorted(out)

    def _committed(self, step):
        d = self._dir(step)
        return os.path.isdir(d) and any(
            n.endswith(".metadata") for n in os.listdir(d))

    def latest_step(self):
        """The step the `latest` pointer names — but only if that
        checkpoint is COMMITTED (metadata present).  The pointer is
        written optimistically before an async save lands, so a pointer
        to a not-yet/never-committed dir (crash mid-async-write) falls
        back to the newest committed checkpoint instead of handing a
        torn directory to an elastic restart."""
        p = os.path.join(self.root, self.LATEST)
        try:
            with open(p) as f:
                step = self._step_of(f.read().strip())
            if step is not None and self._committed(step):
                return step
        except OSError:
            pass
        steps = self.checkpoints()
        return steps[-1] if steps else None

    def latest_path(self):
        step = self.latest_step()
        return None if step is None else self._dir(step)

    # --- save ---------------------------------------------------------------
    def save(self, state_dict, step=None, async_save=False):
        """Write checkpoint `step` (default: newest+1), move the
        `latest` pointer, prune beyond keep_last_k.  Returns the
        checkpoint directory path."""
        if step is None:
            # join any in-flight async save FIRST: its metadata commit
            # is what makes its step visible to checkpoints(), and
            # without it back-to-back async saves would both pick the
            # same step and overwrite each other
            wait_async_save()
            steps = self.checkpoints()
            step = (steps[-1] + 1) if steps else 0
        path = self._dir(step)
        self._inflight_step = int(step)  # prune must never touch it
        save_state_dict(state_dict, path, async_save=async_save)
        self._commit_pointer(path)
        self.prune()
        return path

    def _commit_pointer(self, path):
        tmp = os.path.join(self.root, self.LATEST + ".tmp")
        with open(tmp, "w") as f:
            f.write(os.path.basename(path) + "\n")
            f.flush()
            os.fsync(f.fileno())
        _fsync_and_rename(tmp, os.path.join(self.root, self.LATEST))

    def prune(self):
        """Drop committed checkpoints beyond the newest keep_last_k
        (never the one `latest` points at), plus dead torn-save litter:
        uncommitted ckpt dirs OLDER than the newest commit can never be
        finished (only the newest save may still be landing async), so
        they are removed instead of leaking one per mid-write kill.
        Quarantined `.corrupt` dirs are kept — they are evidence."""
        import shutil

        steps = self.checkpoints()
        keep = set(steps[-self.keep_last_k:])
        latest = self.latest_step()
        if latest is not None:
            keep.add(latest)
        for s in steps:
            if s not in keep:
                shutil.rmtree(self._dir(s), ignore_errors=True)
        newest = steps[-1] if steps else None
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for name in names:
            if not name.startswith("ckpt_") or name.endswith(".corrupt"):
                continue
            s = self._step_of(name)
            if s is None or s in steps or s == self._inflight_step:
                # _inflight_step may still be landing on the async
                # writer thread (an explicit step below the newest
                # commit is legal) — never rmtree under it
                continue
            if newest is not None and s < newest:
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    # --- restore ------------------------------------------------------------
    def restore(self, state_dict):
        """Fill `state_dict` from the newest checkpoint that passes CRC
        verification (checked shard-by-shard as the load streams),
        quarantining failed ones and rolling back to the previous —
        raises CheckpointCorruptionError only when NO checkpoint in the
        rotation survives.  Returns the step loaded.  A corrupt attempt
        may partially fill `state_dict` before the fallback load
        rewrites it; rotation checkpoints of one run share a key set,
        so the successful load overwrites every touched leaf."""
        try:
            wait_async_save()  # an in-flight save must land first...
        except Exception as e:
            # ...but a FAILED async save must not block recovery: the
            # whole point of restore() is falling back to the last
            # committed checkpoint.  The failure is recorded, consumed,
            # and the rotation walk below decides what is loadable.
            try:
                from ...observability import flight as _flight

                _flight.record("resilience.async_save_error_at_restore",
                               error=f"{type(e).__name__}: {e}")
            except Exception:  # pt-lint: ok[PT005]
                pass           # (observability fan-out guard)
        steps = self.checkpoints()
        latest = self.latest_step()
        if latest in steps:  # pointer order wins, then newest-first
            steps = [s for s in steps if s != latest] + [latest]
        if not steps:
            raise CheckpointCorruptionError(
                f"no committed checkpoints under {self.root!r}")
        last_err = None
        for step in reversed(steps):
            path = self._dir(step)
            try:
                # structural gate only — the shard reader CRC-verifies
                # every byte range as the load streams it, so recovery
                # pays one read pass, not verify+load double I/O
                verify_checkpoint(path, deep=False)
                load_state_dict(state_dict, path)
                return step
            except CheckpointCorruptionError as e:
                last_err = e
                self._quarantine(path, e)
        raise CheckpointCorruptionError(
            f"every checkpoint under {self.root!r} failed verification "
            f"(last: {last_err})") from last_err

    def _quarantine(self, path, err):
        """Move a corrupt checkpoint aside (evidence, and so the next
        restore doesn't re-verify it) and record the rollback."""
        try:
            os.rename(path, path + ".corrupt")
        except OSError:
            pass
        try:
            from ...observability import flight as _flight
            from ...observability import metrics as _metrics

            _metrics.inc("resilience.rollbacks")
            _flight.record("resilience.checkpoint_rollback", path=path,
                           error=f"{type(err).__name__}: {err}")
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard)
