from .api import load_state_dict, save_state_dict, wait_async_save  # noqa: F401
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata  # noqa: F401
