from .api import (  # noqa: F401
    CheckpointManager, load_state_dict, save_state_dict, verify_checkpoint,
    wait_async_save,
)
from .metadata import (  # noqa: F401
    CheckpointCorruptionError, LocalTensorIndex, LocalTensorMetadata,
    Metadata, shard_checksum,
)
