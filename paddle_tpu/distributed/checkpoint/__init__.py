from .api import load_state_dict, save_state_dict  # noqa: F401
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata  # noqa: F401
