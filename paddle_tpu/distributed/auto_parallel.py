"""Semi-auto parallel API: shard_tensor / reshard / shard_layer / ProcessMesh.

Role parity: `python/paddle/distributed/auto_parallel/api.py:118,288,387,716`
and the C++ DistTensor + reshard engine
(`paddle/phi/core/distributed/auto_parallel/dist_tensor.h:39`, reshard fns).

TPU-first collapse: DistTensor ≡ a jax.Array with a NamedSharding; the SPMD
rule registry and the pairwise reshard functions (r_to_s, s_to_r, p_to_r, …)
are XLA's sharding propagation + `jax.device_put`/`with_sharding_constraint`;
`Partial` state exists transiently inside compiled programs and is
materialized by psum on output — so the user-facing API keeps the reference's
Placement vocabulary while the compiler does the work.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import flags
from ..core.tensor import Tensor

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "dtensor_from_fn", "reshard", "shard_layer", "shard_optimizer",
           "get_mesh", "set_mesh"]


class Placement:
    pass


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def is_replicated(self):
        return False

    def is_partial(self):
        return False

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Replicate(Placement):
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return True

    def is_partial(self):
        return False

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")


class Partial(Placement):
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """N-D logical device mesh (parity: auto_parallel/process_mesh.py:71).
    Wraps a jax.sharding.Mesh; `dim_names` are the sharding axis names."""

    def __init__(self, mesh=None, dim_names=None, shape=None,
                 process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.asarray(process_ids).reshape(shape)
        self._ids = arr
        self.shape = list(arr.shape)
        self.dim_names = list(dim_names) if dim_names else \
            [f"d{i}" for i in range(arr.ndim)]
        devices = np.array(jax.devices())
        flat = arr.reshape(-1)
        dev = np.empty(flat.shape, dtype=object)
        for i, pid in enumerate(flat):
            dev[i] = devices[int(pid) % len(devices)]
        self.jax_mesh = Mesh(dev.reshape(arr.shape), tuple(self.dim_names))

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    @property
    def mesh(self):
        return self._ids

    def get_dim_size(self, name):
        return self.shape[self.dim_names.index(name)]

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            np.array_equal(self._ids, other._ids) and \
            self.dim_names == other.dim_names

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


_global_mesh = None


def set_mesh(mesh):
    global _global_mesh
    _global_mesh = mesh


def get_mesh():
    return _global_mesh


def _placements_to_spec(placements, ndim, mesh):
    spec = [None] * ndim
    for axis_idx, pl in enumerate(placements):
        if isinstance(pl, Shard):
            name = mesh.dim_names[axis_idx]
            if spec[pl.dim] is None:
                spec[pl.dim] = name
            elif isinstance(spec[pl.dim], tuple):
                spec[pl.dim] = spec[pl.dim] + (name,)
            else:
                spec[pl.dim] = (spec[pl.dim], name)
    return P(*spec)


def shard_tensor(data, mesh, placements, dtype=None, place=None,
                 stop_gradient=None):
    """Place a tensor on the mesh with the given per-mesh-dim placements."""
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    spec = _placements_to_spec(placements, t.ndim, mesh)
    sharding = NamedSharding(mesh.jax_mesh, spec)
    if flags.in_trace():
        val = jax.lax.with_sharding_constraint(t._value, sharding)
        out = Tensor(val, stop_gradient=t.stop_gradient)
    else:
        val = jax.device_put(t._value, sharding)
        out = Tensor(val, stop_gradient=t.stop_gradient
                     if stop_gradient is None else stop_gradient)
        out._grad_node = t._grad_node
    out.dist_attr = (mesh, tuple(placements))
    out.name = t.name
    return out


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(dist_tensor, mesh, placements):
    """Convert between placements (the reshard engine role): on TPU this is a
    device_put (eager) or sharding constraint (traced) — XLA inserts the
    collectives (all_gather for s→r, dynamic-slice for r→s, psum for p→r)."""
    return shard_tensor(dist_tensor, mesh, placements)


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """Apply `shard_fn(name, layer, mesh)` over sublayers to annotate/place
    params (parity: auto_parallel/api.py:387)."""
    if shard_fn is None:
        def shard_fn(name, l, mesh):
            for pname, p in l._parameters.items():
                if p is not None:
                    placements = [Replicate() for _ in mesh.shape]
                    sharded = shard_tensor(p, mesh, placements)
                    p._value = sharded._value
                    p.dist_attr = sharded.dist_attr

    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inp: input_fn(inp, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inp, out: output_fn(out, process_mesh))
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    """Mark the optimizer for state sharding; the train-step builder reads
    this to shard accumulator pytrees (ZeRO recipes live in
    distributed.sharding)."""
    optimizer._shard_fn = shard_fn or True
    return optimizer
