"""paddle_tpu.distributed.rpc: simple worker-to-worker RPC.

Role parity: `paddle.distributed.rpc` (`python/paddle/distributed/rpc/
rpc.py` over brpc, SURVEY §2.2) — init_rpc/rpc_sync/rpc_async/
get_worker_info/shutdown.

Transport: one daemon TCP server thread per worker; worker name→endpoint
registry rides the job's TCPStore (the same rendezvous the collectives
use). Payloads are pickled callables+args, exactly the reference's trust
model: RPC peers are inside one training job's trust domain — do NOT
expose the port beyond the cluster network.
"""
from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import threading

from concurrent.futures import Future

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "get_worker_info",
           "get_all_worker_infos", "get_current_worker_info", "shutdown",
           "WorkerInfo"]


class WorkerInfo:
    def __init__(self, name, rank, ip, port):
        self.name = name
        self.rank = rank
        self.ip = ip
        self.port = port

    def __repr__(self):
        return (f"WorkerInfo(name={self.name}, rank={self.rank}, "
                f"ip={self.ip}, port={self.port})")


_state = {"server": None, "store": None, "me": None, "world_size": 0,
          "workers": {}}


def _send_msg(sock, payload):
    data = pickle.dumps(payload)
    sock.sendall(struct.pack("!Q", len(data)) + data)


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = struct.unpack("!Q", hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return pickle.loads(buf)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            fn, args, kwargs = _recv_msg(self.request)
        except ConnectionError:
            return
        try:
            payload = ("ok", fn(*args, **kwargs))
        except Exception as e:  # ship the exception back
            payload = ("err", e)
        try:
            _send_msg(self.request, payload)
        except ConnectionError:
            pass
        except Exception as e:
            # result/exception not picklable — tell the caller WHY instead
            # of dropping the connection
            try:
                _send_msg(self.request, ("err", RuntimeError(
                    f"rpc reply not picklable: {e!r}")))
            except Exception:
                # the connection died under us too — count it so a
                # flapping peer shows up in the metrics snapshot
                from ..observability import metrics as _metrics

                _metrics.inc("rpc.reply_errors")


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this worker's RPC server and register it."""
    from .store import TCPStore

    rank = rank if rank is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", 0))
    world_size = world_size or int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    master = master_endpoint or os.environ.get("PADDLE_MASTER",
                                               "127.0.0.1:8476")
    host, port = master.split(":")
    store = TCPStore(host, int(port), is_master=(rank == 0))

    # bind only the advertised interface — the handler executes unpickled
    # callables, so don't widen the trust domain beyond the job's network
    my_ip = os.environ.get("PADDLE_LOCAL_IP", "127.0.0.1")
    server = _Server((my_ip, 0), _Handler)
    my_port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    store.set(f"rpc/{rank}", f"{name},{my_ip},{my_port}")

    workers = {}
    for r in range(world_size):
        val = store.get(f"rpc/{r}", timeout=60)
        if isinstance(val, bytes):
            val = val.decode()
        wname, ip, p = val.split(",")
        workers[wname] = WorkerInfo(wname, r, ip, int(p))

    _state.update(server=server, store=store, me=workers_by_rank(workers,
                                                                 rank),
                  world_size=world_size, workers=workers)
    return _state["me"]


def workers_by_rank(workers, rank):
    for w in workers.values():
        if w.rank == rank:
            return w
    raise KeyError(rank)


def get_worker_info(name):
    return _state["workers"][name]


def get_all_worker_infos():
    return list(_state["workers"].values())


def get_current_worker_info():
    return _state["me"]


def rpc_sync(to, fn, args=None, kwargs=None, timeout=60):
    return rpc_async(to, fn, args, kwargs, timeout).result(timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=60):
    info = _state["workers"].get(to)
    if info is None:
        raise KeyError(f"unknown rpc worker {to!r}; did you init_rpc?")
    fut = Future()

    def call():
        try:
            with socket.create_connection((info.ip, info.port),
                                          timeout=timeout) as s:
                _send_msg(s, (fn, args or (), kwargs or {}))
                status, payload = _recv_msg(s)
            if status == "ok":
                fut.set_result(payload)
            else:
                fut.set_exception(payload)
        except Exception as e:
            fut.set_exception(e)

    threading.Thread(target=call, daemon=True).start()
    return fut


def shutdown():
    server = _state.get("server")
    if server is not None:
        server.shutdown()
        server.server_close()
    _state.update(server=None, workers={}, me=None)
