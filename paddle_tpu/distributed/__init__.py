"""paddle.distributed parity surface, TPU-native (SURVEY §2.2, §2.5)."""
from . import fleet  # noqa: F401
from .engine import Engine  # noqa: F401
from .auto_parallel import (  # noqa: F401
    Partial, ProcessMesh, Replicate, Shard, dtensor_from_fn, get_mesh,
    reshard, set_mesh, shard_layer, shard_optimizer, shard_tensor,
)
from .collective import (  # noqa: F401
    Group, ReduceOp, all_gather, all_gather_object, all_reduce, alltoall,
    alltoall_single, barrier, broadcast, get_group, irecv, isend, new_group,
    recv, reduce, reduce_scatter, scatter, send, stream,
)
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
from .parallel import DataParallel, init_parallel_env  # noqa: F401
from .recompute import recompute, recompute_sequential  # noqa: F401
from .topology import HybridTopology, get_topology, set_topology  # noqa: F401
from .train_step import DistributedTrainStep  # noqa: F401
from . import checkpoint  # noqa: F401
from . import mpu  # noqa: F401
from . import rpc  # noqa: F401
from .auto_tuner import AutoTuner  # noqa: F401
from .pipeline import LayerDesc, PipelineLayer, PipelineParallel  # noqa: F401


def is_initialized():
    return True


def get_backend():
    return "xla"


def spawn(func, args=(), nprocs=-1, **kwargs):
    """Reference spawn launches one process per device; single-controller jax
    owns all local devices in-process, so spawn degenerates to a direct call
    with rank 0 semantics (multi-host uses the launcher)."""
    return func(*args)
