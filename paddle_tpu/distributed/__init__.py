"""paddle.distributed parity surface, TPU-native (SURVEY §2.2, §2.5)."""
from . import completion  # noqa: F401  (sharding/reshard ground truth)
from . import fleet  # noqa: F401
from .engine import Engine  # noqa: F401
from .auto_parallel import (  # noqa: F401
    Partial, ProcessMesh, Replicate, Shard, dtensor_from_fn, get_mesh,
    reshard, set_mesh, shard_layer, shard_optimizer, shard_tensor,
)
from .collective import (  # noqa: F401
    Group, ReduceOp, all_gather, all_gather_object, all_reduce, alltoall,
    alltoall_single, barrier, broadcast, get_group, irecv, isend, new_group,
    recv, reduce, reduce_scatter, scatter, send, stream,
)
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
from .parallel import DataParallel, init_parallel_env  # noqa: F401
from .recompute import recompute, recompute_sequential  # noqa: F401
from .topology import HybridTopology, get_topology, set_topology  # noqa: F401
from .train_step import DistributedTrainStep  # noqa: F401
from . import checkpoint  # noqa: F401
from . import mpu  # noqa: F401
from . import rpc  # noqa: F401
from .auto_tuner import AutoTuner  # noqa: F401
from .pipeline import LayerDesc, PipelineLayer, PipelineParallel  # noqa: F401
from .pipeline_spmd import spmd_pipeline, stack_stages  # noqa: F401


def is_initialized():
    return True


def get_backend():
    return "xla"


def spawn(func, args=(), nprocs=-1, **kwargs):
    """Reference spawn launches one process per device; single-controller jax
    owns all local devices in-process, so spawn degenerates to a direct call
    with rank 0 semantics (multi-host uses the launcher)."""
    return func(*args)


# ---- reference __all__ completion (python/paddle/distributed/__init__.py)

from .auto_parallel import Placement  # noqa: F401,E402
from .checkpoint import load_state_dict, save_state_dict  # noqa: F401,E402
from . import launch  # noqa: F401,E402
from . import io  # noqa: F401,E402


class ParallelMode:
    """Reference parallel-mode constants (base/topology.py roles)."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class ReduceType:
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4


class DistAttr:
    """Per-tensor distributed attribute (DistTensor's TensorDistAttr
    role): process mesh + per-dim sharding names."""

    def __init__(self, mesh=None, sharding_specs=None):
        self.process_mesh = mesh
        self.sharding_specs = list(sharding_specs or [])

    def __repr__(self):
        return (f"DistAttr(mesh={self.process_mesh}, "
                f"specs={self.sharding_specs})")


def is_available():
    return True


def destroy_process_group(group=None):
    """Tear down collective state (reference destroy_process_group);
    XLA backends hold no persistent communicators — reset the topology."""
    from . import topology as _topo

    _topo.reset_topology()


def wait(tensor, group=None, use_calc_stream=True):
    """Reference wait blocks on a collective's stream; jax arrays expose
    completion directly."""
    v = tensor._value if hasattr(tensor, "_value") else tensor
    try:
        v.block_until_ready()
    except Exception:
        from ..observability import metrics as _metrics

        _metrics.inc("collective.wait_errors")
    return tensor


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Gather to dst (reference communication/gather.py). On the
    single-controller runtime every rank's shard is addressable, so
    gather == all_gather with the result delivered on dst."""
    out = []
    all_gather(out, tensor, group=group, sync_op=sync_op)
    if gather_list is not None:
        gather_list.clear()
        gather_list.extend(out)
    return out


def broadcast_object_list(object_list, src=0, group=None):
    """Python-object broadcast (pickle transport over the collective
    layer; single-controller: objects are already shared)."""
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    rank = get_rank()
    world = max(get_world_size(), 1)
    if in_object_list is None:
        in_object_list = []
    per = max(len(in_object_list) // world, 1) if in_object_list else 0
    out_object_list.clear()
    out_object_list.extend(in_object_list[rank * per:(rank + 1) * per])
    return out_object_list


def gloo_init_parallel_env(rank_id=0, rank_num=1, server_endpoint=None):
    """CPU-rendezvous parity (gloo role): the TCPStore path."""
    from .parallel import init_parallel_env

    return init_parallel_env()


def gloo_barrier():
    return barrier()


def gloo_release():
    return None


def unshard_dtensor(dist_tensor):
    """Gather a sharded DistTensor to a fully-replicated dense tensor
    (reference unshard_dtensor)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from ..core.tensor import Tensor
    from . import topology as _topo

    v = dist_tensor._value if hasattr(dist_tensor, "_value") else dist_tensor
    mesh = _topo.get_topology().spmd_mesh
    out = jax.device_put(v, NamedSharding(mesh, PartitionSpec()))
    return Tensor(out)


def split(x, size, operation, axis=0, num_partitions=1, weight_attr=None,
          bias_attr=None, gather_out=True, name=None):
    """Model-parallel split op (reference distributed/parallel layers
    `paddle.distributed.split`): builds a row/column-parallel linear or
    a vocab-parallel embedding whose weight shard lives on the mp axis.
    Returns the layer's output for input x (mirrors the reference's
    functional use)."""
    from . import mpu

    if operation == "linear":
        in_f, out_f = size
        if axis == 0:  # row parallel (input dim split)
            layer = mpu.RowParallelLinear(in_f, out_f,
                                          input_is_parallel=False)
        else:
            layer = mpu.ColumnParallelLinear(in_f, out_f,
                                             gather_output=gather_out)
        return layer(x)
    if operation == "embedding":
        vocab, hidden = size
        layer = mpu.VocabParallelEmbedding(vocab, hidden)
        return layer(x)
    raise ValueError(f"unsupported split operation {operation!r}")


# PS-era dataset/entry configs: excluded with the parameter-server stack
# (see README "Scope notes") — loud, documented gates.
def _ps_gate(name):
    def ctor(*a, **kw):
        raise NotImplementedError(
            f"{name} belongs to the parameter-server stack, which this "
            "TPU build deliberately excludes (see README Scope notes); "
            "use paddle_tpu.io.Dataset/DataLoader for data input")

    ctor.__name__ = name
    return ctor


QueueDataset = _ps_gate("QueueDataset")
InMemoryDataset = _ps_gate("InMemoryDataset")
CountFilterEntry = _ps_gate("CountFilterEntry")
ShowClickEntry = _ps_gate("ShowClickEntry")
ProbabilityEntry = _ps_gate("ProbabilityEntry")


# auto-parallel static facade (reference auto_parallel/api.py to_static /
# Strategy / DistModel) over the Engine
class Strategy:
    """Auto-parallel strategy (auto_parallel/strategy.py role): bags of
    config for sharding/amp/recompute consumed by to_static/Engine."""

    def __init__(self, config=None):
        config = config or {}
        self.sharding = config.get("sharding", {})
        self.amp = config.get("amp", {})
        self.recompute = config.get("recompute", {})
        self.pipeline = config.get("pipeline", {})
        self.hybrid_configs = config.get("hybrid_configs", None)


class DistModel:
    """Compiled distributed model handle (auto_parallel/api.py DistModel):
    call it to run one train/eval step under the planned strategy."""

    def __init__(self, engine):
        self._engine = engine
        self._mode = "train"

    def train(self):
        self._mode = "train"

    def eval(self):
        self._mode = "eval"

    def __call__(self, *batch):
        if self._mode == "train":
            return self._engine._step(*batch)
        import paddle_tpu as P

        with P.no_grad():
            out = self._engine.model(batch[0])
            if self._engine.loss is not None and len(batch) > 1:
                return self._engine.loss(out, batch[1])
            return out

    def state_dict(self):
        return self._engine.model.state_dict()


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              input_spec=None):
    """Auto-parallel to_static (auto_parallel/api.py:1358): plan +
    compile the distributed training step; returns (DistModel, loader)."""
    from .engine import Engine

    eng = Engine(model=layer, loss=loss, optimizer=optimizer,
                 strategy=getattr(strategy, "hybrid_configs", None))
    # infer global batch from the loader's first element when available
    gb = 32
    if loader is not None:
        try:
            first = next(iter(loader))
            import numpy as _np

            gb = int(_np.shape(first[0])[0])
        except Exception as e:
            from ..observability import flight as _flight

            _flight.record("fleet.global_batch_probe_failed",
                           error=repr(e), fallback=gb)
    eng.prepare(global_batch=gb)
    dm = DistModel(eng)
    return (dm, loader) if loader is not None else dm
