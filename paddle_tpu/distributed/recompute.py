"""Activation recomputation (parity:
`python/paddle/distributed/fleet/recompute/recompute.py:108,404`).

TPU-first: under tracing this is `jax.checkpoint` (XLA rematerialization) —
the compiler replays the segment in backward instead of saving activations;
the reference's RNG-state tracker for deterministic dropout replay is
unnecessary because the PRNG key threading makes dropout functional.
Eagerly it's a pass-through (tape autograd already frees per-op residuals
after backward).
"""
from __future__ import annotations

import jax

from ..core import flags
from ..core.tensor import Tensor

__all__ = ["recompute", "recompute_sequential"]

# Named rematerialization policies (the TPU memory/FLOPs dial — SURVEY §7
# hard part (c)). "full" replays everything in backward (max memory
# savings); "dots" saves every matmul output (min recompute FLOPs);
# "dots_no_batch" saves matmul outputs except batched dots — the
# standard transformer sweet spot: the attention/mlp GEMMs whose
# recompute costs real MXU time are saved, cheap elementwise replays.
_POLICIES = {
    None: None,
    "full": None,
    "dots": "checkpoint_dots",
    "dots_no_batch": "checkpoint_dots_with_no_batch_dims",
}


def _resolve_policy(name):
    if name not in _POLICIES:
        raise ValueError(
            f"recompute policy must be one of {sorted(k for k in _POLICIES if k)}"
            f" or None, got {name!r}")
    attr = _POLICIES[name]
    return getattr(jax.checkpoint_policies, attr) if attr else None


def recompute(function, *args, use_reentrant=True, preserve_rng_state=True,
              policy=None, **kwargs):
    # validate uniformly: a typo'd policy must fail in eager debugging
    # too, not only once the job reaches a traced run
    pol = _resolve_policy(policy)
    if not flags.in_trace():
        return function(*args, **kwargs)

    leaves, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
    tensor_idx = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    vals = [leaves[i]._value for i in tensor_idx]

    def pure(*tvals):
        cur = list(leaves)
        for i, v in zip(tensor_idx, tvals):
            cur[i] = Tensor(v, stop_gradient=False)
        a, kw = jax.tree_util.tree_unflatten(treedef, cur)
        out = function(*a, **kw)
        return jax.tree_util.tree_map(
            lambda o: o._value if isinstance(o, Tensor) else o, out,
            is_leaf=lambda x: isinstance(x, Tensor))

    out_vals = jax.checkpoint(pure, policy=pol)(*vals)
    return jax.tree_util.tree_map(lambda v: Tensor(v), out_vals)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Recompute over a Sequential in `segments` chunks (parity:
    recompute_sequential)."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    sublayers = list(functions) if isinstance(functions, (list, tuple)) else \
        list(functions.children())
    n = len(sublayers)
    seg = max(1, n // max(1, segments))
    out = args[0] if len(args) == 1 else args

    def run_segment(layers):
        def f(x):
            for l in layers:
                x = l(x)
            return x

        return f

    i = 0
    while i < n:
        chunk = sublayers[i:i + seg]
        out = recompute(run_segment(chunk), out)
        i += seg
    return out
