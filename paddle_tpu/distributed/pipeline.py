"""Pipeline parallelism: 1F1B (and F-then-B) over per-stage compiled programs.

Role parity: `PipelineLayer` partitioning (`fleet/meta_parallel/
parallel_layers/pp_layers.py:237`), the 1F1B schedule
(`pipeline_parallel.py:440` forward_backward_pipeline), and P2P
(`pp_utils/p2p_communication.py`) — reimagined for the single-controller
runtime:

* Each stage is a `Sequential` slice compiled (jit) against its own submesh;
  inner (dp, sep, mp) sharding still applies per stage.
* P2P send/recv = `jax.device_put` of the boundary activation onto the next
  stage's submesh — an async ICI transfer; no stream management (the
  reference's SendRecvMeta/batch_isend_irecv machinery is unnecessary
  because dispatch is async and ordered per device).
* The schedule is an ENQUEUE ORDER: devices execute their queues in
  dispatch order, so emitting ops in 1F1B order yields the 1F1B overlap
  without any host-side blocking. Backward recomputes the stage forward
  under `jax.vjp` (activation-checkpoint style), so no residual closures
  cross jit boundaries.
* Gradient accumulation across micro-batches happens on-device per stage;
  the optimizer update runs per stage after the last cooldown backward.

This tier is single-process by construction (a process can only jit onto
devices it owns). The companion `pipeline_spmd.py` is the COLLECTIVE tier:
one jit program over the global mesh, stage shifts via ppermute — it runs
across processes/hosts and composes with dp/mp through partial-manual
shard_map.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import flags
from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..nn.layers_common import Sequential
from ..observability import trace as _trace
from . import topology as topo_mod
from .train_step import param_placements

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer", "PipelineParallel",
           "segment_layers", "interleaved_order", "simulate_makespan",
           "bubble_fraction"]


class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, *args, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


def segment_layers(layers, num_stages, method="uniform"):
    """Partition a flat layer list into stages (seg-method parity:
    uniform / layer / parameter counts, pp_layers.py seg_method)."""
    n = len(layers)
    if method == "parameter":
        weights = [sum(int(np.prod(p.shape)) for p in l.parameters()) or 1
                   for l in layers]
    else:
        weights = [1] * n
    total = sum(weights)
    target = total / num_stages
    bounds = [0]
    acc = 0
    for i, w in enumerate(weights):
        acc += w
        if acc >= target * len(bounds) and len(bounds) < num_stages:
            bounds.append(i + 1)
    while len(bounds) < num_stages:
        bounds.append(n)
    bounds.append(n)
    return [layers[bounds[i]:bounds[i + 1]] for i in range(num_stages)]


def _vpp_microstep(k, pp, v, forward):
    """Map a rank-local micro-step index to (chunk_round, microbatch).

    Megatron interleave pattern: micro-batches advance in groups of ``pp``;
    within a group the rank cycles through its ``v`` chunks (forward in
    ascending chunk-round order, backward descending).
    """
    group, within = divmod(k, pp * v)
    round_ = within // pp
    if not forward:
        round_ = v - 1 - round_
    mb = group * pp + within % pp
    return round_, mb


def interleaved_order(pp, v, m):
    """Global dependency-valid enqueue order for the interleaved (VPP)
    schedule: list of (chunk, 'F'|'B', mb) with chunk ∈ [0, pp*v).

    Per-rank local op sequences follow Megatron's interleaved 1F1B
    (warmup = 2*(pp-1-rank) + (v-1)*pp micro-steps, then steady 1F1B,
    then cooldown); the global order is a greedy linearization that
    respects both the local sequences and cross-chunk data dependencies.
    """
    if v > 1:  # plain 1F1B (v=1) has no divisibility requirement
        assert m % pp == 0, (
            f"interleaved schedule needs micro-batches ({m}) divisible by "
            f"pipeline stages ({pp})")
    n_chunks = pp * v
    total = m * v  # forward micro-steps per rank
    local = []
    for i in range(pp):
        # v=1 degenerates to classic 1F1B warmup; the 2x factor + (v-1)*pp
        # extra in-flight micro-steps are what lets later chunks start
        # before earlier ones drain (Megatron interleave)
        warm = (min(pp - 1 - i, total) if v == 1 else
                min((pp - 1 - i) * 2 + (v - 1) * pp, total))
        seq = [("F", k) for k in range(warm)]
        for j in range(total - warm):
            seq.append(("F", warm + j))
            seq.append(("B", j))
        seq += [("B", j) for j in range(total - warm, total)]
        local.append(seq)

    ptr = [0] * pp
    fdone, bdone = set(), set()  # (chunk, mb)
    order = []
    remaining = pp * total * 2
    while remaining:
        progressed = False
        for i in range(pp):
            if ptr[i] >= len(local[i]):
                continue
            op, k = local[i][ptr[i]]
            fwd = op == "F"
            round_, mb = _vpp_microstep(k, pp, v, fwd)
            c = round_ * pp + i
            if fwd:
                ready = c == 0 or (c - 1, mb) in fdone
            else:
                ready = (c, mb) in fdone and (
                    c == n_chunks - 1 or (c + 1, mb) in bdone)
            if ready:
                order.append((c, op, mb))
                (fdone if fwd else bdone).add((c, mb))
                ptr[i] += 1
                remaining -= 1
                progressed = True
        assert progressed, "interleaved schedule deadlock"
    return order


def simulate_makespan(order, pp, n_chunks, op_cost=1.0):
    """Event-driven makespan of a schedule order (unit-cost chunk ops).

    Each op occupies its physical rank (chunk % pp) for ``op_cost`` and
    may start once its data dependencies finished. Returns the makespan.
    """
    rank_free = [0.0] * pp
    done = {}
    for (c, op, mb) in order:
        i = c % pp
        t = rank_free[i]
        if op == "F":
            if c > 0:
                t = max(t, done[(c - 1, "F", mb)])
        else:
            t = max(t, done[(c, "F", mb)])
            if c < n_chunks - 1:
                t = max(t, done[(c + 1, "B", mb)])
        t += op_cost
        done[(c, op, mb)] = t
        rank_free[i] = t
    return max(rank_free)


def bubble_fraction(pp, m, v=1):
    """Idle fraction of the schedule, with chunk-op cost 1/v so total work
    per rank is constant across v (same model, finer chunks)."""
    if v == 1:
        # plain 1F1B local orders via the same machinery
        order = interleaved_order(pp, 1, m) if m % pp == 0 else None
        assert order is not None
    else:
        order = interleaved_order(pp, v, m)
    cost = 1.0 / v
    span = simulate_makespan(order, pp, pp * v, cost)
    work = 2.0 * m  # per-rank busy time, in full-stage units
    return (span - work) / span


class PipelineLayer(Layer):
    """Holds the full LayerDesc list + stage partition (pp_layers parity).

    With ``num_virtual_pipeline_stages = v > 1`` the model is cut into
    ``num_stages * v`` chunks; physical stage ``i`` owns chunks
    ``{i, i+pp, i+2pp, …}`` (Megatron-style interleaving, reference
    `fleet/meta_parallel/pipeline_parallel.py:906`
    PipelineParallelWithInterleave).
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        topo = topology or topo_mod.get_topology()
        self.num_stages = num_stages or topo.pp_degree
        self.num_virtual_stages = int(num_virtual_pipeline_stages or 1)
        built = [d.build_layer() if isinstance(d, LayerDesc) else d
                 for d in layers]
        self._full_layers = built
        self.loss_fn = loss_fn
        n_chunks = self.num_stages * self.num_virtual_stages
        if n_chunks > len(built):
            raise ValueError(
                f"cannot split {len(built)} layers into {n_chunks} chunks "
                f"(pp={self.num_stages} × vpp={self.num_virtual_stages})")
        stages = segment_layers(built, n_chunks, seg_method)
        self.stages = [Sequential(*s) for s in stages]
        for i, s in enumerate(self.stages):
            self.add_sublayer(f"stage_{i}", s)

    def forward(self, x):
        for s in self.stages:
            x = s(x)
        return x


class _Stage:
    """One pipeline stage: params on its submesh + compiled fwd / fwd-bwd."""

    def __init__(self, module, mesh, is_last, loss_fn):
        self.module = module
        self.mesh = mesh
        self.is_last = is_last
        self.loss_fn = loss_fn
        params, buffers = module.functional_state()
        self.param_specs = {
            n: P(*param_placements(p))
            for n, p in module.named_parameters()}
        self.params = {
            n: jax.device_put(v, NamedSharding(mesh, self.param_specs[n]))
            for n, v in params.items()}
        self.buffers = {n: jax.device_put(v, NamedSharding(mesh, P()))
                        for n, v in buffers.items()}
        self.grads = None
        self._fwd = jax.jit(self._fwd_fn)
        self._fwdbwd = jax.jit(self._fwdbwd_fn)
        self._accum = jax.jit(
            lambda a, b: jax.tree_util.tree_map(jnp.add, a, b))

    # pure stage apply
    def _apply(self, params, x, labels=None):
        with topo_mod.use_spmd_mesh(self.mesh):
            with flags.trace_guard():
                with self.module.bind_state(params, self.buffers):
                    out = self.module(Tensor(x))
                if self.is_last and self.loss_fn is not None:
                    loss = self.loss_fn(out, Tensor(labels))
                    lv = loss._value if isinstance(loss, Tensor) else loss
                    return jnp.mean(lv.astype(jnp.float32))
        return out._value

    def _fwd_fn(self, params, x, labels=None):
        return self._apply(params, x, labels)

    def _fwdbwd_fn(self, params, x, gy, labels=None):
        def f(p, xx):
            return self._apply(p, xx, labels)

        out, vjp = jax.vjp(f, params, x)
        cot = gy if gy is not None else jnp.ones_like(out)
        gparams, gx = vjp(cot)
        return gx, gparams

    def forward(self, x, labels=None):
        return self._fwd(self.params, x, labels)

    def backward(self, x, gy, labels=None):
        gx, gparams = self._fwdbwd(self.params, x, gy, labels)
        if self.grads is None:
            self.grads = gparams
        else:
            self.grads = self._accum(self.grads, gparams)
        return gx

    def to_mesh(self, value):
        """P2P receive: materialize a boundary tensor on this stage's mesh
        (dp-sharded on dim 0 when divisible)."""
        dp = self.mesh.shape.get("dp", 1)
        spec = P("dp") if (np.ndim(value) >= 1 and dp > 1 and
                           value.shape[0] % dp == 0) else P()
        return jax.device_put(value, NamedSharding(self.mesh, spec))


class PipelineParallel:
    """1F1B runner (PipelineParallel.forward_backward_pipeline parity)."""

    def __init__(self, pipeline_layer, optimizer, topo=None,
                 num_micro_batches=None, schedule="1F1B",
                 sharding_stage=0):
        self.topo = topo or topo_mod.get_topology()
        self.pp = self.topo.pp_degree
        self.optimizer = optimizer
        self.schedule = schedule
        # ZeRO-over-dp composed with PP: optimizer slots (stage>=1) are
        # sharded over each stage submesh's dp axis — the PP analog of
        # DygraphShardingOptimizer under PipelineParallel (reference
        # hybrid_parallel_optimizer.py composing with pipeline_parallel.py)
        self.sharding_stage = int(sharding_stage)
        self.num_micro_batches = num_micro_batches or self.pp
        assert isinstance(pipeline_layer, PipelineLayer)
        self.pipe = pipeline_layer
        self.vpp = getattr(pipeline_layer, "num_virtual_stages", 1)
        self.n_chunks = self.pp * self.vpp
        assert len(pipeline_layer.stages) == self.n_chunks, (
            f"PipelineLayer has {len(pipeline_layer.stages)} chunks, "
            f"topology needs pp×vpp = {self.n_chunks}")
        if self.vpp > 1:
            assert self.num_micro_batches % self.pp == 0, (
                "interleaved (VPP) schedule needs num_micro_batches "
                f"({self.num_micro_batches}) divisible by pp ({self.pp})")
        self.loss_fn = pipeline_layer.loss_fn
        # chunk c lives on physical stage c % pp (interleaved assignment)
        self.stages = [
            _Stage(pipeline_layer.stages[c], self.topo.stage_mesh(c % self.pp),
                   c == self.n_chunks - 1, self.loss_fn)
            for c in range(self.n_chunks)
        ]
        self._opt_states = None
        self._opt_update = None

    # --- optimizer state per stage ------------------------------------------
    def _ensure_opt(self):
        if self._opt_states is not None:
            return
        from .train_step import _zero_shard_spec

        self._opt_states = []
        self._opt_update = []
        for st in self.stages:
            state = self.optimizer.init_state(st.params)
            slot_shardings = None
            if self.sharding_stage >= 1:
                dp = st.mesh.shape.get("dp", 1)
                slot_shardings = {}
                for n, sd in state["slots"].items():
                    base = tuple(st.param_specs[n])
                    specs = {}
                    for k, v in sd.items():
                        spec = (_zero_shard_spec(base, np.shape(v), dp, None)
                                if np.ndim(v) else ())
                        specs[k] = NamedSharding(st.mesh, P(*spec))
                    slot_shardings[n] = specs
                state["slots"] = {
                    n: {k: jax.device_put(v, slot_shardings[n][k])
                        for k, v in sd.items()}
                    for n, sd in state["slots"].items()}
            self._opt_states.append(state)

            def upd(p, g, s, lr, _o=self.optimizer, _sh=slot_shardings,
                    _ps={n: NamedSharding(st.mesh, sp)
                         for n, sp in st.param_specs.items()}):
                new_p, new_s = _o.apply_gradients(p, g, s, lr)
                if _sh is not None:  # pin ZeRO partitioning across steps
                    new_p = {n: jax.lax.with_sharding_constraint(v, _ps[n])
                             for n, v in new_p.items()}
                    new_s = dict(new_s, slots={
                        n: {k: jax.lax.with_sharding_constraint(v, _sh[n][k])
                            for k, v in sd.items()}
                        for n, sd in new_s["slots"].items()})
                return new_p, new_s

            self._opt_update.append(jax.jit(upd))

    def _schedule_1f1b(self, m):
        """Yield (stage, 'F'|'B', mb) in a dependency-valid 1F1B enqueue
        order (pipeline_scheduler_pass 1F1B program order)."""
        pp = self.pp
        local = []
        for i in range(pp):
            warm = min(pp - 1 - i, m)
            seq = ["F"] * warm
            for _ in range(m - warm):
                seq += ["F", "B"]
            seq += ["B"] * warm
            local.append(seq)
        ptr = [0] * pp
        fdone = [set() for _ in range(pp)]
        bdone = [set() for _ in range(pp)]
        fcount = [0] * pp
        bcount = [0] * pp
        done = 0
        total = sum(len(s) for s in local)
        order = []
        while done < total:
            progressed = False
            for i in range(pp):
                if ptr[i] >= len(local[i]):
                    continue
                op = local[i][ptr[i]]
                if op == "F":
                    mb = fcount[i]
                    ready = (i == 0) or (mb in fdone[i - 1])
                    if ready:
                        order.append((i, "F", mb))
                        fdone[i].add(mb)
                        fcount[i] += 1
                        ptr[i] += 1
                        done += 1
                        progressed = True
                else:
                    mb = bcount[i]
                    ready = (mb in fdone[i]) and \
                        (i == pp - 1 or mb in bdone[i + 1])
                    if ready:
                        order.append((i, "B", mb))
                        bdone[i].add(mb)
                        bcount[i] += 1
                        ptr[i] += 1
                        done += 1
                        progressed = True
            assert progressed, "pipeline schedule deadlock"
        return order

    def _schedule_fthenb(self, m):
        order = [(i, "F", mb) for mb in range(m) for i in range(self.pp)]
        order += [(i, "B", mb) for mb in range(m)
                  for i in reversed(range(self.pp))]
        return order

    def train_batch(self, data, optimizer=None, lr_scheduler=None,
                    scaler=None):
        """data: (inputs, labels) full batch; split into micro-batches along
        dim 0. Returns mean loss (train_batch parity)."""
        self._ensure_opt()
        inputs, labels = data
        x = inputs._value if isinstance(inputs, Tensor) else jnp.asarray(inputs)
        y = labels._value if isinstance(labels, Tensor) else jnp.asarray(labels)
        m = self.num_micro_batches
        assert x.shape[0] % m == 0, (
            f"batch {x.shape[0]} not divisible by {m} micro-batches")
        mb_x = jnp.split(x, m, axis=0)
        mb_y = jnp.split(y, m, axis=0)

        acts = {}      # (stage, mb) -> input activation on stage mesh
        outs = {}      # (stage, mb) -> output activation
        gys = {}       # (stage, mb) -> upstream grad for stage output
        losses = []
        for st in self.stages:
            st.grads = None

        if self.vpp > 1:
            order = interleaved_order(self.pp, self.vpp, m)
        elif self.schedule == "1F1B":
            order = self._schedule_1f1b(m)
        else:
            order = self._schedule_fthenb(m)
        for (i, op, mb) in order:
            st = self.stages[i]
            # stage-op span: each F/B micro-step is a slice on the trace
            # timeline, so the schedule's real (host-dispatch) shape —
            # warmup ramp, 1F1B steady state, drain — is visible per
            # chunk/microbatch in Perfetto
            with _trace.span(
                    f"pp.stage{i}.{'fwd' if op == 'F' else 'bwd'}",
                    cat="pipeline", chunk=i, mb=mb):
                if op == "F":
                    if i == 0:
                        xin = st.to_mesh(mb_x[mb])
                    else:
                        xin = st.to_mesh(outs[(i - 1, mb)])
                    acts[(i, mb)] = xin
                    lab = st.to_mesh(mb_y[mb]) if st.is_last else None
                    out = st.forward(xin, lab)
                    outs[(i, mb)] = out
                    if st.is_last:
                        losses.append(out)
                else:
                    if st.is_last:
                        gx = st.backward(acts[(i, mb)], None,
                                         st.to_mesh(mb_y[mb]))
                    else:
                        gy = self.stages[i].to_mesh(gys[(i, mb)])
                        gx = st.backward(acts[(i, mb)], gy)
                    if i > 0:
                        gys[(i - 1, mb)] = gx
                    # free activations for this microbatch at this stage
                    acts.pop((i, mb), None)
                    outs.pop((i, mb), None)

        # optimizer step per stage (grads averaged over micro-batches)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        inv_m = 1.0 / m
        for i, st in enumerate(self.stages):
            grads = jax.tree_util.tree_map(lambda g: g * inv_m, st.grads)
            st.params, self._opt_states[i] = self._opt_update[i](
                st.params, grads, self._opt_states[i], lr)
        if lr_scheduler is not None:
            lr_scheduler.step()
        total = sum(jax.device_get(l) for l in losses) / m
        return Tensor(jnp.asarray(total, jnp.float32))

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        x = inputs._value if isinstance(inputs, Tensor) else jnp.asarray(inputs)
        y = labels._value if isinstance(labels, Tensor) else jnp.asarray(labels)
        cur = x
        for i, st in enumerate(self.stages):
            lab = st.to_mesh(y) if st.is_last else None
            cur = st.forward(st.to_mesh(cur), lab)
        return Tensor(cur)

    def sync_to_model(self):
        for st in self.stages:
            named = dict(st.module.named_parameters())
            for n, v in st.params.items():
                if n in named:
                    named[n]._value = v

    def state_dict(self):
        self.sync_to_model()
        return self.pipe.state_dict()

    # --- exact training resume (per-stage params + slots + step) ------------
    def train_state_dict(self):
        """Flat resumable state across ALL stages/chunks: per-stage
        params, optimizer slots, step counter, buffers — keys
        `stage{c}.param.<n>` / `stage{c}.slot.<slot>.<n>` /
        `stage{c}.opt.step` / `stage{c}.buffer.<n>` (mirrors
        DistributedTrainStep.train_state_dict for the hybrid step)."""
        self._ensure_opt()
        out = {}
        for c, (st, state) in enumerate(zip(self.stages,
                                            self._opt_states)):
            def pin(v, spec, _mesh=st.mesh):
                # uncommitted leaves (fresh init slots/step) must be
                # pinned to THIS stage's mesh before becoming checkpoint
                # targets — the loader commits into the target's
                # placement, and a default-device commit would fight the
                # committed stage params inside the jitted update
                v = jnp.asarray(v)
                if getattr(v, "committed", True):
                    return v
                return jax.device_put(v, NamedSharding(_mesh, spec))

            for n, v in st.params.items():
                out[f"stage{c}.param.{n}"] = Tensor(v)
            for n, sd in state["slots"].items():
                pspec = st.param_specs.get(n, P())
                for k, v in sd.items():
                    spec = pspec if np.shape(v) == np.shape(
                        st.params[n]) else P()
                    out[f"stage{c}.slot.{k}.{n}"] = Tensor(pin(v, spec))
            out[f"stage{c}.opt.step"] = Tensor(pin(state["step"], P()))
            for n, v in st.buffers.items():
                out[f"stage{c}.buffer.{n}"] = Tensor(pin(v, P()))
        return out

    def save_train_state(self, path):
        from .train_step import save_train_checkpoint

        save_train_checkpoint(self.train_state_dict(), path,
                              self.optimizer._learning_rate)

    def load_train_state(self, path):
        """Strict resume incl. the host-side LR scheduler position (see
        load_train_checkpoint for why partial matches refuse)."""
        from .train_step import load_train_checkpoint

        self._ensure_opt()
        tgt = self.train_state_dict()
        load_train_checkpoint(tgt, path, self.optimizer._learning_rate)
        for c, (st, state) in enumerate(zip(self.stages,
                                            self._opt_states)):
            st.params = {n: tgt[f"stage{c}.param.{n}"]._value
                         for n in st.params}
            state["slots"] = {
                n: {k: tgt[f"stage{c}.slot.{k}.{n}"]._value for k in sd}
                for n, sd in state["slots"].items()}
            state["step"] = tgt[f"stage{c}.opt.step"]._value
            st.buffers = {n: tgt[f"stage{c}.buffer.{n}"]._value
                          for n in st.buffers}
