"""Hybrid-parallel topology over a jax device mesh.

Role parity: `CommunicateTopology` / `HybridCommunicateGroup`
(`python/paddle/distributed/fleet/base/topology.py:61,174,228`) — the object
that carves the device set into dp/pp/sharding/sep/mp axes and hands each
parallelism layer its group.

TPU-first: instead of per-axis NCCL communicators, the topology owns ONE
`jax.sharding.Mesh` whose named axes are the hybrid axes; "groups" are mesh
axes (SPMD collectives ride ICI via named-axis reductions inside jit), and
pipeline stages are contiguous submeshes. No ring-ids, no communicator init:
XLA derives the communication from shardings.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# canonical axis order: pp outermost (stages = submeshes), then dp (data /
# zero-sharding axis), sep (sequence/context parallel), mp (tensor parallel)
AXES = ("pp", "dp", "sep", "mp")


class HybridTopology:
    def __init__(self, dp=1, mp=1, pp=1, sep=1, sharding=1, devices=None):
        if devices is None:
            devices = jax.devices()
        n = len(devices)
        # sharding (ZeRO) reuses the dp axis: stage-k sharding shards
        # states over dp (weight-update sharding); a distinct degree is
        # folded into dp for mesh purposes.
        self.dp_degree = dp
        self.mp_degree = mp
        self.pp_degree = pp
        self.sep_degree = sep
        self.sharding_degree = sharding
        need = dp * mp * pp * sep * max(1, sharding) // max(1, sharding)
        need = dp * mp * pp * sep
        if need == 1 and n > 1:
            # default: everything data-parallel
            dp = self.dp_degree = n
            need = n
        if need > n:
            raise ValueError(
                f"hybrid degrees dp={dp} mp={mp} pp={pp} sep={sep} need "
                f"{need} devices, have {n}")
        devices = devices[:need]
        arr = np.array(devices).reshape(self.pp_degree, self.dp_degree,
                                        self.sep_degree, self.mp_degree)
        self._dev_array = arr
        # global mesh including pp (used when pp==1 or for fully-SPMD cases)
        self.mesh = Mesh(arr, AXES)
        # per-stage submeshes for the pipeline runner
        self.stage_meshes = [
            Mesh(arr[i], AXES[1:]) for i in range(self.pp_degree)
        ]

    # --- paddle-style queries -------------------------------------------------
    def get_num_of_ranks(self):
        return int(self._dev_array.size)

    def get_hybrid_group_names(self):
        return list(AXES)

    @property
    def spmd_mesh(self):
        """Mesh used inside a single jit program (no pp axis when pp>1)."""
        if self.pp_degree == 1:
            return Mesh(self._dev_array[0], AXES[1:])
        return self.mesh

    def stage_mesh(self, stage):
        return self.stage_meshes[stage]

    def data_sharding(self, batch_ndim=1, extra_seq_axis=None):
        """NamedSharding for a data batch: batch dim over dp, optionally the
        sequence dim over sep."""
        spec = ["dp"] + [None] * (batch_ndim - 1)
        if extra_seq_axis is not None and self.sep_degree > 1:
            spec[extra_seq_axis] = "sep"
        return NamedSharding(self.spmd_mesh, P(*spec))

    def replicated(self):
        return NamedSharding(self.spmd_mesh, P())

    def param_sharding(self, placements):
        """placements: tuple per-dim of axis-name or None."""
        return NamedSharding(self.spmd_mesh, P(*placements))


_topology = None
_mesh_override = None  # pipeline stages trace against their submesh


def set_topology(topo):
    global _topology
    _topology = topo


def get_topology():
    global _topology
    if _topology is None:
        _topology = HybridTopology()
    return _topology


def reset_topology():
    global _topology
    _topology = None


def current_spmd_mesh():
    if _mesh_override is not None:
        return _mesh_override
    return get_topology().spmd_mesh


import contextlib


@contextlib.contextmanager
def use_spmd_mesh(mesh):
    global _mesh_override
    old = _mesh_override
    _mesh_override = mesh
    try:
        yield
    finally:
        _mesh_override = old
