"""Multi-host launcher: `python -m paddle_tpu.distributed.launch ... script.py`.

Role parity: `python/paddle/distributed/launch/main.py:20` + the collective
controller (`controllers/collective.py:37`) and HTTP master rendezvous
(`controllers/master.py:73`).

TPU-first: ONE process per host owns all local chips (single-controller
jax), so `--devices` fan-out per chip is unnecessary on-host; the launcher's
job is multi-host wiring: it sets the coordinator address (jax distributed
coordination service = the TCPStore role), PADDLE_TRAINER_* env for scripts
that read them, restarts failed children up to --max_restart (elastic role),
and streams per-rank logs to --log_dir.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time


def parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None,
                   help="coordinator ip:port (default: first node, :8476)")
    p.add_argument("--nnodes", default="1",
                   help="N or min:max node count (elastic range)")
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", 0)))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (1 = single-controller default)")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--max_elastic_relaunch", type=int,
                   default=int(os.environ.get("PADDLE_MAX_ELASTIC_RELAUNCH",
                                              10)),
                   help="cap on membership-change relaunches (exit 101)")
    p.add_argument("--devices", default=None,
                   help="accepted for compatibility; chips are owned by the "
                        "single controller")
    p.add_argument("--job_id", default="default")
    p.add_argument("script", nargs="?")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def build_env(args, local_rank=0):
    env = dict(os.environ)
    nnodes = int(str(args.nnodes).split(":")[0])
    world = nnodes * args.nproc_per_node
    rank = args.rank * args.nproc_per_node + local_rank
    master = args.master or "127.0.0.1:8476"
    env.update({
        "PADDLE_MASTER": master,
        "COORDINATOR_ADDRESS": master,
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_NODE_RANK": str(args.rank),
        "PADDLE_JOB_ID": str(args.job_id),
    })
    return env


def launch(args=None):
    args = args or parse_args()
    if args.script is None:
        print("usage: python -m paddle_tpu.distributed.launch [opts] script.py",
              file=sys.stderr)
        return 1
    from ..fleet.elastic import ELASTIC_EXIT_CODE

    os.makedirs(args.log_dir, exist_ok=True)
    restarts = 0
    elastic_relaunches = 0
    while True:
        procs = []
        logs = []
        for lr in range(args.nproc_per_node):
            env = build_env(args, lr)
            log_path = os.path.join(
                args.log_dir, f"workerlog.{env['PADDLE_TRAINER_ID']}")
            lf = open(log_path, "a")
            logs.append(lf)
            cmd = [sys.executable, args.script] + list(args.script_args)
            procs.append(subprocess.Popen(cmd, env=env, stdout=lf,
                                          stderr=subprocess.STDOUT))
        codes = [p.wait() for p in procs]
        for lf in logs:
            lf.close()
        if all(c == 0 for c in codes):
            return 0
        if any(c == ELASTIC_EXIT_CODE for c in codes):
            # fleet.elastic protocol: membership change — relaunch without
            # charging max_restart, but bounded so a permanently dead peer
            # can't spin the pod forever
            elastic_relaunches += 1
            if elastic_relaunches > args.max_elastic_relaunch:
                print(f"giving up after {elastic_relaunches - 1} elastic "
                      "relaunches (membership never stabilized)",
                      file=sys.stderr)
                return ELASTIC_EXIT_CODE
            print("elastic membership change; relaunching pod "
                  f"({elastic_relaunches}/{args.max_elastic_relaunch})",
                  file=sys.stderr)
            time.sleep(1)
            continue
        restarts += 1
        if restarts > args.max_restart:
            print(f"giving up after {restarts - 1} restarts; exit codes "
                  f"{codes}", file=sys.stderr)
            return max(codes)
        print(f"restarting pod (attempt {restarts}/{args.max_restart}); "
              f"exit codes {codes}", file=sys.stderr)
        time.sleep(3)


if __name__ == "__main__":
    sys.exit(launch())
