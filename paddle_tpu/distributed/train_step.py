"""The SPMD train-step builder: one compiled program for the whole hybrid
(dp × mp × sep [+ ZeRO]) training step.

Role parity (SURVEY §2.5, §3.3): this is where the reference's imperative
machinery — `fleet.distributed_model` wrappers, `EagerReducer` bucketed
allreduce, `DygraphShardingOptimizer`/GroupSharded stage 1-3,
`HybridParallelOptimizer` grad clip across axes — collapses into sharding
annotations on ONE jit'd function:

* DP grad sync          → XLA auto-inserts the grad all-reduce because params
                          are replicated over dp while the batch is sharded
                          (no bucketing logic: the compiler fuses collectives)
* TP / SP               → param + activation shardings from mpu layers
* ZeRO-1/2 (stage 1/2)  → params AND optimizer slots live dp-sharded between
                          steps (weight-update sharding, ISSUE 11): the step
                          opens with one all-gather restoring full params for
                          the forward, each parameter's gradient carries its
                          own sharding constraint at the point the backward
                          produces it (per-layer reduce-scatters the
                          scheduler can overlap with remaining backward
                          compute — no end-of-backward barrier), and the
                          optimizer update runs on 1/dp of every parameter
                          (*Automatic Cross-Replica Sharding of Weight
                          Update in Data-Parallel Training*, PAPERS.md).
                          Bit-identical to the replicated update (pinned by
                          tests/test_sharding_zero.py on the 8-device mesh).
* ZeRO-3 (stage 3)      → params themselves dp-sharded; forward all-gathers
                          per-layer on demand (compiler-scheduled)
* grad clip             → global norm computed inside the same program, so
                          the cross-axis reductions ride ICI with everything
                          else
* collective precision  → PADDLE_TPU_COLLECTIVE_PRECISION=bf16|int8 runs the
                          gradient sync payload through the EQuARX-style
                          chunked codec (distributed/quantized.py); off by
                          default — the default step is exact (docs/
                          SHARDING.md "Precision knob")

``sharding_stage=None`` (the default) resolves to ZeRO-1 whenever the mesh
has a real dp axis and stage 0 on a single chip — sharded weight update IS
the default multi-chip training configuration (ROADMAP item 1).

Buffers (batch-norm stats) and the PRNG key are threaded through as carried
state, donated each step.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import flags, rng
from ..core.tensor import Tensor
from ..observability import xla_cost as _xla_cost
from . import topology as topo_mod

__all__ = ["DistributedTrainStep", "param_placements",
           "save_train_checkpoint", "load_train_checkpoint"]

_LR_SIDECAR = "lr_scheduler.json"


def save_train_checkpoint(tensors, path, lr_sched=None):
    """Shared writer for both training tiers (hybrid step + pipeline):
    distributed checkpoint of the flat leaf dict, plus a host-side LR
    scheduler sidecar JSON when one is attached."""
    import json as _json
    import os as _os

    from ..optimizer.lr import LRScheduler
    from .checkpoint import save_state_dict

    save_state_dict(tensors, path)
    if isinstance(lr_sched, LRScheduler):
        with open(_os.path.join(path, _LR_SIDECAR), "w") as f:
            _json.dump(lr_sched.state_dict(), f)


def load_train_checkpoint(tensors, path, lr_sched=None):
    """Shared strict loader: every leaf in `tensors` must exist in the
    checkpoint (a partial match would silently mix loaded and fresh
    state), and when the caller trains under an LRScheduler its sidecar
    must be present too (restoring the step counter but restarting the
    warmup/decay schedule is the same silent divergence). Loads in place
    (leaves reshard onto each target tensor's placement)."""
    import json as _json
    import os as _os

    from ..optimizer.lr import LRScheduler
    from .checkpoint import load_state_dict
    from .checkpoint.api import _load_metadata

    meta = _load_metadata(path)
    if meta is None:
        raise ValueError(f"no checkpoint metadata found under {path!r}")
    missing = sorted(set(tensors) - set(meta.state_dict_metadata))
    if missing:
        raise ValueError(
            f"checkpoint at {path!r} is missing {len(missing)} of "
            f"{len(tensors)} training-state leaves (first: "
            f"{missing[:5]}) — refusing a partial resume (wrong model "
            "config or corrupt checkpoint?)")
    sched_file = _os.path.join(path, _LR_SIDECAR)
    if isinstance(lr_sched, LRScheduler):
        if not _os.path.exists(sched_file):
            raise ValueError(
                f"checkpoint at {path!r} has no {_LR_SIDECAR} but this "
                "run trains under an LRScheduler — resuming would "
                "restart the schedule at step 0 (was the checkpoint "
                "saved with a float learning rate?)")
        with open(sched_file) as f:
            state = _json.load(f)
    load_state_dict(tensors, path)
    if isinstance(lr_sched, LRScheduler):
        lr_sched.set_state_dict(state)


def param_placements(param, ndim=None):
    """Per-dim axis names from a parameter's dist_attr annotation."""
    ndim = ndim if ndim is not None else param.ndim
    da = getattr(param, "dist_attr", None)
    if isinstance(da, tuple) and (not da or not hasattr(da[0], "jax_mesh")):
        spec = list(da) + [None] * (ndim - len(da))
        return tuple(spec[:ndim])
    return (None,) * ndim


def _zero_shard_spec(spec, shape, dp_size, used_axes):
    """Add 'dp' to the first free, divisible dim (ZeRO weight partitioning)."""
    spec = list(spec)
    for d, s in enumerate(shape):
        if spec[d] is None and dp_size > 0 and s % dp_size == 0 and s >= dp_size:
            spec[d] = "dp"
            return tuple(spec)
    return tuple(spec)


class DistributedTrainStep:
    def __init__(self, model, optimizer, loss_fn=None, topo=None,
                 sharding_stage=None, recompute=False, amp_dtype=None,
                 grad_clip_norm=None, loss_has_aux=False, guard=None,
                 checkpoint_manager=None, preemption_guard=None,
                 collective_precision=None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.topo = topo or topo_mod.get_topology()
        if sharding_stage is None:
            # ZeRO-1 is the default multi-chip configuration: a real dp
            # axis means the replicated weight update is pure waste
            # (PT403's finding); a single chip has nothing to shard over.
            dp = self.topo.spmd_mesh.shape.get("dp", 1)
            sharding_stage = 1 if dp > 1 else 0
        self.sharding_stage = int(sharding_stage)
        # resolve the EQuARX tier once, at build time: an invalid knob
        # must fail construction, not step N of a training run
        from . import quantized as _quantized

        self.collective_precision = _quantized.collective_precision(
            collective_precision)
        self.amp_dtype = amp_dtype
        self.grad_clip_norm = grad_clip_norm
        self._compiled = None
        self._state = None
        self._param_names = [n for n, _ in model.named_parameters()]
        # --- resilience (docs/RESILIENCE.md) ---
        # guard=True/StepGuard: a finiteness reduction over loss+grads is
        # fused into the compiled step and bad steps keep the previous
        # state ON DEVICE (jnp.where select — with ok the selected leaves
        # are the new values bit-for-bit, so a fault-free guarded run
        # matches the unguarded trajectory exactly); the host sees one
        # ok scalar per dispatch and escalates warn→skip→rollback.
        if guard is True:
            from ..resilience.guards import StepGuard

            guard = StepGuard(name="train_step")
        self.guard = guard or None
        self._ckpt_mgr = checkpoint_manager
        if self.guard is not None and self._ckpt_mgr is not None \
                and self.guard.on_rollback is None:
            self.guard.set_rollback(self.rollback)
        # preemption_guard: a resilience.preemption.PreemptionGuard this
        # step consults at its safe points (between dispatches) — a
        # SIGTERM/maintenance event checkpoints through the attached
        # manager and raises TrainingPreempted instead of vanishing
        # mid-collective with unsaved state.
        self._preemption_guard = preemption_guard
        self._preemption_handled = None  # TrainingPreempted once raised

    # --- sharding planning ---------------------------------------------------
    def _plan(self, params, slots):
        """Storage shardings for params and optimizer slots.

        Returns ``(p_spec, s_spec)`` — the specs the state LIVES under
        between steps (and the compiled step's output pins):

          stage 0   params/slots follow the mpu placements (replicated
                    over dp)
          stage 1/2 ZeRO weight-update sharding: params AND slots carry
                    a dp shard on their first free divisible dim; the
                    step all-gathers full params for the forward
                    (``_p_full_spec`` keeps the forward-view spec)
          stage 3   same sharded storage, but no up-front gather — the
                    compiler all-gathers per use site on demand
        """
        mesh = self.topo.spmd_mesh
        dp = mesh.shape.get("dp", 1)
        named = dict(self.model.named_parameters())
        p_spec, p_full = {}, {}
        for n, v in params.items():
            spec = param_placements(named[n], np.ndim(v))
            p_full[n] = spec
            if self.sharding_stage >= 1:
                spec = _zero_shard_spec(spec, np.shape(v), dp, None)
            p_spec[n] = spec
        s_spec = {}
        for n, slotdict in slots.items():
            # slots inherit the param's storage spec: under ZeRO it is
            # already dp-sharded, so re-running _zero_shard_spec here
            # would pick a SECOND dim for same-shaped slots (the bug the
            # old dead `base = ... if ... else ...` branch masked)
            base = p_spec[n]
            out = {}
            for k, v in slotdict.items():
                if np.shape(v) == np.shape(params[n]):
                    out[k] = base
                else:
                    spec = param_placements(named[n], np.ndim(v))
                    if self.sharding_stage >= 1:
                        spec = _zero_shard_spec(spec, np.shape(v), dp,
                                                None)
                    out[k] = spec
            s_spec[n] = out
        self._p_full_spec = p_full
        return p_spec, s_spec

    def _sharding(self, spec):
        return NamedSharding(self.topo.spmd_mesh, P(*spec))

    # --- state ---------------------------------------------------------------
    def _put_state(self, v, sharding):
        """Place a host value (held in FULL on every process) with
        `sharding`. Single-process: plain device_put. Multi-process
        (multi-host training over the jax coordination service): the
        sharding spans non-addressable devices, which device_put rejects
        — build the global array from per-device slices of the full
        value instead (each process materializes only its addressable
        shards)."""
        if jax.process_count() == 1:
            return jax.device_put(v, sharding)
        v = jnp.asarray(v)
        return jax.make_array_from_callback(v.shape, sharding,
                                            lambda idx: v[idx])

    def init_state(self):
        params, buffers = self.model.functional_state()
        opt_state = self.optimizer.init_state(params)
        p_spec, s_spec = self._plan(params, opt_state["slots"])
        mesh = self.topo.spmd_mesh

        params = {n: self._put_state(v, self._sharding(p_spec[n]))
                  for n, v in params.items()}
        slots = {n: {k: self._put_state(v, self._sharding(s_spec[n][k]))
                     for k, v in sd.items()}
                 for n, sd in opt_state["slots"].items()}
        buffers = {n: self._put_state(v, NamedSharding(mesh, P()))
                   for n, v in buffers.items()}
        self._p_spec, self._s_spec = p_spec, s_spec
        # every leaf — including the scalar step counter and the PRNG key —
        # must carry the mesh sharding the compiled step emits, or the
        # second call's input avals differ from the first's and jit
        # retraces+recompiles the whole program (a full second XLA compile)
        rep = NamedSharding(mesh, P())
        self._state = {
            "params": params,
            "opt": {"slots": slots,
                    "step": self._put_state(
                        jnp.asarray(opt_state["step"]), rep)},
            "buffers": buffers,
            # fresh buffer: the step donates its state, so it must NOT alias
            # the global generator's key array
            "key": self._put_state(
                jax.random.fold_in(rng.default_generator.get_state(), 7),
                rep),
        }
        return self._state

    # --- compiled step -------------------------------------------------------
    def _build(self, batch_treedef, batch_specs):
        model = self.model
        optimizer = self.optimizer
        loss_fn = self.loss_fn
        amp_dtype = self.amp_dtype
        clip_norm = self.grad_clip_norm
        mesh = self.topo.spmd_mesh

        def loss_of(params, buffers, key, batch_leaves):
            old = rng.default_generator.get_state()
            rng.default_generator.set_state(key)
            try:
                run_params = params
                if amp_dtype is not None:
                    run_params = {
                        n: (v.astype(amp_dtype)
                            if jnp.issubdtype(v.dtype, jnp.floating) else v)
                        for n, v in params.items()}
                def _amp_in(b):
                    # O2 semantics: floating model inputs enter in the
                    # compute dtype (conv/matmul operands must agree)
                    if amp_dtype is not None and \
                            jnp.issubdtype(b.dtype, jnp.floating):
                        return b.astype(amp_dtype)
                    return b

                with flags.trace_guard():
                    with model.bind_state(run_params, buffers) as (np_, nb_):
                        args = jax.tree_util.tree_unflatten(
                            batch_treedef,
                            [Tensor(_amp_in(b)) for b in batch_leaves])
                        if loss_fn is not None:
                            inputs, labels = args
                            out = model(inputs)
                            loss = loss_fn(out, labels)
                        else:
                            loss = model(*args)
                        new_buffers = {n: nb_[n]._value for n in nb_}
                new_key = rng.default_generator.get_state()
            finally:
                rng.default_generator.set_state(old)
            lv = loss._value if isinstance(loss, Tensor) else loss
            if lv.ndim > 0:
                lv = jnp.mean(lv)
            return lv.astype(jnp.float32), (new_buffers, new_key)

        guarded = self.guard is not None
        dp = mesh.shape.get("dp", 1)
        # ZeRO weight-update sharding is live when state storage carries a
        # dp shard: stage 1/2 materialize full params up front (ONE
        # gather the scheduler can prefetch); stage 3 leaves gathering to
        # the compiler per use site.
        zero_sharded = self.sharding_stage >= 1 and dp > 1
        gather_full = zero_sharded and self.sharding_stage < 3
        precision = self.collective_precision if zero_sharded else None
        if precision is not None:
            from . import quantized as _quantized
            from ..observability import metrics as _metrics

            # counted only when the tier is actually traced into the
            # step — on a single chip (or stage 0) the knob is inert and
            # every collective stays exact, so telemetry must not claim
            # a lossy codec ran
            _metrics.inc("collective.quantized_tier", precision=precision)

        def step(params, opt_state, buffers, key, lr, *batch_leaves):
            if gather_full:
                # all-gather: full params for the next forward (ZeRO-1's
                # per-step gather — the bits equal the sharded storage's)
                run_params = {
                    n: jax.lax.with_sharding_constraint(
                        v, self._sharding(self._p_full_spec[n]))
                    for n, v in params.items()}
            else:
                run_params = params
            (loss, (new_buffers, new_key)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(run_params, buffers, key,
                                       list(batch_leaves))
            if clip_norm is not None:
                gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                          for g in jax.tree_util.tree_leaves(grads))
                scale = jnp.minimum(
                    1.0, clip_norm / jnp.maximum(jnp.sqrt(gsq), 1e-6))
                grads = jax.tree_util.tree_map(
                    lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype),
                    grads)
            if zero_sharded:
                # per-parameter sharding constraint at the point the
                # backward produces each grad: the partitioner reduces
                # straight into 1/dp shards (reduce-scatter on TPU; the
                # CPU partitioner realizes it as all-reduce+slice, same
                # math) — one collective per layer, overlappable with
                # the remaining backward, not one end-of-backward
                # barrier.  The quantized tier codecs the payload first.
                def _sync(n, g):
                    if precision is not None:
                        g = _quantized.qdq(g, precision)
                    return jax.lax.with_sharding_constraint(
                        g, self._sharding(self._p_spec[n]))

                grads = {n: _sync(n, g) for n, g in grads.items()}
            # the update consumes the SHARDED params/grads/slots: every
            # optimizer is elementwise over same-shaped leaves, so the
            # whole weight update runs on 1/dp of each parameter
            new_params, new_opt = optimizer.apply_gradients(
                params, grads, opt_state, lr)
            # pin result shardings so the update stays ZeRO-partitioned
            new_params = {
                n: jax.lax.with_sharding_constraint(
                    v, self._sharding(self._p_spec[n]))
                for n, v in new_params.items()}
            new_opt_slots = {
                n: {k: jax.lax.with_sharding_constraint(
                    v, self._sharding(self._s_spec[n][k]))
                    for k, v in sd.items()}
                for n, sd in new_opt["slots"].items()}
            new_opt = {"slots": new_opt_slots, "step": new_opt["step"]}
            if guarded:
                # in-step NaN/Inf guard: one fused finiteness reduction
                # over loss + grads; a bad step keeps params/opt/buffers
                # (incl. the opt step counter) on device — no host
                # round-trip, no torn half-applied update.  The PRNG key
                # still advances: a skipped step must not replay the
                # same dropout mask into the retry.
                from ..resilience import guards as _guards

                ok = _guards.tree_finite(loss, grads)
                new_params = _guards.tree_select(ok, new_params, params)
                new_opt = _guards.tree_select(ok, new_opt, opt_state)
                new_buffers = _guards.tree_select(ok, new_buffers, buffers)
            else:
                ok = jnp.bool_(True)
            return loss, ok, new_params, new_opt, new_buffers, new_key

        self._step_fn = step
        # with telemetry on, the compile happens inside an
        # `xla.compile:train_step` span annotated with cost_analysis
        # FLOPs/bytes (plain jit call otherwise)
        return _xla_cost.instrument(
            jax.jit(step, donate_argnums=(0, 1, 2, 3),
                    compiler_options=flags.jit_compiler_options()),
            label="train_step")

    def _build_multi(self, batch_treedef, is_repeat):
        """N steps in ONE compiled program: lax.scan over the leading batch
        axis (or `repeat` times over one batch). Host dispatches once per
        N steps — on a tunneled/remote chip the per-dispatch gap (~tens of
        ms) otherwise shows up as device IDLE between steps (PERF.md
        profile). XLA keeps state resident across scan iterations, so this
        is also the idiomatic TPU shape for a training loop (host loop
        minimization)."""
        self._build(batch_treedef, None)  # ensure _step_fn exists
        step = self._step_fn

        def multi(params, opt_state, buffers, key, lrs, *batch_leaves):
            def body(carry, sl):
                params, opt_state, buffers, key = carry
                lr_i = sl[0]
                batch_sl = batch_leaves if is_repeat else sl[1:]
                loss, ok, p2, o2, b2, k2 = step(params, opt_state, buffers,
                                                key, lr_i, *batch_sl)
                return (p2, o2, b2, k2), (loss, ok)

            # scan length comes from lrs' leading dim: one jit WRAPPER
            # serves every step count in this mode (a new N still
            # retraces inside it, since lrs' shape changes — but the
            # previous N's executable stays cached alongside)
            xs = (lrs,) if is_repeat else (lrs,) + tuple(batch_leaves)
            (p, o, b, k), (losses, oks) = jax.lax.scan(
                body, (params, opt_state, buffers, key), xs)
            return losses, oks, p, o, b, k

        return _xla_cost.instrument(
            jax.jit(multi, donate_argnums=(0, 1, 2, 3),
                    compiler_options=flags.jit_compiler_options()),
            label="train_step_multi")

    def run_steps(self, *batch, lrs=None, repeat=None):
        """Run one optimizer step per leading-axis slice of `batch` (every
        leaf shaped [n_steps, ...]) inside a single compiled program;
        returns the per-step losses as one [n_steps] Tensor.

        repeat: alternatively, pass ONE batch (no leading step axis) and
        scan it `repeat` times — same dispatch amortization without
        materializing n_steps copies of the data (benchmarks, gradient
        sanity loops).

        lrs: optional per-step learning rates, shape [n_steps]. With an
        LRScheduler-driven optimizer and lrs=None, the schedule's next
        n_steps values are read (and the scheduler advanced n_steps) here
        — matching the sequential `__call__`+`scheduler.step()` loop. An
        explicit lrs leaves the scheduler untouched: the caller owns the
        schedule position in that mode."""
        from ..optimizer.lr import LRScheduler

        self._check_preemption()  # don't start a scan we can't keep
        if repeat is not None:
            repeat = int(repeat)
            if repeat < 1:
                raise ValueError(f"repeat must be >= 1, got {repeat}")
        placed, treedef = self._place_batch(
            batch, batch_axis=0 if repeat else 1)
        if repeat:
            n_steps = repeat
        else:
            n_steps = int(placed[0].shape[0]) if placed else 0
        if lrs is None:
            sched = self.optimizer._learning_rate
            if isinstance(sched, LRScheduler):
                # consume the next n_steps of the schedule host-side (the
                # scan cannot step the scheduler), leaving it positioned
                # exactly as n_steps sequential __call__+step()s would
                vals = []
                for _ in range(n_steps):
                    vals.append(float(self.optimizer.get_lr()))
                    sched.step()
                lrs = jnp.asarray(vals, jnp.float32)
            else:
                lrs = jnp.full((n_steps,), self.optimizer.get_lr(),
                               jnp.float32)
        else:
            lrs = jnp.asarray(
                lrs._value if isinstance(lrs, Tensor) else lrs,
                jnp.float32)
            if lrs.shape != (n_steps,):
                raise ValueError(
                    f"lrs must have shape ({n_steps},), got {lrs.shape}")
        multi_sig = (treedef, repeat is not None)
        if getattr(self, "_compiled_multi", None) is None or \
                getattr(self, "_multi_sig", None) != multi_sig:
            self._multi_sig = multi_sig
            self._compiled_multi = self._build_multi(
                treedef, repeat is not None)
        placed = self._maybe_poison(placed, n_steps=n_steps)
        s = self._state
        losses, oks, params, opt, buffers, key = self._compiled_multi(
            s["params"], s["opt"], s["buffers"], s["key"], lrs, *placed)
        self._swap_state(params, opt, buffers, key)
        if self.guard is not None:
            for ok in np.asarray(oks):
                self.guard.observe(bool(ok))
        self._check_preemption()  # signal landed mid-scan: state is
        return Tensor(losses)     # post-scan consistent → save now

    def _place_batch(self, batch, batch_axis):
        """Unwrap/flatten a batch and device_put each leaf with the dp
        axis on `batch_axis` (0 for single steps, 1 under a leading step
        axis). Returns (placed_leaves, treedef)."""
        if self._state is None:
            self.init_state()
        vals = jax.tree_util.tree_map(
            lambda b: b._value if isinstance(b, Tensor) else jnp.asarray(b),
            batch, is_leaf=lambda x: isinstance(x, Tensor))
        leaves, treedef = jax.tree_util.tree_flatten(vals)
        mesh = self.topo.spmd_mesh
        dp = mesh.shape.get("dp", 1)
        placed = []
        multiproc = jax.process_count() > 1
        # multi-host: each process holds its LOCAL shard, which must be
        # divisible by the dp devices *this process* contributes — not
        # by the global degree
        dp_div = max(dp // jax.process_count(), 1) if multiproc \
            else max(dp, 1)
        for b in leaves:
            batched = np.ndim(b) > batch_axis
            if batched and b.shape[batch_axis] % dp_div == 0:
                spec = [None] * batch_axis + ["dp"] + \
                    [None] * (np.ndim(b) - batch_axis - 1)
            elif batched and multiproc and dp > 1:
                # replicating per-rank-DIFFERENT data as a "replicated"
                # global array would silently diverge the ranks — refuse
                raise ValueError(
                    f"multi-process batch leaf with local batch "
                    f"{b.shape[batch_axis]} not divisible by the "
                    f"process-local dp share ({dp_div}); pad or resize "
                    f"the per-rank batch")
            else:
                spec = [None] * np.ndim(b)
            if multiproc:
                # assemble the global array across processes (global
                # batch = sum of local batches along the dp axis)
                from jax.experimental import multihost_utils

                placed.append(
                    multihost_utils.host_local_array_to_global_array(
                        np.asarray(b), mesh, P(*spec)))
            else:
                placed.append(
                    jax.device_put(b, NamedSharding(mesh, P(*spec))))
        return placed, treedef

    def _swap_state(self, params, opt, buffers, key):
        self._state = {"params": params, "opt": opt, "buffers": buffers,
                       "key": key}

    def _ensure_compiled(self, treedef):
        """One compile-cache keying for __call__ and lower(): a drift
        between the lowered-for-analysis and executed programs would
        defeat the analyzer's purpose."""
        if self._compiled is None or \
                getattr(self, "_batch_treedef", None) != treedef:
            self._batch_treedef = treedef
            self._compiled = self._build(treedef, None)
        return self._compiled

    def lower(self, *batch):
        """Lower the compiled step for `batch` without executing it
        (state does NOT advance). Feeds the completion/reshard analyzers
        (`distributed.completion.analyze`): `.as_text()` carries the
        GSPMD sharding annotations, `.compile().as_text()` the inserted
        collectives."""
        placed, treedef = self._place_batch(batch, batch_axis=0)
        compiled = self._ensure_compiled(treedef)
        s = self._state
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        return compiled.lower(
            s["params"], s["opt"], s["buffers"], s["key"], lr, *placed)

    def _maybe_poison(self, placed, n_steps=1):
        """`train.step` fault point: kind="error" raises at dispatch;
        kind="nan" poisons the first floating batch leaf so a NaN flows
        through the REAL compiled program (loss and grads go non-finite
        the way a genuinely bad batch/overflow makes them — the guard is
        exercised end-to-end, not mocked)."""
        from ..resilience import faults as _faults

        action = _faults.fire("train.step", n_steps=n_steps)
        if action is not None and action.kind == "nan":
            for i, b in enumerate(placed):
                if jnp.issubdtype(b.dtype, jnp.floating):
                    placed = list(placed)
                    # 0*nan propagates NaN elementwise, sharding intact
                    placed[i] = b + jnp.asarray(
                        float("nan"), b.dtype) * jnp.zeros_like(b)
                    break
        return placed

    def __call__(self, *batch):
        """batch: (inputs, labels) Tensors (loss_fn mode) or raw model args.
        Returns the loss as a Tensor; model/optimizer state advances."""
        self._check_preemption()  # safe point: pre-dispatch
        placed, treedef = self._place_batch(batch, batch_axis=0)
        compiled = self._ensure_compiled(treedef)
        placed = self._maybe_poison(placed)
        s = self._state
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        loss, ok, params, opt, buffers, key = compiled(
            s["params"], s["opt"], s["buffers"], s["key"], lr, *placed)
        self._swap_state(params, opt, buffers, key)
        if self.guard is not None:
            # ONE host-visible scalar per dispatch (the guarded mode's
            # only extra transfer) drives the warn→skip→rollback ladder
            self.guard.observe(bool(ok))
        self._check_preemption()  # safe point: post-step, state swapped
        return Tensor(loss)

    # --- state sync back to the eager model ---------------------------------
    def sync_to_model(self):
        """Write compiled-state params/buffers back into the eager Layer
        (for checkpointing / eval in eager mode)."""
        if self._state is None:
            return
        named_p = dict(self.model.named_parameters())
        for n, v in self._state["params"].items():
            if n in named_p:
                named_p[n]._value = v
        named_b = dict(self.model.named_buffers())
        for n, v in self._state["buffers"].items():
            if n in named_b:
                named_b[n]._value = v

    def state_dict(self):
        self.sync_to_model()
        return self.model.state_dict()

    # --- exact training resume (params + slots + step), reshard-aware -------
    def train_state_dict(self):
        """The COMPLETE resumable training state as a flat dict of
        Tensors wrapping the live (sharded) arrays: parameters, every
        optimizer slot, the step counter, and buffers. Keys are stable
        across topologies (`param.<name>` / `slot.<slot>.<name>` /
        `opt.step` / `buffer.<name>`), so a checkpoint saved under one
        mesh loads into a step built under another — the distributed
        checkpoint reshards leaf-by-leaf (reference role:
        fleet checkpointing of params + DygraphShardingOptimizer slots).
        The PRNG key is deliberately excluded: dropout streams are not
        resumable across topology changes (keys fold per-device)."""
        if self._state is None:
            self.init_state()
        s = self._state
        out = {}
        for n, v in s["params"].items():
            out[f"param.{n}"] = Tensor(v)
        for n, sd in s["opt"]["slots"].items():
            for k, v in sd.items():
                out[f"slot.{k}.{n}"] = Tensor(v)
        out["opt.step"] = Tensor(s["opt"]["step"])
        for n, v in s["buffers"].items():
            out[f"buffer.{n}"] = Tensor(v)
        return out

    def save_train_state(self, path):
        """Write the full training state with the distributed checkpoint
        writer (per-shard files, reshard-on-load). A host-side LR
        scheduler's position (warmup/decay progress) rides alongside as
        JSON — the device step counter alone would resume Adam bias
        correction correctly but silently restart the LR schedule."""
        save_train_checkpoint(self.train_state_dict(), path,
                              self.optimizer._learning_rate)

    def load_train_state(self, path):
        """Resume exactly: load a `save_train_state` checkpoint into
        THIS step's shardings (any source topology — the checkpoint
        loader reshards), then swap the loaded leaves into the live
        state. Strict: every leaf of this step's state must exist in the
        checkpoint — a partial match would silently mix loaded and
        freshly-initialized state (wrong model/config checkpoints fail
        loudly instead). The optimizer's step counter AND any host-side
        LR scheduler position resume mid-schedule."""
        if self._state is None:
            self.init_state()
        tgt = self.train_state_dict()
        load_train_checkpoint(tgt, path, self.optimizer._learning_rate)
        self._adopt(tgt)

    def _adopt(self, tgt):
        """Swap loaded train_state_dict leaves into the live state."""
        s = self._state
        s["params"] = {n: tgt[f"param.{n}"]._value for n in s["params"]}
        s["opt"]["slots"] = {
            n: {k: tgt[f"slot.{k}.{n}"]._value for k in sd}
            for n, sd in s["opt"]["slots"].items()}
        s["opt"]["step"] = tgt["opt.step"]._value
        s["buffers"] = {n: tgt[f"buffer.{n}"]._value
                        for n in s["buffers"]}

    # --- resilience: preemption safe points ----------------------------------
    def attach_preemption_guard(self, guard):
        """Consult `guard` (resilience.preemption.PreemptionGuard) at
        this step's safe points: a trip checkpoints through the attached
        manager and raises TrainingPreempted with the resumable path."""
        self._preemption_guard = guard
        return self

    def _check_preemption(self):
        """Safe-point probe, called between dispatches (never inside
        one): the live state is a complete, consistent post-step
        snapshot here, so the emergency checkpoint it writes is exactly
        what `load_train_state`/`rollback` resumes bit-for-bit."""
        g = self._preemption_guard
        if g is None or not g.check():
            return
        if self._preemption_handled is not None:
            # already checkpointed for this trip: a caller ignoring the
            # first TrainingPreempted must not silently keep training —
            # re-raise the same resumable exception, without re-saving
            raise self._preemption_handled
        from ..resilience.preemption import TrainingPreempted

        ckpt_dir = step_no = None
        if self._ckpt_mgr is not None and self._state is not None:
            try:
                step_no = int(np.asarray(self._state["opt"]["step"]))
            except (TypeError, ValueError):
                step_no = None  # manager picks newest+1
            ckpt_dir = self.save_checkpoint(step=step_no)
            try:
                from ..observability import flight as _flight
                from ..observability import metrics as _metrics

                _metrics.inc("preemption.checkpoints")
                _flight.record("preemption.checkpoint_saved",
                               path=ckpt_dir, step=step_no,
                               reason=g.reason)
            except Exception:  # pt-lint: ok[PT005]
                pass           # (observability fan-out guard: the
                # checkpoint landed — telemetry must not turn a clean
                # preemption exit into a crash)
        exit_code = getattr(g, "exit_code", 0)
        self._preemption_handled = TrainingPreempted(
            g.reason, checkpoint_dir=ckpt_dir, step=step_no,
            exit_code=exit_code)
        raise self._preemption_handled

    # --- resilience: rotation checkpointing + guard rollback -----------------
    def attach_checkpoint_manager(self, manager):
        """Use a `distributed.checkpoint.CheckpointManager` as this
        step's save target and (when a guard is active with no explicit
        rollback) the guard's rollback source."""
        self._ckpt_mgr = manager
        if self.guard is not None and self.guard.on_rollback is None:
            self.guard.set_rollback(self.rollback)
        return self

    def save_checkpoint(self, step=None, async_save=False):
        """Checkpoint the full training state through the attached
        manager (atomic, CRC'd, rotated); returns the checkpoint dir."""
        if self._ckpt_mgr is None:
            raise ValueError("no CheckpointManager attached "
                             "(attach_checkpoint_manager first)")
        return self._ckpt_mgr.save(self.train_state_dict(), step=step,
                                   async_save=async_save)

    def rollback(self):
        """Restore the newest VERIFIED checkpoint from the attached
        manager into the live state (corrupt ones are quarantined and
        skipped) — the guard escalation lands here after K consecutive
        non-finite steps.  Returns the checkpoint step restored."""
        if self._ckpt_mgr is None:
            raise ValueError("no CheckpointManager attached "
                             "(attach_checkpoint_manager first)")
        if self._state is None:
            self.init_state()
        tgt = self.train_state_dict()
        step = self._ckpt_mgr.restore(tgt)
        self._adopt(tgt)
        return step
