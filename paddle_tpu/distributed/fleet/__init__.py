"""Fleet facade (parity: `python/paddle/distributed/fleet/fleet.py:100,603` —
fleet.init / distributed_model / distributed_optimizer, DistributedStrategy,
HybridCommunicateGroup accessors).

TPU-first: `init` builds the hybrid mesh topology; `distributed_model` +
`distributed_optimizer` return wrappers whose training path is the single
compiled SPMD step (`distributed.train_step.DistributedTrainStep`) rather
than per-axis communicator wrappers; eager per-step semantics are preserved
for the dygraph UX.
"""
from __future__ import annotations

from . import elastic  # noqa: F401
from .. import topology as topo_mod
from ..topology import HybridTopology
from ..train_step import DistributedTrainStep
from ...optimizer.optimizer import Optimizer

__all__ = ["DistributedStrategy", "init", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "init_parallel_env", "worker_num", "worker_index",
           "is_first_worker", "barrier_worker", "resolve_sharding_stage"]


class DistributedStrategy:
    """Parity with the protobuf-backed DistributedStrategy
    (`paddle/fluid/framework/distributed_strategy.proto:359`): a python
    config object; only TPU-meaningful fields are interpreted, the rest are
    accepted for compatibility."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sep_degree": 1,
            "sharding_degree": 1,
        }
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768, "use_pure_bf16": False}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {"stage": 1}
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "schedule_mode": "1F1B"}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.fuse_all_reduce_ops = True
        self.find_unused_parameters = False

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


class _FleetState:
    strategy = None
    topo = None
    initialized = False


_state = _FleetState()


def resolve_sharding_stage(strategy):
    """The ZeRO stage a strategy asks for (ISSUE 11 wiring: the
    ``sharding_degree`` / ``sharding_configs["stage"]`` stubs now reach
    ``DistributedTrainStep(sharding_stage=...)``):

      * ``strategy.sharding`` set      → ``sharding_configs["stage"]``
        (the explicit GroupSharded request, parity with the reference's
        DygraphShardingOptimizer selection)
      * ``sharding_degree > 1``        → ZeRO-1 — sharded weight update
        is the DEFAULT multi-chip training configuration (ROADMAP item
        1); the update is bit-identical to the replicated one (pinned by
        tests/test_sharding_zero.py), so opting in costs nothing
      * otherwise                      → stage 0: a strategy that says
        ``sharding_degree=1`` asked for a replicated update, even when
        the topology auto-expands its device axis (reference
        DistributedStrategy parity: sharding is off unless configured).
        A bare ``DistributedTrainStep(sharding_stage=None)`` with no
        strategy resolves from the MESH instead (dp>1 → ZeRO-1) — set
        ``sharding_degree`` to the dp degree to get the same through
        fleet.
    """
    if strategy is None:
        return None  # DistributedTrainStep resolves from the mesh
    if strategy.sharding:
        return int(strategy.sharding_configs.get("stage", 1))
    if int(strategy.hybrid_configs.get("sharding_degree", 1)) > 1:
        return 1
    return 0


def init(role_maker=None, is_collective=False, strategy=None, log_level="INFO"):
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    topo = HybridTopology(
        dp=hc.get("dp_degree", 1), mp=hc.get("mp_degree", 1),
        pp=hc.get("pp_degree", 1), sep=hc.get("sep_degree", 1),
        sharding=hc.get("sharding_degree", 1))
    topo_mod.set_topology(topo)
    _state.strategy = strategy
    _state.topo = topo
    _state.initialized = True
    return _state


def get_hybrid_communicate_group():
    return _state.topo or topo_mod.get_topology()


def get_strategy():
    return _state.strategy


class HybridParallelOptimizer:
    """Wrapper returned by distributed_optimizer (parity:
    `hybrid_parallel_optimizer.py:254`): eager `.step()` delegates to the
    inner optimizer (grad sync is the compiled path's job on TPU); exposes
    `build_train_step` to assemble the compiled hybrid step."""

    def __init__(self, optimizer, strategy=None):
        self._inner_opt = optimizer
        self._strategy = strategy or _state.strategy or DistributedStrategy()

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad()

    def minimize(self, loss, **kw):
        return self._inner_opt.minimize(loss, **kw)


class DistributedModelProxy:
    """Wrapper returned by distributed_model (parity: fleet/model.py:32 —
    which picks DataParallel/TensorParallel/PipelineParallel wrappers).
    Forwarding is unchanged (mpu annotations already carry TP); train_batch
    drives the compiled hybrid step (PipelineParallel.train_batch parity)."""

    def __init__(self, model, strategy):
        self._layers = model
        self._strategy = strategy
        self._train_step = None

    def __getattr__(self, item):
        return getattr(self._layers, item)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def build_train_step(self, optimizer, loss_fn, **kw):
        strategy = self._strategy or DistributedStrategy()
        inner = optimizer._inner_opt if isinstance(
            optimizer, HybridParallelOptimizer) else optimizer
        kw.setdefault("amp_dtype", "bfloat16" if strategy.amp else None)
        kw.setdefault("sharding_stage", resolve_sharding_stage(strategy))
        kw.setdefault("topo", _state.topo)
        self._train_step = DistributedTrainStep(
            self._layers, inner, loss_fn, **kw)
        return self._train_step

    def train_batch(self, batch, optimizer=None, lr_scheduler=None,
                    loss_fn=None, scaler=None):
        if self._train_step is None:
            assert optimizer is not None and loss_fn is not None, \
                "first train_batch needs optimizer and loss_fn"
            self.build_train_step(optimizer, loss_fn)
        loss = self._train_step(*batch)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss


def distributed_model(model):
    return DistributedModelProxy(model, _state.strategy)


def distributed_optimizer(optimizer, strategy=None):
    return HybridParallelOptimizer(optimizer, strategy)


def init_parallel_env():
    if not _state.initialized:
        init(is_collective=True)
    return _state


def worker_num():
    from ..env import get_world_size

    return get_world_size()


def worker_index():
    from ..env import get_rank

    return get_rank()


def is_first_worker():
    return worker_index() == 0


def barrier_worker():
    from ..collective import barrier

    barrier()


# utils namespace parity (fleet.utils.recompute)
from .. import recompute as _recompute_mod  # noqa: E402


class utils:
    recompute = staticmethod(_recompute_mod.recompute)
    recompute_sequential = staticmethod(_recompute_mod.recompute_sequential)



# reference fleet __all__ completion
from ..topology import HybridTopology as HybridCommunicateGroup  # noqa: F401,E402
from ..topology import HybridTopology as CommunicateTopology  # noqa: F401,E402


class Fleet:
    """The fleet facade class (fleet/fleet.py Fleet); module-level
    init/distributed_model/... are the bound methods of the default
    instance, mirroring the reference's `fleet = Fleet()` singleton."""

    init = staticmethod(init)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)

    @staticmethod
    def is_first_worker():
        from .. import get_rank

        return get_rank() == 0

    @staticmethod
    def worker_index():
        from .. import get_rank

        return get_rank()

    @staticmethod
    def worker_num():
        from .. import get_world_size

        return get_world_size()


class UtilBase:
    """fleet.util role: tiny collective helpers over the topology."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        from .. import all_reduce as _ar

        return _ar(input)

    def barrier(self, comm_world="worker"):
        from .. import barrier as _b

        return _b()

    def get_file_shard(self, files):
        from .. import get_rank, get_world_size

        n = get_world_size()
        return files[get_rank()::max(n, 1)]


class Role:
    WORKER = 1
    SERVER = 2


def _ps_role_gate(name):
    class _Gate:
        def __init__(self, *a, **kw):
            raise NotImplementedError(
                f"{name} configures parameter-server roles, excluded by "
                "design (README Scope notes); collective mode needs no "
                "role maker")

    _Gate.__name__ = name
    return _Gate


UserDefinedRoleMaker = _ps_role_gate("UserDefinedRoleMaker")
PaddleCloudRoleMaker = _ps_role_gate("PaddleCloudRoleMaker")
MultiSlotDataGenerator = _ps_role_gate("MultiSlotDataGenerator")
MultiSlotStringDataGenerator = _ps_role_gate("MultiSlotStringDataGenerator")
