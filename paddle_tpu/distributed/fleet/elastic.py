"""Elastic training: node registry, heartbeats, membership watch, restart.

Role parity: `ElasticManager`
(`python/paddle/distributed/fleet/elastic/manager.py:126`, SURVEY §2.5/§5)
— etcd node registry + heartbeats, fault-tolerance levels, watch+restart
loop, `--nnodes=min:max` scale range, and the exit-code protocol the
launcher understands.

TPU-first: the registry rides the framework's own TCPStore (native tier,
`paddle_tpu/native/src/tcp_store.cc`) instead of etcd — one fewer external
service; membership changes trigger the same local-pod restart protocol
(on TPU pods a membership change also invalidates the mesh, so restart is
the correct granularity — XLA programs are compiled for a fixed topology).
"""
from __future__ import annotations

import os
import signal
import threading
import time

# exit-code protocol (manager.py:32-39 parity)
ELASTIC_EXIT_CODE = 101          # relaunch me with a new world
ELASTIC_AUTO_PARALLEL_EXIT_CODE = 102


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticLevel:
    FAULT_TOLERANCE = 1   # fixed world size, restart on failure
    ELASTIC = 2           # world may scale within [min, max]


class ElasticManager:
    def __init__(self, args=None, store=None, job_id=None, np_range=None,
                 heartbeat_interval=2.0, heartbeat_ttl=8.0):
        from ..store import TCPStore

        self.job_id = job_id or os.environ.get("PADDLE_JOB_ID", "default")
        rng = np_range or os.environ.get("PADDLE_ELASTIC_NP", "1")
        if isinstance(rng, str) and ":" in rng:
            lo, hi = rng.split(":")
            self.min_np, self.max_np = int(lo), int(hi)
        else:
            self.min_np = self.max_np = int(rng)
        self.elastic_level = (
            ElasticLevel.ELASTIC if self.max_np > self.min_np
            else ElasticLevel.FAULT_TOLERANCE)
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_ttl = heartbeat_ttl
        if store is not None:
            self.store = store
        else:
            master = os.environ.get("PADDLE_MASTER", "127.0.0.1:8476")
            host, port = master.split(":")
            self.store = TCPStore(host, int(port),
                                  is_master=(self.rank == 0))
        self._stop = threading.Event()
        self._thread = None
        self._membership_version = 0
        self.enabled = os.environ.get("PADDLE_ELASTIC_ENABLE",
                                      "1") not in ("0", "false")
        # heartbeat store traffic rides the resilience retry policy: a
        # transient TCPStore error (master restarting, tunnel blip) is
        # retried with backoff instead of silently dropping beats — and
        # a persistent one is COUNTED (resilience.giveups) while the
        # watch thread stays alive to beat again next interval
        from ...resilience.retry import RetryPolicy

        self._hb_retry = RetryPolicy(
            "elastic.heartbeat", max_attempts=3,
            base_delay=min(0.1, heartbeat_interval / 10.0),
            max_delay=max(0.25, heartbeat_interval / 2.0))
        self.missed_beats = 0
        self._done_marked = False
        self._telemetry_fn = None  # attach_telemetry(): digest provider

    # --- registry ------------------------------------------------------------
    def _hb_key(self, rank=None):
        r = self.rank if rank is None else rank
        return f"elastic/{self.job_id}/hb/{r}"

    def register(self):
        """Join the registry and start heartbeating (idempotent: a
        second register on a live manager is a no-op, and a register
        after exit() restarts the beat)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._hb_retry.call(self._set_heartbeat)
        self._thread = threading.Thread(target=self._beat, daemon=True,
                                        name="elastic-heartbeat")
        self._thread.start()

    def _set_heartbeat(self):
        from ...resilience import faults as _faults

        _faults.fire("store.op", op="heartbeat", rank=self.rank)
        self.store.set(self._hb_key(), str(time.time()))
        if self._telemetry_fn is not None:
            self._set_telemetry_digest()

    # --- telemetry digests ---------------------------------------------------
    def _tel_key(self, rank=None):
        r = self.rank if rank is None else rank
        return f"elastic/{self.job_id}/telemetry/{r}"

    def attach_telemetry(self, digest_fn):
        """Ride a small telemetry digest on every heartbeat (ISSUE 7):
        `digest_fn` is a zero-arg callable returning a JSON-friendly
        dict — typically `observability.export.TelemetryExporter
        .digest` — written next to this rank's heartbeat key, so
        `telemetry_digests()` answers "how is every live rank doing"
        from the store alone, with the freshness guarantee of the beat
        itself."""
        self._telemetry_fn = digest_fn
        return self

    def _set_telemetry_digest(self):
        import json as _json

        try:
            self.store.set(self._tel_key(),
                           _json.dumps(self._telemetry_fn(),
                                       default=str))
        except Exception:
            # the digest is best-effort cargo on the beat: losing it
            # must never cost the heartbeat (the retry policy would
            # re-raise and the rank would age out) — but count it
            try:
                from ...observability import metrics as _metrics

                _metrics.inc("fleet.telemetry_digest_errors")
            except Exception:  # pt-lint: ok[PT005]
                pass           # (observability fan-out guard: the
                # beat must go on through interpreter teardown)

    def telemetry_digests(self, scan_up_to=None):
        """{rank: digest dict} for every rank that published one —
        the live-fleet rollup view (`tools/telemetry_agg.py` reads the
        dump DIRECTORY for the full streams; this is the cheap
        store-side summary)."""
        import json as _json

        out = {}
        for r in range(scan_up_to if scan_up_to is not None
                       else self.max_np):
            try:
                raw = self.store.get(self._tel_key(r), timeout=0.5)
                out[r] = _json.loads(raw)
            except Exception:  # pt-lint: ok[PT005]
                continue       # absent key IS the signal: rank never
                # published (or its beat aged out with it)
        return out

    def _beat(self):
        while not self._stop.is_set():
            try:
                self._hb_retry.call(self._set_heartbeat)
            except Exception:
                # beats missed past the retry budget: the registry will
                # age this rank out after heartbeat_ttl — but the thread
                # MUST survive to resume beating if the store comes back
                # (a dead watch thread turns one transient blip into a
                # permanent eviction)
                self.missed_beats += 1
            self._stop.wait(self.heartbeat_interval)

    def alive_ranks(self, scan_up_to=None):
        """Ranks with fresh heartbeats, scanned over the FULL scale range
        (so joins beyond the current world — scale-out — are visible)."""
        now = time.time()
        alive = []
        for r in range(scan_up_to if scan_up_to is not None else self.max_np):
            try:
                ts = float(self.store.get(self._hb_key(r), timeout=0.5))
            except Exception:
                # an absent key IS the signal (rank not registered /
                # aged out) — but count the scan miss so a store that
                # errors on every rank is distinguishable from a world
                # that is genuinely down to one rank
                try:
                    from ...observability import metrics as _metrics

                    _metrics.inc("resilience.heartbeat_scan_misses")
                except Exception:  # pt-lint: ok[PT005]
                    pass           # (observability fan-out guard: the
                    # membership scan must survive interpreter teardown)
                continue
            if now - ts <= self.heartbeat_ttl:
                alive.append(r)
        return alive

    # --- watch ---------------------------------------------------------------
    def watch(self, world_size):
        """One membership check. Returns an ElasticStatus.

        After a RESTART the relaunched script must derive its NEW world from
        the registry (`len(alive_ranks())`), not from the stale
        PADDLE_TRAINERS_NUM env — the launcher restarts the local pod; the
        world resize happens at rendezvous.
        """
        alive = self.alive_ranks()
        n = len(alive)
        if n == world_size:
            return ElasticStatus.COMPLETED if self._job_done() \
                else ElasticStatus.HOLD
        if self.elastic_level == ElasticLevel.FAULT_TOLERANCE:
            # fixed world: any membership change means restart-and-rejoin;
            # the launcher's max_restart caps repeated failures
            self._membership_version += 1
            return ElasticStatus.RESTART
        if n >= self.min_np:
            # scale-in or scale-out within [min, max]: relaunch on the new
            # membership
            self._membership_version += 1
            return ElasticStatus.RESTART
        return ElasticStatus.ERROR

    def _job_done(self):
        try:
            return self.store.check(f"elastic/{self.job_id}/done")
        except Exception:
            return False

    def mark_done(self):
        self.store.set(f"elastic/{self.job_id}/done", "1")

    def exit(self, completed=True):
        """Stop heartbeating and (rank 0, completed=True) mark the job
        done.  Idempotent on BOTH effects independently: repeated
        exit()/stop() calls — launcher teardown racing a signal handler
        racing atexit — are safe, and a stop() followed by a genuine
        exit(completed=True) still marks done (the done-marker has its
        own once-guard, not the stop flag's)."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2)
        if t is None or t is threading.current_thread() \
                or not t.is_alive():
            self._thread = None
        # else: the beat thread is stuck in a blocked store call — KEEP
        # the handle so register() refuses to spawn a duplicate; _stop
        # stays set, so the orphan exits when the call finally returns
        if completed and self.rank == 0 and not self._done_marked:
            try:
                self.mark_done()
                self._done_marked = True
            except Exception as e:
                # an unmarked done means the other ranks will treat the
                # next membership change as a failure and restart — a
                # state worth a flight event, not a silent shrug
                try:
                    from ...observability import flight as _flight

                    _flight.record(
                        "resilience.elastic_mark_done_failed",
                        job_id=self.job_id,
                        error=f"{type(e).__name__}: {e}")
                except Exception:  # pt-lint: ok[PT005]
                    pass           # (observability fan-out guard:
                    # exit() runs in signal/atexit paths and must
                    # never raise)

    def stop(self):
        """Generic teardown (failure paths, signal handlers, atexit):
        stops heartbeating WITHOUT marking the job done — only an
        explicit exit(completed=True) may cancel the restart protocol
        for the other ranks."""
        self.exit(completed=False)

    shutdown = stop

    # --- preemption ----------------------------------------------------------
    def attach_preemption_guard(self, guard, install=True):
        """Cooperative preemption (docs/RESILIENCE.md): when `guard`
        (resilience.preemption.PreemptionGuard) trips, this rank STOPS
        heartbeating — it ages out of membership at heartbeat_ttl and
        the surviving ranks restart on the shrunk world — instead of
        the legacy hard `os._exit` that vanished mid-collective while
        its last fresh beat still advertised it alive.  The guard's
        exit_code is set to ELASTIC_EXIT_CODE so TrainingPreempted
        carries the launcher's relaunch protocol.  The training loop's
        safe point (DistributedTrainStep._check_preemption) does the
        checkpointing; this hook only handles membership."""
        if install:
            guard.install()
        guard.exit_code = ELASTIC_EXIT_CODE
        guard.on_preempt(self._on_preempt)
        self._preemption_guard = guard
        return guard

    def _on_preempt(self, reason):
        try:
            from ...observability import flight as _flight

            _flight.record("preemption.elastic_deregister",
                           job_id=self.job_id, rank=self.rank,
                           reason=reason)
        except Exception:  # pt-lint: ok[PT005]
            pass           # (observability fan-out guard: runs in
            # signal context — deregistration must still happen)
        self.stop()  # stop beating; TTL ages this rank out

    # --- restart protocol ----------------------------------------------------
    @staticmethod
    def request_relaunch():
        """Child signals the launcher: bring me back with a fresh world."""
        os._exit(ELASTIC_EXIT_CODE)

    @staticmethod
    def signal_handler(sig, frame):
        os._exit(ELASTIC_EXIT_CODE)

    def install_signal_handlers(self):
        """Legacy hard-exit handlers (immediate ELASTIC_EXIT_CODE, no
        checkpoint, no deregistration).  Prefer
        `attach_preemption_guard(PreemptionGuard())`: same relaunch
        protocol, but the training loop checkpoints at its next safe
        point and the rank leaves membership cleanly first."""
        signal.signal(signal.SIGTERM, self.signal_handler)
        signal.signal(signal.SIGINT, self.signal_handler)
