"""Auto-tuner: search hybrid-parallel configs, prune by memory, rank by cost.

Role parity: `python/paddle/distributed/auto_tuner/{tuner.py,search.py,
prune.py}` (SURVEY §2.5) — enumerate dp/mp/pp/sharding/micro-batch
combinations, prune those that exceed per-chip memory, and (reference:
relaunch trials; here:) rank by the analytic roofline and optionally run
user trials best-first.

TPU-first: pruning uses the v5p chip model in `paddle_tpu.cost_model`; mp
candidates prefer powers of two ≤ 8 that divide both head count and an ICI
axis; trials run in-process against a user callback (a jit'd step) instead
of relaunching pods — compile cache makes sequential in-process trials
cheap on TPU.
"""
from __future__ import annotations


from ..cost_model import (TransformerShape, V5P, memory_per_chip,
                          train_step_cost)

__all__ = ["AutoTuner", "Candidate", "default_candidates"]


class Candidate:
    __slots__ = ("dp", "mp", "pp", "sharding_stage", "micro_batch",
                 "recompute", "est_time_s", "est_mem_bytes")

    def __init__(self, dp, mp, pp, sharding_stage, micro_batch,
                 recompute=False):
        self.dp = dp
        self.mp = mp
        self.pp = pp
        self.sharding_stage = sharding_stage
        self.micro_batch = micro_batch
        self.recompute = recompute
        self.est_time_s = None
        self.est_mem_bytes = None

    def as_strategy(self):
        return {"dp_degree": self.dp, "mp_degree": self.mp,
                "pp_degree": self.pp, "sharding_stage": self.sharding_stage,
                "micro_batch_size": self.micro_batch,
                "recompute": self.recompute}

    def __repr__(self):
        t = f", est={self.est_time_s:.3f}s" if self.est_time_s else ""
        return (f"Candidate(dp={self.dp}, mp={self.mp}, pp={self.pp}, "
                f"zero={self.sharding_stage}, mbs={self.micro_batch}, "
                f"rc={self.recompute}{t})")


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def default_candidates(n_chips, global_batch, num_heads, num_layers,
                       sharding_stages=(0, 1, 2, 3), allow_recompute=True):
    out = []
    for mp in [d for d in _divisors(n_chips)
               if d <= 8 and num_heads % d == 0]:
        for pp in [d for d in _divisors(n_chips // mp)
                   if num_layers % d == 0]:
            dp = n_chips // mp // pp
            if dp * mp * pp != n_chips or global_batch % dp != 0:
                continue
            per_dp = global_batch // dp
            for mbs in _divisors(per_dp):
                if mbs > 64:
                    continue
                for st in sharding_stages:
                    if st > 0 and dp == 1:
                        continue
                    for rc in ((False, True) if allow_recompute
                               else (False,)):
                        out.append(Candidate(dp, mp, pp, st, mbs, rc))
    return out


class AutoTuner:
    def __init__(self, model_shape, n_chips, global_batch, chip=V5P,
                 n_hosts=1, mem_fraction=0.9):
        if not isinstance(model_shape, TransformerShape):
            raise TypeError("model_shape must be a TransformerShape")
        self.shape = model_shape
        self.n_chips = n_chips
        self.global_batch = global_batch
        self.chip = chip
        self.n_hosts = n_hosts
        self.mem_budget = chip.hbm_bytes * mem_fraction
        self.history = []

    def prune(self, candidates):
        kept = []
        for c in candidates:
            mem = memory_per_chip(self.shape, c.micro_batch, c.dp, c.mp,
                                  c.pp, c.sharding_stage, c.recompute)
            c.est_mem_bytes = mem
            if mem <= self.mem_budget:
                kept.append(c)
        return kept

    def rank(self, candidates):
        for c in candidates:
            c.est_time_s = train_step_cost(
                self.shape, self.global_batch, c.micro_batch, c.dp, c.mp,
                c.pp, c.sharding_stage, self.chip, self.n_hosts).total_s
        return sorted(candidates, key=lambda c: c.est_time_s)

    def search(self, candidates=None):
        """Prune + rank; returns candidates best-first."""
        if candidates is None:
            candidates = default_candidates(
                self.n_chips, self.global_batch, self.shape.heads,
                self.shape.L)
        return self.rank(self.prune(candidates))

    def tune(self, trial_fn, candidates=None, max_trials=5):
        """Run real trials best-first: trial_fn(candidate) -> measured
        seconds (or raise/return None to reject). Returns the best
        (candidate, time)."""
        ranked = self.search(candidates)
        best = None
        for c in ranked[:max_trials]:
            try:
                t = trial_fn(c)
            except Exception as e:  # OOM / compile failure prunes the point
                self.history.append((c, None, repr(e)))
                continue
            if t is None:
                continue
            self.history.append((c, t, None))
            if best is None or t < best[1]:
                best = (c, t)
        return best
