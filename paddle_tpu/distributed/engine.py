"""Static auto-parallel Engine with a cost-model planner.

Role parity: `python/paddle/distributed/auto_parallel/static/engine.py:59`
(Engine: completion → partition → reshard → execute) and the planner the
reference drives from its op-level cost model (`auto_parallel/static/
cost/`, `tuner/`).

TPU-first collapse of that pipeline:
  * completion + partition + reshard == sharding annotations on one
    compiled train step (XLA GSPMD propagates; `DistributedTrainStep`
    pins param/state shardings) — there is no separate program rewrite;
  * the piece that still needs an explicit algorithm is the PLAN — which
    (dp, mp, pp, sharding, micro-batch) factorization of the mesh to
    use. `plan()` derives a TransformerShape from the model, enumerates
    feasible factorizations, prunes by the per-chip memory model, ranks
    by the analytic step-time cost model (`paddle_tpu.cost_model`), and
    returns candidates best-first (AutoTuner underneath).

`Engine.prepare()` plans (unless a strategy is forced), initializes the
hybrid topology, and builds the compiled step; `fit`/`evaluate` run it.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Engine", "plan"]


def _infer_shape(model, seq_len=1024, global_batch=32):
    """Best-effort TransformerShape from a model's config or parameters."""
    from ..cost_model import TransformerShape

    cfg = getattr(model, "config", None)
    inner = getattr(model, "network", None) or getattr(model, "model", None)
    if cfg is None and inner is not None:
        cfg = getattr(inner, "config", None)
    if cfg is not None and hasattr(cfg, "hidden_size"):
        return TransformerShape(
            hidden=cfg.hidden_size,
            ffn_hidden=getattr(cfg, "ffn_hidden", None)
            or 4 * cfg.hidden_size,
            num_heads=cfg.num_heads,
            # the WORKLOAD's sequence length prices compute and comm
            # commensurately (cfg.max_seq_len only caps it) — costing at
            # max_seq_len while measuring comm at seq_len would skew the
            # re-rank whenever they differ
            seq_len=min(seq_len, getattr(cfg, "max_seq_len", seq_len)),
            vocab_size=getattr(cfg, "vocab_size", 50304),
            num_layers=cfg.num_layers)
    # fall back: estimate from parameter shapes (largest 2-D weight is
    # the vocab projection; most-common square dim is the hidden size)
    dims = {}
    vocab, hidden = 0, 0
    n_layers = 0
    for name, p in model.named_parameters():
        if len(p.shape) == 2:
            a, b = int(p.shape[0]), int(p.shape[1])
            vocab = max(vocab, max(a, b))
            if a == b:
                dims[a] = dims.get(a, 0) + 1
            n_layers += 1
    hidden = max(dims, key=dims.get) if dims else 768
    return TransformerShape(hidden=hidden, ffn_hidden=4 * hidden,
                            num_heads=max(1, hidden // 64),
                            seq_len=seq_len, vocab_size=max(vocab, hidden),
                            num_layers=max(1, n_layers // 6))


def plan(model, n_devices=None, global_batch=32, seq_len=1024, chip=None,
         n_hosts=1, top_k=5):
    """Rank hybrid-parallel strategies for `model` on `n_devices` chips.

    Returns AutoTuner candidates best-first; each carries
    `est_time_s` / `est_mem_bytes` and `.as_strategy()` for fleet.init.
    """
    import jax

    from .auto_tuner import AutoTuner
    from ..cost_model import V5P

    n_devices = n_devices or jax.device_count()
    shape = _infer_shape(model, seq_len, global_batch)
    tuner = AutoTuner(shape, n_devices, global_batch, chip=chip or V5P,
                      n_hosts=n_hosts)
    ranked = tuner.search()
    if not ranked:
        raise RuntimeError(
            f"no feasible parallel plan for {n_devices} devices / "
            f"global batch {global_batch} under the memory model")
    return ranked[:top_k]


def _strategy_from_dict(d):
    """Candidate.as_strategy() dict → DistributedStrategy (one shared
    conversion: search() measures and prepare() builds the SAME config)."""
    from . import fleet

    stage = d.get("sharding_stage", 0)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": d.get("dp_degree", 1),
        "mp_degree": d.get("mp_degree", 1),
        "pp_degree": d.get("pp_degree", 1),
        "sep_degree": d.get("sep_degree", 1),
        # ZeRO shards over the dp axis unless explicitly set
        "sharding_degree": d.get("sharding_degree",
                                 d.get("dp_degree", 1) if stage else 1),
    }
    if stage:
        # what build_train_step actually reads (fleet.__init__):
        # strategy.sharding + sharding_configs["stage"]
        strategy.sharding = True
        strategy.sharding_configs = {"stage": stage}
    return strategy


class Engine:
    """Plan → topology → compiled step → run (static Engine role)."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics or []
        self.strategy = strategy
        self.plan_result = None
        self._step = None
        self._wrapped = None

    # --- planning -----------------------------------------------------------
    def _ensure_prepared(self, global_batch=32, seq_len=1024):
        if self._step is not None:
            return
        import jax

        from . import fleet, topology

        if self.strategy is None:
            cands = plan(self.model, jax.device_count(), global_batch,
                         seq_len)
            self.plan_result = cands[0]
            self.strategy = self.plan_result.as_strategy()
        strategy = self.strategy
        if isinstance(strategy, dict):  # a Candidate.as_strategy() dict
            strategy = _strategy_from_dict(strategy)
        topology.reset_topology()
        fleet.init(is_collective=True, strategy=strategy)
        # search() leaves factories behind: rebuild the net under the
        # winning topology (TP layers read mesh degrees at construction).
        # A rebuilt model also invalidates any pre-existing optimizer —
        # its parameter list references the discarded instance.
        rebuilt = getattr(self, "_model_factory", None) is not None
        if rebuilt:
            self.model = self._model_factory()
        self._wrapped = fleet.distributed_model(self.model)
        opt = self.optimizer
        if getattr(self, "_opt_factory", None) is not None and (
                opt is None or rebuilt):
            opt = self._opt_factory(self._wrapped.parameters())
        opt = fleet.distributed_optimizer(opt)
        self._step = self._wrapped.build_train_step(
            opt, self.loss, amp_dtype="bfloat16")

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train",
                global_batch=32, seq_len=1024):
        self._ensure_prepared(global_batch, seq_len)
        return self

    def search(self, model_factory, optimizer_factory, sample_batch,
               global_batch=8, seq_len=32, top_k=3, chip=None):
        """Placement search closed on compiler ground truth (VERDICT r4
        Next #6; reference `auto_parallel/static/engine.py:59` + `tuner/`
        explore placements — here the explore loop is: enumerate → rank
        analytically → compile the leaders → re-rank on measured comm).

        1. Enumerate (dp, mp, zero, micro-batch) factorizations of the
           live mesh and rank by the analytic cost model (AutoTuner).
        2. For the ``top_k`` compilable leaders (pp=1 — pipeline plans
           rank analytically but execute through PipelineParallel, not
           this step builder), build the hybrid step under that topology
           and read the collectives XLA/GSPMD *actually* inserted
           (`completion.collective_report`).
        3. Audit the predicted comm bytes
           (`cost_model.comm_bytes_per_step`) against the measured bytes
           and re-rank by the cost estimate with the comm term replaced
           by the measured bytes — a mispredicted plan can no longer win
           on its misprediction.

        ``model_factory``/``optimizer_factory`` rebuild the net under
        each candidate topology (TP layers pick up mesh degrees at
        construction). ``sample_batch`` is an (inputs, labels) pair of
        numpy arrays at the global batch size used to trace the step.

        Returns ``(best, trials)``: ``best`` is the winning trial dict
        (its ``"strategy"`` feeds fleet.init / Engine(strategy=...)),
        ``trials`` has one entry per validated candidate with
        ``predicted_bytes`` / ``measured_bytes`` / ``agreement`` /
        ``measured_time_s``. The Engine's own strategy is set to the
        winner."""
        import jax

        import paddle_tpu as P
        from . import completion, fleet, topology
        from ..cost_model import V5P, comm_bytes_per_step

        chip = chip or V5P
        n_devices = jax.device_count()
        shape = _infer_shape(self.model, seq_len, global_batch)
        cands = plan(self.model, n_devices, global_batch, seq_len,
                     chip=chip, top_k=max(top_k * 4, 8))
        xs, ys = sample_batch
        trials = []
        for cand in cands:
            if len(trials) >= top_k:
                break
            if cand.pp > 1 or global_batch % cand.dp != 0:
                continue
            topology.reset_topology()
            fleet.init(is_collective=True,
                       strategy=_strategy_from_dict(cand.as_strategy()))
            P.seed(0)
            model = fleet.distributed_model(model_factory())
            opt = fleet.distributed_optimizer(
                optimizer_factory(model.parameters()))
            step = model.build_train_step(opt, self.loss,
                                          amp_dtype="bfloat16")
            report = completion.analyze(
                step, P.to_tensor(xs), P.to_tensor(ys))
            measured = report["collectives"]["total_bytes"]
            n_params = sum(int(np.prod(p.shape))
                           for p in model.parameters())
            pred = comm_bytes_per_step(
                n_params, local_batch=global_batch // cand.dp,
                seq=seq_len, hidden=shape.h, num_layers=shape.L,
                dp=cand.dp, mp=cand.mp,
                sharding_stage=cand.sharding_stage)
            # re-rank: the analytic compute/memory roofline with the comm
            # term re-priced at the MEASURED bytes (ring steps ~ 2x
            # payload/bw). Rebuilt from train_step_cost's components —
            # subtracting a differently-modelled comm estimate from
            # est_time_s would not cancel and can go negative.
            from ..cost_model import train_step_cost

            est = train_step_cost(
                shape, global_batch, cand.micro_batch, dp=cand.dp,
                mp=cand.mp, pp=1, sharding_stage=cand.sharding_stage,
                chip=chip)
            measured_comm_s = 2.0 * measured / chip.ici_bw
            measured_time = max(est.compute_s, est.memory_s) + \
                measured_comm_s
            trials.append({
                "strategy": cand.as_strategy(),
                "candidate": repr(cand),
                "predicted_bytes": pred["total"],
                "predicted_by_kind": pred["by_kind"],
                "measured_bytes": measured,
                "measured_by_kind": report["collectives"]["totals"],
                "agreement": pred["total"] / max(measured, 1),
                "est_time_s": cand.est_time_s,
                "measured_time_s": measured_time,
            })
        if not trials:
            raise RuntimeError("no compilable (pp=1) candidate to search")
        best = min(trials, key=lambda t: t["measured_time_s"])
        self.strategy = best["strategy"]
        self.plan_result = None
        self._step = None  # prepare() rebuilds under the winner
        self._model_factory = model_factory
        self._opt_factory = optimizer_factory
        return best, trials

    def cost(self, mode="train"):
        """Planner estimate for the chosen strategy (reference
        Engine.cost): dict with step time and per-chip memory."""
        if self.plan_result is None:
            return None
        return {"est_step_time_s": self.plan_result.est_time_s,
                "est_memory_bytes": self.plan_result.est_mem_bytes,
                "strategy": repr(self.plan_result)}

    def analyze(self, *batch, verbose=False):
        """Compiler ground truth for the prepared step (completion +
        reshard evidence, `distributed.completion`): the shardings GSPMD
        assigned and the collectives it inserted, to audit the planner's
        claims against the program that will actually run."""
        from . import completion

        if self._step is None:  # auto-prepare from the batch, like fit()
            self._ensure_prepared(
                global_batch=int(np.shape(
                    batch[0]._value if hasattr(batch[0], "_value")
                    else batch[0])[0]))
        report = completion.analyze(self._step, *batch)
        if verbose:
            print(completion.format_report(report))
        return report

    # --- running ------------------------------------------------------------
    def fit(self, train_data, epochs=1, batch_size=None, steps_per_epoch=None,
            valid_data=None, log_freq=10):
        from ..io import DataLoader, Dataset

        loader = DataLoader(train_data, batch_size=batch_size or 8) \
            if isinstance(train_data, Dataset) else train_data
        first = next(iter(loader))
        self._ensure_prepared(global_batch=int(np.shape(first[0])[0]))
        history = []
        for _ in range(epochs):
            for step, batch in enumerate(loader):
                loss = self._step(*batch)
                history.append(float(np.asarray(loss._value)))
                if steps_per_epoch and step + 1 >= steps_per_epoch:
                    break
        return history

    def evaluate(self, eval_data, batch_size=None):
        from ..io import DataLoader, Dataset

        loader = DataLoader(eval_data, batch_size=batch_size or 8) \
            if isinstance(eval_data, Dataset) else eval_data
        self.model.eval()
        total, n = 0.0, 0
        import paddle_tpu as P

        with P.no_grad():
            for batch in loader:
                out = self.model(batch[0])
                loss = self.loss(out, batch[1])
                total += float(np.asarray(
                    loss._value if hasattr(loss, "_value") else loss))
                n += 1
        self.model.train()
        return {"loss": total / max(1, n)}

    def predict(self, data, batch_size=None):
        from ..io import DataLoader, Dataset

        loader = DataLoader(data, batch_size=batch_size or 8) \
            if isinstance(data, Dataset) else data
        self.model.eval()
        outs = []
        import paddle_tpu as P

        with P.no_grad():
            for batch in loader:
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                outs.append(np.asarray(self.model(x).numpy()))
        self.model.train()
        return outs
