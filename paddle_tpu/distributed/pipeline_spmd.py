"""SPMD collective pipeline parallelism: ONE jit program over the global
mesh, stage shifts via `lax.ppermute` — multi-host-ready by construction.

Role parity: the reference's cross-rank pipeline runtime — the send/recv
tier (`fleet/meta_parallel/pp_utils/p2p_communication.py`) plus the
schedule loops (`fleet/meta_parallel/pipeline_parallel.py:440`) — rebuilt
the TPU-native way: every stage's parameters live stacked along a `pp`
mesh axis, all devices run the SAME compiled program, and the boundary
activation shifts one stage per tick through `ppermute` (XLA
collective-permute, riding ICI/DCN like any other collective). The
single-controller tier (`pipeline.py`: per-stage jit programs + async
device_put boundaries, dispatch-order 1F1B) cannot cross process
boundaries — a process cannot jit onto devices it does not own. This tier
can: under multi-process JAX every process executes the same program and
XLA moves the boundary activations between hosts.

Autodiff reverses the schedule for free: the transpose of a forward
ppermute(i -> i+1) is ppermute(i+1 -> i), so `jax.grad` of the scanned
forward IS the backward pipeline — no hand-written reverse schedule, no
SendRecvMeta handshakes.

Memory model: GPipe-style — boundary activations for all `m` microbatches
persist until backward (the classic collective-pipeline trade, cf. GSPMD
pipelining). `remat_stage=True` wraps the stage in `jax.checkpoint`, so
per microbatch ONLY the boundary activation is saved and stage internals
recompute in backward: per-device residual footprint O(m * |act|). The
dispatch-order 1F1B tier in `pipeline.py` keeps the lower-memory schedule
for single-process meshes; this module is the one-program tier that
scales past one process.

Bubble fraction is the GPipe (pp-1)/(m+pp-1); the schedule runs
m + pp - 1 ticks and every device computes every tick (devices outside
their active window compute on zeros — in SPMD the bubble is wasted FLOPs,
not idleness, which is exactly how GSPMD-pipelined TPU programs behave).

No interleaved (VPP) variant here, by design: VPP's bubble win comes from
interleaving FORWARD and BACKWARD micro-steps, and in this tier the
backward order belongs to autodiff (that is the point — the reverse
schedule is derived, not hand-written). Interleaved 1F1B lives in the
per-stage tier (`pipeline.py`), which owns its backward explicitly.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
try:  # jax>=0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # jax 0.4.x: experimental namespace
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

__all__ = ["stack_stages", "spmd_pipeline", "spmd_pipeline_reference"]


def stack_stages(per_stage_params):
    """[pytree] * pp (identical treedefs, identical leaf shapes) ->
    one pytree whose every leaf gains a leading [pp] dim. The inverse of
    what each device sees inside `spmd_pipeline` (its own stage's slice).
    """
    if len(per_stage_params) == 0:
        raise ValueError("stack_stages: need at least one stage")
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def spmd_pipeline_reference(stage_fn, per_stage_params, x_mb):
    """Sequential semantics `spmd_pipeline` must reproduce: every
    microbatch through every stage in order (the parity oracle for
    tests; also the pp=1 execution path)."""
    def one(xb):
        for p in per_stage_params:
            xb = stage_fn(p, xb)
        return xb

    return jax.lax.map(one, x_mb)


def spmd_pipeline(stage_fn, stage_params, x_mb, mesh=None, axis="pp",
                  remat_stage=False):
    """Run `x_mb` microbatches through a `pp`-stage pipeline as one SPMD
    program.

    stage_fn(params_i, act) -> act        (shape- and dtype-preserving)
    stage_params: pytree with a leading [pp] dim on every leaf
                  (`stack_stages`), sharded/shardable over `axis`
    x_mb: [m, ...] microbatches entering stage 0 (replicated over `axis`;
          other mesh axes stay with the compiler — `shard_map` runs in
          partial-manual mode over `axis` alone, so dp/mp/sep sharding
          inside the stage is still GSPMD's job)
    Returns [m, ...] outputs of the LAST stage, replicated over `axis`.
    """
    if mesh is None:
        from . import topology as topo_mod

        mesh = topo_mod.current_spmd_mesh()
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no '{axis}' axis: {mesh.shape}")
    pp = mesh.shape[axis]
    lead = {l.shape[0] for l in jax.tree_util.tree_leaves(stage_params)}
    if lead != {pp}:
        raise ValueError(
            f"stage_params leaves must carry a leading [pp={pp}] dim "
            f"(stack_stages); got leading dims {sorted(lead)}")
    if pp == 1:
        fn = jax.checkpoint(stage_fn) if remat_stage else stage_fn
        p0 = jax.tree_util.tree_map(lambda l: l[0], stage_params)
        return spmd_pipeline_reference(fn, [p0], x_mb)
    treedef = jax.tree_util.tree_structure(stage_params)
    compiled = _compiled_pipeline(stage_fn, mesh, axis, pp, remat_stage,
                                  treedef)
    return compiled(stage_params, x_mb)


@functools.lru_cache(maxsize=64)
def _compiled_pipeline(stage_fn, mesh, axis, pp, remat_stage, treedef):
    """One jitted pipeline program per (stage_fn, mesh, axis, pp, remat,
    param treedef): an eager caller in a loop hits jit's compile cache
    instead of rebuilding (and retracing) a fresh closure per call. The
    jit is also load-bearing for eager use at all — shard_map cannot
    eagerly evaluate closed_call bodies (a lax.scan inside stage_fn)."""
    fn = jax.checkpoint(stage_fn) if remat_stage else stage_fn

    def body(params_local, xloc):
        # shard_map hands each device its [1, ...] stage slice
        params_i = jax.tree_util.tree_map(lambda l: l[0], params_local)
        m = xloc.shape[0]
        sid = jax.lax.axis_index(axis)
        perm = [(i, i + 1) for i in range(pp - 1)]
        # carries must enter the scan already marked varying-over-pp:
        # the tick output is (per-device activations differ), and scan
        # requires carry-in/out types — including the vma component —
        # to match
        if hasattr(jax.lax, "pcast"):
            act0 = jax.lax.pcast(jnp.zeros_like(xloc[0]), axis,
                                 to="varying")
            ys0 = jax.lax.pcast(jnp.zeros_like(xloc), axis, to="varying")
        else:
            # jax 0.4.x has no varying-manual-axes tracking (check_rep
            # era): the carries need no vma marking there
            act0 = jnp.zeros_like(xloc[0])
            ys0 = jnp.zeros_like(xloc)

        def tick(carry, t):
            act, ys = carry
            # previous tick's outputs move one stage down the ring;
            # stage 0 instead ingests the next microbatch (a clamped
            # index past m re-feeds the last one — those ticks' results
            # never reach the collection window)
            shifted = jax.lax.ppermute(act, axis, perm)
            inj = jax.lax.dynamic_index_in_dim(
                xloc, jnp.minimum(t, m - 1), 0, keepdims=False)
            act_in = jnp.where(sid == 0, inj, shifted)
            act_out = fn(params_i, act_in)
            # the last stage emits microbatch t-(pp-1) at tick t
            idx = jnp.clip(t - (pp - 1), 0, m - 1)
            cur = jax.lax.dynamic_index_in_dim(ys, idx, 0, keepdims=False)
            keep = jnp.where(t >= pp - 1, act_out, cur)
            ys = jax.lax.dynamic_update_index_in_dim(ys, keep, idx, 0)
            return (act_out, ys), None

        (_, ys), _ = jax.lax.scan(tick, (act0, ys0),
                                  jnp.arange(m + pp - 1))
        # only the last stage holds real outputs; the masked psum makes
        # them global (its transpose routes the cotangent straight back
        # to the last stage — the backward pipeline's entry point)
        ys = jax.lax.psum(
            jnp.where(sid == pp - 1, ys, jnp.zeros_like(ys)), axis)
        return ys

    pspecs = jax.tree_util.tree_unflatten(
        treedef, [P(axis)] * treedef.num_leaves)
    try:
        mapped = shard_map(
            body,
            mesh=mesh,
            in_specs=(pspecs, P()),
            out_specs=P(),
            axis_names=frozenset({axis}),
        )
    except TypeError:
        # jax 0.4.x: no axis_names — the manual-axes set is expressed as
        # its complement via `auto` (axes left to the compiler), and its
        # replication checker predates vma marking (mis-flags the
        # pipeline's ppermute carries), so it is disabled
        mapped = shard_map(
            body,
            mesh=mesh,
            in_specs=(pspecs, P()),
            out_specs=P(),
            auto=frozenset(mesh.axis_names) - {axis},
            check_rep=False,
        )
    return jax.jit(mapped)
