"""Distributed environment (parity: `paddle.distributed.parallel.ParallelEnv`
+ launcher env conventions `PADDLE_TRAINER_*`).

On the jax runtime, a "rank" is a host process in a multi-host program
(`jax.process_index()`); within one host all local devices belong to the same
process (single-controller), so most single-host "multi-rank" behavior is
expressed as sharding over the device mesh instead. Env vars mirror the
reference's so launcher-style scripts port over unchanged.
"""
from __future__ import annotations

import os

import jax


def get_rank(group=None):
    if group is not None:
        return group.get_rank()
    return int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))


def get_world_size(group=None):
    if group is not None:
        return group.get_world_size()
    n = os.environ.get("PADDLE_TRAINERS_NUM")
    if n is not None:
        return int(n)
    return jax.process_count()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", 0))

    @property
    def dev_id(self):
        return self.local_rank

    @property
    def nranks(self):
        return self.world_size

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else [self.current_endpoint]
