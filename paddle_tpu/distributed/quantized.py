"""EQuARX-style quantized collective tier (ISSUE 11, PAPERS.md: *EQuARX:
Efficient Quantized AllReduce in XLA*).

Gradient collectives dominate the wire time of data-parallel training at
pod scale; EQuARX shows the allreduce payload can ride ICI in int8 (with
per-block scales) or bf16 at a small, bounded accuracy cost.  This module
is the repo's single source of truth for that tier:

* ``collective_precision()``   — the ``PADDLE_TPU_COLLECTIVE_PRECISION``
  knob (``f32``/``full``/unset → None = exact collectives; ``bf16``;
  ``int8``).  Invalid values fail loudly at build time, not mid-train.
* ``quantize_chunked`` / ``dequantize_chunked`` — the chunked int8 codec:
  per-chunk absmax scales (CHUNK=256 elements), symmetric round-to-nearest
  into [-127, 127].  A zero chunk quantizes to zeros (scale clamped to 1),
  never NaN.  Since ISSUE 12 the codec itself lives in
  ``paddle_tpu/ops/quant.py`` (re-exported here): the engine's int8
  weight tier and the quantized KV page pool share the same
  scale/encode definitions, pinned by a bit-equivalence test.
* ``qdq(x, precision)``        — in-jit payload emulation for the
  GSPMD-partitioned train step: quantize→dequantize the gradient payload
  the compiler-scheduled reduce-scatter will move.  (Inside one jit
  program the partitioner owns the wire, so the codec is applied to the
  gradient value; the true quantize→REDUCE→dequantize wire recipe lives
  in the shard_map tier below and is what a hand-scheduled TPU collective
  runs.  docs/SHARDING.md "Precision knob" states the distinction.)
* ``psum`` / ``psum_scatter``  — the wire-honest shard_map tier used by
  ``distributed.collective`` (eager collectives): per-chunk scales are
  SHARED across replicas first (one small pmax), each replica quantizes
  its local partial, the reduction runs over int32 (no int8 overflow up
  to dp·127 per element), and the result dequantizes with the shared
  scales — the EQuARX recipe, minus the XLA-internal fusion.

Everything here is pure jax-traceable math (usable inside jit/shard_map)
with no framework deps, so the train step, the collective API, and the
tests share one codec.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..ops.quant import (  # noqa: F401  (re-exported: this module was
    # the codec's original home; the engine's weight/KV tiers and the
    # wire tier now share ops/quant.py as the ONE definition — ISSUE 12)
    CHUNK, _as_chunks, dequantize_chunked, quantize_chunked,
)
from ..ops.quant import encode_int8 as _encode
from ..ops.quant import scales_from_absmax as _scales_of

__all__ = [
    "CHUNK", "collective_precision", "quantize_chunked",
    "dequantize_chunked", "qdq", "psum", "psum_scatter",
]

_VALID = {"": None, "f32": None, "full": None, "fp32": None,
          "bf16": "bf16", "int8": "int8"}

ENV_KNOB = "PADDLE_TPU_COLLECTIVE_PRECISION"


def collective_precision(explicit=None):
    """Resolve the collective-precision tier: an explicit argument wins,
    else the ``PADDLE_TPU_COLLECTIVE_PRECISION`` env knob.  Returns
    ``None`` (exact), ``"bf16"`` or ``"int8"``."""
    raw = explicit if explicit is not None else os.environ.get(ENV_KNOB, "")
    key = str(raw).strip().lower()
    if key not in _VALID:
        raise ValueError(
            f"{ENV_KNOB}={raw!r}: expected one of "
            f"{sorted(k for k in _VALID if k)} (or unset for exact "
            f"f32 collectives)")
    return _VALID[key]


def _quantizable(x):
    """Only floating payloads ride the lossy codec: an int32 sum (a
    token/sample count, a step counter) must stay EXACT — quantizing it
    would silently corrupt values the caller believes are integers."""
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def qdq(x, precision, chunk=CHUNK):
    """Quantize→dequantize ``x`` through the tier's payload codec
    (identity for ``None`` and for non-floating payloads).  Output
    dtype matches the input."""
    if precision is None or not _quantizable(x):
        return x
    if precision == "bf16":
        return x.astype(jnp.bfloat16).astype(x.dtype)
    if precision == "int8":
        q, scales, pad = quantize_chunked(x, chunk)
        return dequantize_chunked(q, scales, jnp.shape(x), pad) \
            .astype(x.dtype)
    raise ValueError(f"unknown collective precision {precision!r}")


# ----------------------- shard_map wire tier -----------------------


def psum(x, axis, precision, chunk=CHUNK):
    """Quantized all-reduce body (call inside shard_map with ``axis``
    bound): shared per-chunk scales (pmax), int32-accumulated psum of
    int8 payloads, dequantize.  ``precision=None`` → plain psum;
    non-floating payloads always reduce exactly."""
    if precision is None or not _quantizable(x):
        return jax.lax.psum(x, axis)
    if precision == "bf16":
        return jax.lax.psum(x.astype(jnp.bfloat16), axis) \
            .astype(jnp.float32 if x.dtype == jnp.float32 else x.dtype)
    ch, pad = _as_chunks(x.astype(jnp.float32), chunk)
    absmax = jnp.max(jnp.abs(ch), axis=1)
    absmax = jax.lax.pmax(absmax, axis)  # one shared scale per chunk
    scales = _scales_of(absmax)
    q = _encode(ch, scales[:, None]).astype(jnp.int32)
    s = jax.lax.psum(q, axis)
    out = s.astype(jnp.float32) * scales[:, None]
    flat = out.reshape(-1)
    if pad:
        flat = flat[:flat.size - pad]
    return flat.reshape(jnp.shape(x)).astype(x.dtype)


def psum_scatter(x, axis, axis_size, precision, chunk=CHUNK):
    """Quantized reduce-scatter body (inside shard_map): ``x`` is this
    replica's ``[D0, ...]`` partial with ``D0 % axis_size == 0``; returns
    the summed ``[D0/axis_size, ...]`` slice owned by this replica.
    Chunks are laid out per destination slice so every replica
    dequantizes its own slice with the shared scales.  Non-floating
    payloads always reduce exactly."""
    if precision is None or not _quantizable(x):
        return jax.lax.psum_scatter(x, axis, scatter_dimension=0,
                                    tiled=True)
    d0 = x.shape[0]
    if d0 % axis_size:
        raise ValueError(
            f"reduce_scatter dim0 {d0} not divisible by axis size "
            f"{axis_size}")
    per = d0 // axis_size
    out_shape = (per,) + tuple(x.shape[1:])
    if precision == "bf16":
        s = jax.lax.psum_scatter(x.astype(jnp.bfloat16), axis,
                                 scatter_dimension=0, tiled=True)
        return s.astype(jnp.float32 if x.dtype == jnp.float32
                        else x.dtype)
    slice_elems = x.size // axis_size
    sl = x.astype(jnp.float32).reshape(axis_size, slice_elems)
    pad = (-slice_elems) % chunk
    if pad:
        sl = jnp.concatenate([sl, jnp.zeros((axis_size, pad), sl.dtype)],
                             axis=1)
    ch = sl.reshape(axis_size, -1, chunk)
    absmax = jnp.max(jnp.abs(ch), axis=2)
    absmax = jax.lax.pmax(absmax, axis)  # shared [axis_size, cps]
    scales = _scales_of(absmax)
    q = _encode(ch, scales[:, :, None]).astype(jnp.int32)
    s = jax.lax.psum_scatter(q, axis, scatter_dimension=0, tiled=True)
    idx = jax.lax.axis_index(axis)
    my_scales = jax.lax.dynamic_slice_in_dim(scales, idx, 1, axis=0)
    out = s.astype(jnp.float32) * my_scales[:, :, None]
    flat = out.reshape(-1)
    if pad:
        flat = flat[:slice_elems]
    return flat.reshape(out_shape).astype(x.dtype)
