"""Tensor-parallel (mpu) layers + sequence-parallel variants.

Role parity: `python/paddle/distributed/fleet/layers/mpu/mp_layers.py`
(VocabParallelEmbedding :47, ColumnParallelLinear :333, RowParallelLinear
:540, ParallelCrossEntropy) and
`fleet/utils/sequence_parallel_utils.py` (Column/RowSequenceParallelLinear).

TPU-first: these layers DON'T hand-code identity/allreduce/scatter ops.
Each parameter carries a sharding annotation (`dist_attr` = per-dim mesh axis
names); the train-step builder turns annotations into NamedShardings and XLA
inserts the TP collectives (the reference's _c_identity/_mp_allreduce pairs)
optimally. Eagerly on one chip they behave like their dense counterparts, so
the same model runs single-chip and distributed — the mpu API contract.

Activation sharding (Megatron-SP) is expressed with sharding constraints on
the sequence dim inside forward (sequence_parallel=True), the compiled analog
of ScatterOp/AllGatherOp PyLayers.
"""
from __future__ import annotations

import jax

from ..core import flags
from ..core.dispatch import apply
from ..nn import functional as F
from ..nn.initializer import Normal, XavierUniform
from ..nn.layer_base import Layer
from . import topology as topo_mod

__all__ = [
    "VocabParallelEmbedding", "ColumnParallelLinear", "RowParallelLinear",
    "ParallelCrossEntropy", "ColumnSequenceParallelLinear",
    "RowSequenceParallelLinear", "get_rng_state_tracker",
    "mark_sharding", "sequence_parallel_constraint",
]


def mark_sharding(x, spec):
    """Annotate activation sharding inside a traced program; no-op eagerly
    off-mesh. spec: tuple of axis names / None per dim."""
    if not flags.in_trace():
        return x
    from jax.sharding import PartitionSpec as P

    mesh = topo_mod.current_spmd_mesh()
    # drop axes this mesh doesn't carry (e.g. a pipeline stage submesh)
    spec = tuple(
        s if (s is None or s in mesh.shape) else None for s in spec)

    def f(v):
        try:
            return jax.lax.with_sharding_constraint(
                v, jax.sharding.NamedSharding(mesh, P(*spec)))
        except Exception:
            return v

    return apply("sharding_constraint", f, x)


def sequence_parallel_constraint(x, seq_axis=1):
    spec = [None] * x.ndim
    spec[seq_axis] = "sep"
    spec[0] = "dp"
    return mark_sharding(x, tuple(spec))


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 0.02))
        # vocab dim sharded over the tensor-parallel axis
        self.weight.dist_attr = ("mp", None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return out


class ColumnParallelLinear(Layer):
    """Output-dim sharded linear. gather_output=False keeps the activation
    sharded over mp for the following RowParallelLinear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.dist_attr = (None, "mp")
        if has_bias is None or has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.dist_attr = ("mp",)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output:
            spec = [None] * out.ndim
            spec[0] = "dp"
            spec[-1] = "mp"
            out = mark_sharding(out, tuple(spec))
        return out


class RowParallelLinear(Layer):
    """Input-dim sharded linear; XLA inserts the partial-sum all-reduce the
    reference performs with _mp_allreduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.dist_attr = ("mp", None)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.dist_attr = (None,)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        spec = [None] * out.ndim
        spec[0] = "dp"
        out = mark_sharding(out, tuple(spec))
        return out


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Megatron-SP: input arrives sequence-sharded; the all-gather before the
    matmul is compiler-inserted from the constraint pair."""

    def forward(self, x):
        x = sequence_parallel_constraint(x)
        out = F.linear(x, self.weight, self.bias)
        spec = [None] * out.ndim
        spec[0] = "dp"
        spec[-1] = "mp"
        out = mark_sharding(out, tuple(spec))
        return out


class RowSequenceParallelLinear(RowParallelLinear):
    """Megatron-SP: output is reduce-scattered onto the sequence axis."""

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        out = sequence_parallel_constraint(out)
        return out


class ParallelCrossEntropy(Layer):
    """Vocab-sharded softmax cross entropy (c_softmax_with_cross_entropy
    role): with the logits' vocab dim annotated over mp, XLA keeps the
    softmax reduction distributed; semantics match dense CE."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        spec = [None] * input.ndim
        spec[0] = "dp"
        spec[-1] = "mp"
        input = mark_sharding(input, tuple(spec))
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


class _RNGStateTracker:
    """TP RNG isolation (parity: fleet/layers/mpu/random.py): named states
    derive per-axis keys via fold_in so dropout differs across mp ranks but
    reproduces under recompute."""

    def __init__(self):
        from ..core import rng

        self._states = {}
        self._rng = rng

    def add(self, name, seed):
        self._states[name] = self._rng.Generator(seed)

    def get_states_tracker(self):
        return dict(self._states)

    def set_states_tracker(self, states):
        self._states = states

    def rng_state(self, name="global_seed"):
        import contextlib

        @contextlib.contextmanager
        def cm():
            gen = self._states.get(name)
            if gen is None:
                gen = self._rng.Generator(hash(name) % (2 ** 31))
                self._states[name] = gen
            old = self._rng.default_generator
            self._rng.default_generator = gen
            try:
                yield
            finally:
                self._rng.default_generator = old

        return cm()


_tracker = _RNGStateTracker()


def get_rng_state_tracker():
    return _tracker
