"""pt_lint — the framework-aware static-analysis CLI.

Layers (see docs/STATIC_ANALYSIS.md for the rule catalog):

  ast       PT001–PT007  trace-safety lint (stdlib ast, fast)
  lock      PT101/PT102  lock-discipline race checker (fast)
  conc      PT501–PT505  whole-program concurrency auditor: inferred
                         thread roots, blocking calls under locks,
                         lock-order cycles, unguarded cross-thread
                         state, guard drift, condition-variable misuse
                         (stdlib ast, fast)
  manifest  PT301        OPS_MANIFEST.json vs live module surface
                         (imports paddle_tpu — a few seconds)
  jaxpr     PT201–PT203  jaxpr/StableHLO audit of the exported op
                         table and the hybrid train step (traces and
                         lowers real programs — slow tier)
  perf      PT400–PT405  static performance auditor: layout-tax
                         transposes, recompile hazards, replicated
                         state, collective anti-patterns, hot-loop
                         host syncs — gated against committed
                         per-model budgets (tools/perf_budget.json)

Usage:
  python tools/pt_lint.py                  # report (ast+lock+conc)
  python tools/pt_lint.py --check          # gate: exit 2 on NEW
                                           # violations vs the baseline
                                           # (runs ast+lock+conc+manifest)
  python tools/pt_lint.py --update-baseline
  python tools/pt_lint.py --jaxpr --check  # include the slow layer
  python tools/pt_lint.py --layers ast     # pick layers explicitly
  python tools/pt_lint.py --select PT501,PT502 --emit out.json
                                           # concurrency findings only,
                                           # machine-readable JSON
  python tools/pt_lint.py --perf           # perf audit, fast subset
                                           # (train/sharded-train/
                                           #  decode/call-sites)
  python tools/pt_lint.py --perf --check   # gate: exit 2 when any
                                           # audited metric EXCEEDS its
                                           # committed budget
  python tools/pt_lint.py --update-budget  # full audit (op table too),
                                           # rewrite tools/perf_budget.json

The committed baseline (tools/lint_baseline.json) counts pre-existing
violations by line-free key, so the gate fails only on findings the
current change introduced. Inline suppression: `# pt-lint: ok[PT005]`
on the finding's line, the line above, or a def/class header.
The committed perf budget (tools/perf_budget.json) records each
representative program's quantified costs; `--perf --check` fails only
on metrics above budget (improvements print a ratchet-down note), and
`--emit-static rows.json` exports the audited metrics as
`static.<program>.<metric>` rows for tools/perf_gate.py to gate next
to the measured bench numbers.

The ast/lock fast path never imports jax: the analysis package is
file-loaded standalone, bypassing `paddle_tpu/__init__`.
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_PATH = os.path.join(REPO, "tools", "lint_baseline.json")
BUDGET_PATH = os.path.join(REPO, "tools", "perf_budget.json")

# the manifest/jaxpr layers import paddle_tpu lazily; make sure the
# repo root wins over tools/ in sys.path when invoked as a script
if REPO not in sys.path:
    sys.path.insert(0, REPO)

EXIT_OK = 0
EXIT_USAGE = 1
EXIT_NEW_VIOLATIONS = 2


def load_analysis():
    """paddle_tpu.analysis WITHOUT importing (jax-heavy) paddle_tpu:
    load the subpackage as a standalone top-level package."""
    if "paddle_tpu" in sys.modules:  # already paid — use the real one
        import paddle_tpu.analysis as analysis

        return analysis
    if "pt_analysis" in sys.modules:
        return sys.modules["pt_analysis"]
    pkg_dir = os.path.join(REPO, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "pt_analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["pt_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def run_perf(args) -> int:
    """The --perf / --update-budget flow: audit representative programs,
    report quantified PT4xx findings, gate metrics against the
    committed budget (exit 2 on any metric above budget)."""
    import importlib
    import json

    analysis = load_analysis()
    perf = importlib.import_module(f"{analysis.__name__}.perf_audit")

    if args.perf_programs:
        programs = tuple(x.strip() for x in args.perf_programs.split(",")
                         if x.strip())
    elif args.update_budget or args.perf_full:
        # the budget file must cover the slow-tier programs too — a
        # fast-subset rewrite would orphan the op-table entries
        programs = perf.FULL_PROGRAMS
    else:
        programs = perf.DEFAULT_PROGRAMS

    violations, metrics = perf.audit_perf(programs=programs,
                                          repo_root=REPO)
    if violations:
        print(analysis.render_report(violations))

    if args.emit_static:
        rows = perf.metrics_to_static_rows(metrics)
        with open(args.emit_static, "w") as f:
            for row in rows:
                f.write(json.dumps(row, sort_keys=True) + "\n")
        print(f"pt_lint: {len(rows)} static metric row(s) -> "
              f"{args.emit_static}")

    blind = [v for v in violations if v.rule == "PT400"]
    if args.update_budget:
        if blind:
            # a program that failed to build has an EMPTY metrics entry;
            # committing it would silently erase its budget ceilings
            print(f"pt_lint: FAIL — {len(blind)} program(s) could not "
                  f"be audited (PT400); budget NOT updated",
                  file=sys.stderr)
            return EXIT_NEW_VIOLATIONS
        if args.perf_programs:
            # subset update: merge into the existing budget so the
            # unaudited programs keep their committed ceilings
            merged = dict(analysis.load_budget(args.budget))
            merged.update(metrics)
        else:
            merged = metrics  # full run: drop stale/renamed programs
        analysis.save_budget(args.budget, merged)
        n = sum(len(v) for v in merged.values())
        print(f"pt_lint: perf budget updated — {n} metric(s) over "
              f"{len(merged)} program(s) in "
              f"{os.path.relpath(args.budget, REPO)}"
              + (f" ({len(metrics)} re-audited)" if args.perf_programs
                 else ""))
        return EXIT_OK

    if args.check:
        budget = analysis.load_budget(args.budget)
        if not budget:
            print(f"pt_lint: FAIL — no perf budget at {args.budget} "
                  f"(run --update-budget)", file=sys.stderr)
            return EXIT_NEW_VIOLATIONS
        regressions, improvements, _unbudgeted = \
            analysis.diff_against_budget(metrics, budget)
        diff = analysis.render_budget_diff(regressions, improvements)
        if diff:
            print(diff)
        if blind:
            # a program the auditor could not see cannot be vouched for
            print(f"pt_lint: FAIL — {len(blind)} program(s) could not "
                  f"be audited (PT400)")
            return EXIT_NEW_VIOLATIONS
        if regressions:
            print(f"pt_lint: FAIL — {len(regressions)} perf metric(s) "
                  f"over budget (programs={','.join(sorted(metrics))})")
            return EXIT_NEW_VIOLATIONS
        print(f"pt_lint: OK — all audited perf metrics within budget "
              f"(programs={','.join(sorted(metrics))}"
              f"{', %d improvable' % len(improvements) if improvements else ''})")
        return EXIT_OK

    for prog in sorted(metrics):
        print(f"pt_lint: perf[{prog}] " + " ".join(
            f"{k}={v}" for k, v in sorted(metrics[prog].items())))
    print(f"pt_lint: perf audit done — {len(violations)} finding(s), "
          f"programs={','.join(sorted(metrics))}")
    return EXIT_NEW_VIOLATIONS if blind else EXIT_OK


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="pt_lint", description=__doc__.split("\n\n")[0])
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: repo roots "
                         "paddle_tpu/ tools/ tests/ bench.py)")
    ap.add_argument("--check", action="store_true",
                    help="gate mode: diff against the baseline, exit 2 "
                         "on new violations")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite tools/lint_baseline.json from the "
                         "current findings")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="baseline path (default tools/lint_baseline."
                         "json)")
    ap.add_argument("--layers", default=None,
                    help="comma list among ast,lock,conc,manifest,"
                         "jaxpr (default: ast,lock,conc; --check adds "
                         "manifest)")
    ap.add_argument("--jaxpr", action="store_true",
                    help="include the jaxpr/HLO audit layer (slow)")
    ap.add_argument("--select", default=None,
                    help="only report these rule ids (comma list)")
    ap.add_argument("--emit", metavar="OUT", default=None,
                    help="also write the (post --select) findings as a "
                         "JSON array of {file,line,rule,message} rows "
                         "('-' for stdout)")
    ap.add_argument("--perf", action="store_true",
                    help="run the static performance auditor "
                         "(PT400-PT405) instead of the source layers")
    ap.add_argument("--perf-full", action="store_true",
                    help="perf audit over the FULL program set "
                         "(adds the op-table sweep — slow tier)")
    ap.add_argument("--perf-programs", default=None,
                    help="comma list among train_step,swin_train_step,"
                         "decode_step,paged_decode_step,call_sites,"
                         "op_table (overrides the subset)")
    ap.add_argument("--update-budget", action="store_true",
                    help="rewrite tools/perf_budget.json from a full "
                         "perf audit")
    ap.add_argument("--budget", default=BUDGET_PATH,
                    help="budget path (default tools/perf_budget.json)")
    ap.add_argument("--emit-static", metavar="OUT", default=None,
                    help="also write the audited metrics as "
                         "static.<program>.<metric> rows (JSON lines) "
                         "for tools/perf_gate.py")
    args = ap.parse_args(argv)

    if args.perf or args.update_budget:
        return run_perf(args)

    if args.layers is not None:
        layers = tuple(x.strip() for x in args.layers.split(",")
                       if x.strip())
    else:
        # --update-baseline must record the SAME layer set --check
        # gates on, or a manifest finding could never be baselined
        layers = ("ast", "lock", "conc", "manifest") \
            if (args.check or args.update_baseline) \
            else ("ast", "lock", "conc")
    if args.jaxpr and "jaxpr" not in layers:
        layers = layers + ("jaxpr",)

    if args.update_baseline and (args.paths or args.select):
        # a baseline built from a subset scan would silently delete
        # every entry outside the subset and turn the next full
        # --check red — refuse instead
        print("pt_lint: --update-baseline must run over the full "
              "default scope (no paths, no --select); it rewrites the "
              "whole baseline", file=sys.stderr)
        return EXIT_USAGE

    analysis = load_analysis()
    roots = tuple(args.paths) if args.paths else analysis.DEFAULT_ROOTS
    violations = analysis.analyze_repo(REPO, roots=roots, layers=layers)
    if args.select:
        wanted = {x.strip() for x in args.select.split(",")}
        violations = [v for v in violations if v.rule in wanted]

    if args.emit:
        import json

        rows = [{"file": v.file, "line": v.line, "rule": v.rule,
                 "message": v.message} for v in violations]
        payload = json.dumps(rows, indent=2, sort_keys=True) + "\n"
        if args.emit == "-":
            sys.stdout.write(payload)
        else:
            with open(args.emit, "w") as f:
                f.write(payload)
            print(f"pt_lint: {len(rows)} finding(s) -> {args.emit}")

    if args.update_baseline:
        analysis.save_baseline(args.baseline, violations)
        print(f"pt_lint: baseline updated — {len(violations)} "
              f"violation(s) recorded in "
              f"{os.path.relpath(args.baseline, REPO)}")
        return EXIT_OK

    if args.check:
        baseline = analysis.load_baseline(args.baseline)
        new, known, stale = analysis.diff_against_baseline(
            violations, baseline)
        if stale:
            print(f"pt_lint: note — {len(stale)} stale baseline "
                  f"entr{'y' if len(stale) == 1 else 'ies'} (fixed "
                  f"findings still counted; run --update-baseline):")
            for key in stale[:10]:
                print(f"  stale: {key}")
        if new:
            print(analysis.render_report(new))
            print(f"pt_lint: FAIL — {len(new)} new violation(s) "
                  f"({len(known)} baselined, layers={','.join(layers)})")
            return EXIT_NEW_VIOLATIONS
        print(f"pt_lint: OK — no new violations "
              f"({len(known)} baselined, layers={','.join(layers)})")
        return EXIT_OK

    if violations:
        print(analysis.render_report(violations))
    print(f"pt_lint: {len(violations)} violation(s), "
          f"layers={','.join(layers)}")
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
