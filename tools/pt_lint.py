"""pt_lint — the framework-aware static-analysis CLI.

Layers (see docs/STATIC_ANALYSIS.md for the rule catalog):

  ast       PT001–PT007  trace-safety lint (stdlib ast, fast)
  lock      PT101/PT102  lock-discipline race checker (fast)
  manifest  PT301        OPS_MANIFEST.json vs live module surface
                         (imports paddle_tpu — a few seconds)
  jaxpr     PT201–PT203  jaxpr/StableHLO audit of the exported op
                         table and the hybrid train step (traces and
                         lowers real programs — slow tier)

Usage:
  python tools/pt_lint.py                  # report everything (ast+lock)
  python tools/pt_lint.py --check          # gate: exit 2 on NEW
                                           # violations vs the baseline
                                           # (runs ast+lock+manifest)
  python tools/pt_lint.py --update-baseline
  python tools/pt_lint.py --jaxpr --check  # include the slow layer
  python tools/pt_lint.py --layers ast     # pick layers explicitly

The committed baseline (tools/lint_baseline.json) counts pre-existing
violations by line-free key, so the gate fails only on findings the
current change introduced. Inline suppression: `# pt-lint: ok[PT005]`
on the finding's line, the line above, or a def/class header.

The ast/lock fast path never imports jax: the analysis package is
file-loaded standalone, bypassing `paddle_tpu/__init__`.
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_PATH = os.path.join(REPO, "tools", "lint_baseline.json")

# the manifest/jaxpr layers import paddle_tpu lazily; make sure the
# repo root wins over tools/ in sys.path when invoked as a script
if REPO not in sys.path:
    sys.path.insert(0, REPO)

EXIT_OK = 0
EXIT_USAGE = 1
EXIT_NEW_VIOLATIONS = 2


def load_analysis():
    """paddle_tpu.analysis WITHOUT importing (jax-heavy) paddle_tpu:
    load the subpackage as a standalone top-level package."""
    if "paddle_tpu" in sys.modules:  # already paid — use the real one
        import paddle_tpu.analysis as analysis

        return analysis
    if "pt_analysis" in sys.modules:
        return sys.modules["pt_analysis"]
    pkg_dir = os.path.join(REPO, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "pt_analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["pt_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="pt_lint", description=__doc__.split("\n\n")[0])
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: repo roots "
                         "paddle_tpu/ tools/ tests/ bench.py)")
    ap.add_argument("--check", action="store_true",
                    help="gate mode: diff against the baseline, exit 2 "
                         "on new violations")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite tools/lint_baseline.json from the "
                         "current findings")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="baseline path (default tools/lint_baseline."
                         "json)")
    ap.add_argument("--layers", default=None,
                    help="comma list among ast,lock,manifest,jaxpr "
                         "(default: ast,lock; --check adds manifest)")
    ap.add_argument("--jaxpr", action="store_true",
                    help="include the jaxpr/HLO audit layer (slow)")
    ap.add_argument("--select", default=None,
                    help="only report these rule ids (comma list)")
    args = ap.parse_args(argv)

    if args.layers is not None:
        layers = tuple(x.strip() for x in args.layers.split(",")
                       if x.strip())
    else:
        # --update-baseline must record the SAME layer set --check
        # gates on, or a manifest finding could never be baselined
        layers = ("ast", "lock", "manifest") \
            if (args.check or args.update_baseline) else ("ast", "lock")
    if args.jaxpr and "jaxpr" not in layers:
        layers = layers + ("jaxpr",)

    if args.update_baseline and (args.paths or args.select):
        # a baseline built from a subset scan would silently delete
        # every entry outside the subset and turn the next full
        # --check red — refuse instead
        print("pt_lint: --update-baseline must run over the full "
              "default scope (no paths, no --select); it rewrites the "
              "whole baseline", file=sys.stderr)
        return EXIT_USAGE

    analysis = load_analysis()
    roots = tuple(args.paths) if args.paths else analysis.DEFAULT_ROOTS
    violations = analysis.analyze_repo(REPO, roots=roots, layers=layers)
    if args.select:
        wanted = {x.strip() for x in args.select.split(",")}
        violations = [v for v in violations if v.rule in wanted]

    if args.update_baseline:
        analysis.save_baseline(args.baseline, violations)
        print(f"pt_lint: baseline updated — {len(violations)} "
              f"violation(s) recorded in "
              f"{os.path.relpath(args.baseline, REPO)}")
        return EXIT_OK

    if args.check:
        baseline = analysis.load_baseline(args.baseline)
        new, known, stale = analysis.diff_against_baseline(
            violations, baseline)
        if stale:
            print(f"pt_lint: note — {len(stale)} stale baseline "
                  f"entr{'y' if len(stale) == 1 else 'ies'} (fixed "
                  f"findings still counted; run --update-baseline):")
            for key in stale[:10]:
                print(f"  stale: {key}")
        if new:
            print(analysis.render_report(new))
            print(f"pt_lint: FAIL — {len(new)} new violation(s) "
                  f"({len(known)} baselined, layers={','.join(layers)})")
            return EXIT_NEW_VIOLATIONS
        print(f"pt_lint: OK — no new violations "
              f"({len(known)} baselined, layers={','.join(layers)})")
        return EXIT_OK

    if violations:
        print(analysis.render_report(violations))
    print(f"pt_lint: {len(violations)} violation(s), "
          f"layers={','.join(layers)}")
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
