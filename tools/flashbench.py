"""Flash-attention kernel micro-benchmark on the real chip.

Slope timing (PERF.md methodology): chain N iterations with a data
dependency, fetch one scalar, subtract two chain lengths to cancel the
tunnel's fixed dispatch+fetch cost.

Usage:
    python tools/flashbench.py [--fwd-only] [--blocks 128x128,256x256,...]
"""
from __future__ import annotations

import argparse
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from paddle_tpu.ops.pallas import flash_attention as FA  # noqa: E402

B, H, S, D = 32, 12, 1024, 64
CAUSAL = True
DTYPE = jnp.bfloat16


def sync(x):
    return float(np.asarray(jax.device_get(x.ravel()[0:1]), np.float32)[0])


def slope(f, q, n1=3, n2=9):
    def chain(n):
        x = q
        for _ in range(n):
            x = f(x)
        return sync(x)

    chain(1)
    chain(1)
    t0 = time.perf_counter(); chain(n1); d1 = time.perf_counter() - t0
    t0 = time.perf_counter(); chain(n2); d2 = time.perf_counter() - t0
    return (d2 - d1) / (n2 - n1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fwd-only", action="store_true")
    ap.add_argument("--blocks", default="128x128,256x256,256x512,512x512,512x1024,1024x1024")
    ap.add_argument("--shape", default=f"{B}x{H}x{S}x{D}")
    args = ap.parse_args()
    b, h, s, d = map(int, args.shape.split("x"))

    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, s, h, d), DTYPE)
    k = jnp.asarray(rs.randn(b, s, h, d), DTYPE)
    v = jnp.asarray(rs.randn(b, s, h, d), DTYPE)

    # causal attention FLOPs (fwd): 2 matmuls * 2*S^2*D * 0.5 causal
    flops_fwd = b * h * (4 * s * s * d) * (0.5 if CAUSAL else 1.0)

    def report(name, t, mult):
        fl = flops_fwd * mult
        print(f"{name:40s} {t*1e3:8.2f} ms  {fl/t/1e12:7.2f} TFLOP/s")

    # jnp reference
    if args.fwd_only:
        ref = jax.jit(lambda q: FA._ref_attention(q, k, v, None, CAUSAL))
        report("jnp ref fwd", slope(lambda x: ref(x), q), 1)
    else:
        refg = jax.jit(jax.grad(lambda q: FA._ref_attention(
            q, k, v, None, CAUSAL).astype(jnp.float32).sum()))
        report("jnp ref fwd+bwd(dq,..)", slope(lambda x: refg(x), q), 3.5)

    for blk in args.blocks.split(","):
        bq, bk = map(int, blk.split("x"))
        if s % bq or s % bk:
            continue
        try:
            if args.fwd_only:
                f = jax.jit(lambda q, bq=bq, bk=bk: FA._flash_core(
                    q, k, v, CAUSAL, bq, bk))
                t = slope(lambda x: f(x), q)
                report(f"pallas fwd {bq}x{bk}", t, 1)
            else:
                f = jax.jit(jax.grad(
                    lambda q, bq=bq, bk=bk: FA._flash_core(
                        q, k, v, CAUSAL, bq, bk).astype(jnp.float32).sum()))
                t = slope(lambda x: f(x), q)
                report(f"pallas fwd+bwd {bq}x{bk}", t, 3.5)
        except Exception as e:
            print(f"pallas {bq}x{bk} FAILED: {type(e).__name__}: {str(e)[:120]}")


if __name__ == "__main__":
    main()
