"""One-shot TPU perf experiment sweep (run on the real chip).

Usage: python tools/tpu_experiments.py [--quick]
Prints a markdown table of step times for the GPT-125M bench config
under different knobs (flash blocks, pallas on/off, batch size), using
the chained-fetch slope timing from PERF.md. Paste results into PERF.md.
"""
from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def step_time(batch=32, seq=1024, iters=8, flags_overrides=None,
              blocks=None):
    import jax

    import paddle_tpu as P
    from paddle_tpu.core import flags as F
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    if flags_overrides:
        F.set_flags(flags_overrides)
    if blocks is not None:
        from paddle_tpu.ops.pallas import flash_attention as fa

        fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K = blocks
        F.set_flags({"FLAGS_use_autotune": False})
    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=seq)
    P.seed(0)
    model = fleet.distributed_model(GPTForCausalLM(cfg))
    opt = fleet.distributed_optimizer(
        P.optimizer.AdamW(parameters=model.parameters(),
                          learning_rate=1e-4))
    crit = GPTPretrainingCriterion()
    step = model.build_train_step(opt, crit, amp_dtype="bfloat16")
    rs = np.random.RandomState(0)
    ids = P.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
    labels = P.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)),
                         "int32")
    loss = step(ids, labels)
    float(np.asarray(loss._value))
    loss = step(ids, labels)
    float(np.asarray(loss._value))
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, labels)
    final = float(np.asarray(loss._value))
    dt = (time.perf_counter() - t0) / iters
    assert np.isfinite(final), final
    tps = batch * seq / dt
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    mfu = tps * 6 * n_params / 197e12
    return dt * 1e3, tps, mfu


def run_in_subprocess(desc, **kw):
    """Each config in a fresh process: flags/caches/donated state clean."""
    import json
    import subprocess

    code = (
        "import sys; sys.path.insert(0, '.');"
        "from tools.tpu_experiments import step_time; import json;"
        f"r = step_time(**{kw!r}); print('RESULT ' + json.dumps(r))"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=1500)
    for line in reversed(r.stdout.splitlines()):
        if line.startswith("RESULT "):
            ms, tps, mfu = json.loads(line[len("RESULT "):])
            print(f"| {desc} | {ms:.0f} | {tps:,.0f} | {mfu*100:.1f}% |")
            return mfu
    print(f"| {desc} | FAILED: {r.stderr.strip().splitlines()[-1][:90] if r.stderr else '?'} | | |")
    return None


def main():
    quick = "--quick" in sys.argv
    print("| config | ms/step | tokens/s | MFU |")
    print("|---|---|---|---|")
    run_in_subprocess("baseline b32 (autotuned blocks)")
    if not quick:
        for bq, bk in [(128, 128), (256, 256), (512, 512), (256, 512),
                       (512, 1024), (1024, 1024)]:
            run_in_subprocess(f"blocks {bq}x{bk}", blocks=(bq, bk))
    run_in_subprocess("jnp attention (flash off)",
                      flags_overrides={"FLAGS_disable_pallas_flash": True})
    run_in_subprocess("batch 16", batch=16)
    run_in_subprocess("batch 64", batch=64)


if __name__ == "__main__":
    main()
