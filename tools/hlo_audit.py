"""Chip-free MFU forensics: audit the compiled train step's HLO for the
two program-structure sins that cap MXU utilization —

  * GEMMs running with f32 operands where bf16 was intended (a stray
    f32 dot runs the MXU at quarter rate; PERF.md round-3 found exactly
    this inside the flash kernel)
  * layout transposes in the hot path ([B,S,H,D] <-> [B,H,S,D] around
    attention — the cost the head-major residuals halved and the mh
    kernels would eliminate)

Method: lower the GPT train step at a proxy shape (same dtypes/structure
as the bench shape, smaller batch/depth so CPU lowering is quick), walk
the PRE-OPTIMIZATION StableHLO, and bucket every dot_general and
transpose by result dtype and size. Pre-optimization is the honest view
for dtypes: XLA:CPU's optimized HLO legalizes every bf16 dot to f32
(no bf16 units on CPU), which says nothing about the TPU program.
Caveats the other way: the attention dots here are the reference path
(CPU has no Pallas flash), and StableHLO transposes are an upper bound —
XLA fuses/elides some of them on TPU.

Run: python tools/hlo_audit.py   # table + one JSON line per section
"""
from __future__ import annotations

import json
import re
import sys

sys.path.insert(0, ".")


_DOT = re.compile(
    r"stablehlo\.dot_general[^\n]*:\s*\(tensor<[0-9x]+x(\w+)>,\s*"
    r"tensor<[0-9x]+x(\w+)>\)\s*-> tensor<([0-9x]+)x(\w+)>")
_TRANSPOSE = re.compile(
    r"stablehlo\.transpose[^\n]*?dims = \[([\d, ]+)\][^\n]*"
    r"-> tensor<([0-9x]+)x(\w+)>")


def _numel(dims: str) -> int:
    n = 1
    for d in dims.split("x"):
        if d.strip():
            n *= int(d)
    return n


def audit_hlo(hlo_text: str, min_numel: int = 1 << 14):
    """Bucket dots by result dtype and big transposes by moved bytes."""
    # bucket by OPERAND dtypes: bf16 operands with f32 accumulation
    # (preferred_element_type) is the full-rate MXU mode — a dot is only
    # a quarter-rate problem when an OPERAND is f32
    dots = {"bf16_operands": 0, "f32_operands": 0, "mixed": 0, "other": 0}
    f32_dot_shapes = []
    for m in _DOT.finditer(hlo_text):
        lhs, rhs, dims, _ = m.groups()
        if lhs == rhs == "bf16":
            key = "bf16_operands"
        elif lhs == rhs == "f32":
            key = "f32_operands"
        elif {lhs, rhs} <= {"bf16", "f32"}:
            key = "mixed"
        else:
            key = "other"
        dots[key] += 1
        if key != "bf16_operands" and _numel(dims) >= min_numel:
            f32_dot_shapes.append(f"{lhs}x{rhs}->[{dims}]")
    transposes = []
    for m in _TRANSPOSE.finditer(hlo_text):
        perm, dims, dt = m.groups()
        n = _numel(dims)
        if n >= min_numel:
            itemsize = {"bf16": 2, "f16": 2, "f32": 4, "i32": 4,
                        "ui32": 4, "f64": 8}.get(dt, 4)
            transposes.append({"dtype": dt, "shape": dims,
                               "perm": perm.replace(" ", ""),
                               "mbytes": round(n * itemsize / 2**20, 2)})
    transposes.sort(key=lambda t: -t["mbytes"])
    return {"dot_counts": dots,
            "big_non_bf16_dots": f32_dot_shapes[:20],
            "big_transposes": transposes[:20],
            "transpose_mbytes_total": round(
                sum(t["mbytes"] for t in transposes), 1)}


def train_step_hlo(batch=4, seq=1024, layers=2):
    """Lower the GPT train step (bench dtypes, reduced batch/depth) and
    return its PRE-OPTIMIZATION StableHLO text (see module docstring for
    why not the backend-optimized HLO)."""
    from memory_report import _build_lowered

    lowered, _ = _build_lowered(
        dict(vocab_size=50304, hidden_size=768, num_layers=layers,
             num_heads=12, max_seq_len=seq, fused_head_ce=True,
             dropout=0.0),
        batch, seq)
    return lowered.as_text()


def main():
    hlo = train_step_hlo()
    report = audit_hlo(hlo)
    print(json.dumps({"section": "train_step_hlo_audit", **report},
                     indent=1))
    # the one hard gate: no large f32 GEMM may exist in the bf16 step
    if report["big_non_bf16_dots"]:
        print("WARNING: large non-bf16-operand dots present — "
              "quarter-rate MXU risk", file=sys.stderr)


if __name__ == "__main__":
    main()
