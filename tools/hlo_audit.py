"""Chip-free MFU forensics — thin CLI shim.

The actual analysis lives in ``paddle_tpu.analysis.perf_audit``
(``audit_hlo`` / ``train_step_hlo``) so the standalone tool and the
static-analysis package cannot drift: one regex set decides what "an
f32-operand dot" or "a big transpose" means for both the CLI table and
the PT401 budget gate.

What it reports (see perf_audit.audit_hlo for the method):
  * GEMMs bucketed by OPERAND dtype — a stray f32-operand dot runs the
    MXU at quarter rate (bf16 operands + f32 accumulation is full rate)
  * big layout transposes by moved bytes — the PERF.md 66 ms/step
    (20%) finding, statically

Run: python tools/hlo_audit.py   # table + one JSON line per section
"""
from __future__ import annotations

import json
import sys

sys.path.insert(0, ".")

from paddle_tpu.analysis.perf_audit import (  # noqa: E402
    audit_hlo, train_step_hlo,
)

__all__ = ["audit_hlo", "train_step_hlo", "main"]


def main():
    hlo = train_step_hlo()
    report = audit_hlo(hlo)
    print(json.dumps({"section": "train_step_hlo_audit", **report},
                     indent=1))
    # the one hard gate: no large f32 GEMM may exist in the bf16 step
    if report["big_non_bf16_dots"]:
        print("WARNING: large non-bf16-operand dots present — "
              "quarter-rate MXU risk", file=sys.stderr)


if __name__ == "__main__":
    main()
