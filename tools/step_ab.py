"""Layout / fused-kernel A/B harness: one real train (or decode) step
per variant, perf_gate-compatible rows out.

Usage:
    python tools/step_ab.py [VARIANT] [--model {gpt,swin,resnet}]
                            [--smoke] [--decode] [--iters N]

VARIANT:
  * --model gpt (default): a flash attention layout —
    transpose|kv|flat|mh|auto (FLAGS_flash_layout). Default: transpose.
  * --model swin/resnet: `fused` (Pallas vision kernels on) or
    `fallback` (FLAGS_disable_pallas_window_attn/conv_norm) — the
    vision A/B axis is kernels-vs-composed-ops, not attention layout.

Mirrors chip_session's bench_quick body for gpt (batch 32, seq 1024,
autotune off, 8 scanned steps) and prints ONE human line per program:
    AB layout=<variant> tokens/s=<v> mfu=<v> loss=<v>
followed by a perf_gate-compatible JSON row
    {"metric": "step_ab_<model>_<variant>_<program>", "value": ...}
(rows are marked degraded off-TPU, so a CPU run never gates against an
on-chip floor). Run once per variant and compare — the chained-kernel
slope A/B cannot decide layouts because back-to-back swapaxes cancel
inside the timing loop; only the real step sees the transpose cost
(docs/ATTENTION.md "The layout story"). Invoked by chip_session's
layout_step_ab phase as a subprocess with a hard timeout: a
pathological Mosaic compile (seen once on the flat layout in round 5)
must cost one phase, not the window.

--smoke: CPU mode at proxy shapes — the harness itself is exercised in
tier-1 (tests/test_step_ab.py) instead of only inside a tunnel window.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _parse_args(argv):
    p = argparse.ArgumentParser(prog="step_ab", description=__doc__)
    p.add_argument("variant", nargs="?", default="transpose",
                   help="gpt: flash layout (transpose|kv|flat|mh|auto); "
                        "swin/resnet: fused|fallback")
    p.add_argument("--model", default="gpt",
                   choices=("gpt", "swin", "resnet"))
    p.add_argument("--smoke", action="store_true",
                   help="CPU proxy shapes (tier-1 harness smoke)")
    p.add_argument("--decode", action="store_true",
                   help="also A/B the gpt decode program")
    p.add_argument("--iters", type=int, default=None)
    return p.parse_args(argv)


def _emit(model, variant, program, value, unit, extra=None,
          degraded=False):
    row = {"metric": f"step_ab_{model}_{variant}_{program}",
           "value": round(value, 1), "unit": unit}
    if degraded:
        row["degraded"] = True
    if extra:
        row.update(extra)
    sys.stdout.flush()
    print(json.dumps(row))
    sys.stdout.flush()


def _on_accel():
    import jax

    return jax.devices()[0].platform in ("tpu", "axon")


def _init_fleet():
    from paddle_tpu.distributed import fleet, topology

    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    return fleet


def run_gpt_train(variant, smoke, iters=None):
    """One GPT train-step A/B point at FLAGS_flash_layout=variant.
    Returns (tokens_per_sec, mfu, final_loss)."""
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    _flags.set_flags({"FLAGS_use_autotune": 0})
    if smoke:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128, fused_head_ce=True)
        batch, seq, iters = 2, 128, iters or 2
    else:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=1024,
                        fused_head_ce=True)
        batch, seq, iters = 32, 1024, iters or 8
    fleet = _init_fleet()
    rs = np.random.RandomState(0)
    P.seed(0)
    inner = GPTForCausalLM(cfg)
    model = fleet.distributed_model(inner)
    opt = fleet.distributed_optimizer(P.optimizer.AdamW(
        parameters=model.parameters(), learning_rate=1e-4))
    step = model.build_train_step(opt, GPTPretrainingCriterion(model=inner),
                                  amp_dtype="bfloat16")
    ids = P.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
    labels = P.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)),
                         "int32")
    losses = step.run_steps(ids, labels, repeat=iters)
    final = float(np.asarray(losses._value[-1]))
    best = 0.0
    for _ in range(2 if smoke else 3):
        t0 = time.perf_counter()
        losses = step.run_steps(ids, labels, repeat=iters)
        final = float(np.asarray(losses._value[-1]))
        dt = time.perf_counter() - t0
        best = max(best, batch * seq * iters / dt)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    mfu = best * 6 * n_params / 197e12
    return best, mfu, final


def run_gpt_decode(smoke):
    """Decode-program A/B point (static-KV generate) at the layout the
    caller already applied via FLAGS_flash_layout — the decode kernels'
    Q/O views ride the same flag. Returns tokens/s."""
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    if smoke:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=64)
        B, S0, NEW = 2, 8, 8
    else:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=512)
        B, S0, NEW = 8, 128, 128
    P.seed(0)
    model = GPTForCausalLM(cfg)
    if not smoke:
        model.to(dtype="bfloat16")
    model.eval()
    rs = np.random.RandomState(0)
    prompt = P.to_tensor(rs.randint(0, cfg.vocab_size, (B, S0)), "int32")
    out = model.generate(prompt, max_new_tokens=NEW)  # compile+warm
    np.asarray(out._value)
    t0 = time.perf_counter()
    out = model.generate(prompt, max_new_tokens=NEW)
    np.asarray(out._value)
    return B * NEW / (time.perf_counter() - t0)


def run_vision_train(model_name, variant, smoke, iters=None):
    """Vision train-step A/B point: `fused` (Pallas vision kernels
    eligible) vs `fallback` (kernels disabled). Returns images/s."""
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.vision import models as V

    _flags.set_flags({"FLAGS_use_autotune": 0})
    if variant == "fallback":
        _flags.set_flags({"FLAGS_disable_pallas_window_attn": True,
                          "FLAGS_disable_pallas_conv_norm": True})
    if smoke:
        batch, img, iters = 2, 32, iters or 2
        build = (lambda: V.SwinTransformer(
            img_size=32, patch_size=4, embed_dim=24, depths=(2, 2),
            num_heads=(2, 4), window_size=4, num_classes=8)) \
            if model_name == "swin" else \
            (lambda: V.resnet18(num_classes=8))
    else:
        batch, img, iters = 64, 224, iters or 8
        build = (lambda: V.swin_t(num_classes=1000)) \
            if model_name == "swin" else \
            (lambda: V.resnet50(num_classes=1000))
    fleet = _init_fleet()
    rs = np.random.RandomState(0)
    P.seed(0)
    model = fleet.distributed_model(build())
    opt = fleet.distributed_optimizer(P.optimizer.Momentum(
        parameters=model.parameters(), learning_rate=1e-3, momentum=0.9))
    step = model.build_train_step(opt, P.nn.CrossEntropyLoss(),
                                  amp_dtype="bfloat16")
    imgs = P.to_tensor(rs.rand(batch, 3, img, img).astype(np.float32))
    labels = P.to_tensor(rs.randint(0, 8 if smoke else 1000, (batch,)),
                         "int32")
    losses = step.run_steps(imgs, labels, repeat=iters)  # warm
    float(np.asarray(losses._value[-1]))
    t0 = time.perf_counter()
    losses = step.run_steps(imgs, labels, repeat=iters)
    final = float(np.asarray(losses._value[-1]))
    dt = time.perf_counter() - t0
    if not np.isfinite(final):
        raise RuntimeError(f"non-finite loss {final}")
    return batch * iters / dt


def main(argv=None):
    args = _parse_args(list(sys.argv[1:] if argv is None else argv))
    variant = args.variant

    if args.model == "gpt":
        # validate BEFORE writing the flag: the flash dispatcher treats
        # an unknown layout as "transpose", so a typo'd variant would
        # silently measure the transpose core yet label the perf_gate
        # row with the bogus name — a mislabeled chip-window datapoint
        if variant not in ("transpose", "kv", "flat", "mh", "auto"):
            print(f"step_ab: gpt variant must be transpose|kv|flat|mh|"
                  f"auto, got {variant!r}", file=sys.stderr)
            return 1
        os.environ["FLAGS_flash_layout"] = variant
    elif variant not in ("fused", "fallback"):
        print(f"step_ab: vision variant must be fused|fallback, got "
              f"{variant!r}", file=sys.stderr)
        return 1

    from paddle_tpu.backend_guard import enable_persistent_compile_cache

    enable_persistent_compile_cache(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_tpu_cache"))
    if args.smoke:
        from paddle_tpu.backend_guard import force_cpu_mesh

        force_cpu_mesh(1)
    degraded = not _on_accel()

    if args.model == "gpt":
        tps, mfu, loss = run_gpt_train(variant, args.smoke, args.iters)
        print(f"AB layout={variant} tokens/s={tps:.1f} mfu={mfu:.4f} "
              f"loss={loss:.4f}")
        _emit("gpt", variant, "train_tokens_per_sec", tps, "tokens/s",
              extra={"mfu": round(mfu, 4)}, degraded=degraded)
        if args.decode:
            dtps = run_gpt_decode(args.smoke)
            print(f"AB layout={variant} decode_tokens/s={dtps:.1f}")
            _emit("gpt", variant, "decode_tokens_per_sec", dtps,
                  "tokens/s", degraded=degraded)
    else:
        ips = run_vision_train(args.model, variant, args.smoke,
                               args.iters)
        print(f"AB layout={variant} model={args.model} "
              f"images/s={ips:.1f}")
        _emit(args.model, variant, "train_images_per_sec", ips,
              "images/s", degraded=degraded)
    return 0


if __name__ == "__main__":
    sys.exit(main())
