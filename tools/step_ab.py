"""One GPT-125M train-step benchmark at a chosen flash layout.

Usage: python tools/step_ab.py [transpose|kv|flat|mh|auto]

Mirrors chip_session's bench_quick body (batch 32, seq 1024, autotune
off, 8 scanned steps) and prints ONE line:
    AB layout=<layout> tokens/s=<v> mfu=<v> loss=<v>
Run once per layout and compare — the chained-kernel slope A/B cannot
decide layouts because back-to-back swapaxes cancel inside the timing
loop; only the real step sees the transpose cost (docs/ATTENTION.md
"The layout story"). Invoked by chip_session's layout_step_ab phase as
a subprocess with a hard timeout: a pathological Mosaic compile (seen
once on the flat layout this round) must cost one phase, not the
window.
"""
import os, sys, time
sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))
import numpy as np

layout = sys.argv[1] if len(sys.argv) > 1 else "transpose"
os.environ["FLAGS_flash_layout"] = layout

from paddle_tpu.backend_guard import enable_persistent_compile_cache
enable_persistent_compile_cache(__import__("os").path.join(__import__("os").path.dirname(__import__("os").path.abspath(__file__)), ".jax_tpu_cache"))

import jax
import paddle_tpu as P
from paddle_tpu.core import flags as _flags
from paddle_tpu.distributed import fleet, topology
from paddle_tpu.models.gpt import (
    GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
)

_flags.set_flags({"FLAGS_use_autotune": 0})
cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                num_heads=12, max_seq_len=1024, fused_head_ce=True)
rs = np.random.RandomState(0)
batch, seq, iters = 32, 1024, 8
topology.reset_topology()
strategy = fleet.DistributedStrategy()
strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                           "sep_degree": 1, "sharding_degree": 1}
fleet.init(is_collective=True, strategy=strategy)
P.seed(0)
inner = GPTForCausalLM(cfg)
model = fleet.distributed_model(inner)
opt = fleet.distributed_optimizer(P.optimizer.AdamW(
    parameters=model.parameters(), learning_rate=1e-4))
step = model.build_train_step(opt, GPTPretrainingCriterion(model=inner),
                              amp_dtype="bfloat16")
ids = P.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
labels = P.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
losses = step.run_steps(ids, labels, repeat=iters)
final = float(np.asarray(losses._value[-1]))
best = 0.0
for _ in range(3):
    t0 = time.perf_counter()
    losses = step.run_steps(ids, labels, repeat=iters)
    f2 = float(np.asarray(losses._value[-1]))
    dt = time.perf_counter() - t0
    best = max(best, batch * seq * iters / dt)
n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
mfu = best * 6 * n_params / 197e12
print(f"AB layout={layout} tokens/s={best:.1f} mfu={mfu:.4f} "
      f"loss={final:.4f}")
