"""One-shot TPU measurement session: run everything perf-related while the
chip is reachable, append results to tools/chip_session_log.json as each
phase lands (the tunnel can drop at any time — nothing waits on anything
it doesn't need).

Phases:
  1. sanity matmul (chip + timing-method check)
  2. flash fwd and fwd+bwd block sweep at the bench shape
  3. autotune-seed: run _tuned_blocks for the bench + ViT signatures so
     the on-disk cache is hot for bench.py
  4. bench.py subprocess (headline + secondary JSON lines)

Usage: python tools/chip_session.py [phase...]   (default: all)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, ".")

LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "chip_session_log.jsonl")


def log(phase, payload):
    # JSONL append: crash-safe — a tunnel drop mid-write can at worst
    # truncate the LAST line, never clobber earlier measurements
    entry = {"t": time.strftime("%H:%M:%S"), "phase": phase, **payload}
    with open(LOG, "a") as f:
        f.write(json.dumps(entry) + "\n")
    print(f"[{entry['t']}] {phase}: {payload}", flush=True)


def sync(x):
    import numpy as np

    import jax

    return float(np.asarray(jax.device_get(x.ravel()[0:1]), np.float32)[0])


def slope(f, x, n1=4, n2=16, reps=2, consts=()):
    """Per-iteration time of a shape-preserving f, with dispatch overhead
    cancelled OUT OF THE COMPILED PROGRAM, not just out of the host loop.

    Round-4 lesson (VERDICT r4 Weak #2): chaining y=f(y) as separate
    dispatches measures the tunnel's ~17 ms per-dispatch stall, not the
    kernel (apparent HBM bandwidth came out at 0.5% of roofline). Here the
    whole chain runs inside ONE jitted fori_loop with a *traced* trip
    count, so each timing is a single dispatch + single D2H fetch; the
    (d2-d1)/(n2-n1) difference cancels that constant. XLA's while-loop
    LICM does not hoist size-inflating ops (e.g. int8->bf16 dequant), so
    weight streams stay inside the loop — the same structure a real
    scanned decode/train step has."""
    import jax

    # `consts` ride as jit ARGUMENTS, not closure captures: a closed-over
    # device array is baked into the HLO as a literal, and through this
    # remote-compile tunnel a big weight constant blows the request-body
    # limit (r5: lm-head decode_quant died with HTTP 413)
    @jax.jit
    def run(x, n, *cs):
        body = (lambda i, y: f(y, *cs)) if cs else (lambda i, y: f(y))
        return jax.lax.fori_loop(0, n, body, x)

    sync(run(x, n1, *consts))  # compile + warm (one executable, both n)
    best = 1e9
    # a tunnel hiccup during either timing makes (d2-d1) negative or
    # absurd (observed r5: fwd_ms=-184): only positive diffs count, and
    # up to 3 extra attempts replace stall-corrupted ones
    attempts = 0
    valid = 0
    while valid < reps and attempts < reps + 3:
        attempts += 1
        t0 = time.perf_counter(); sync(run(x, n1, *consts))
        d1 = time.perf_counter() - t0
        t0 = time.perf_counter(); sync(run(x, n2, *consts))
        d2 = time.perf_counter() - t0
        per_it = (d2 - d1) / (n2 - n1)
        if per_it > 0:
            valid += 1
            best = min(best, per_it)
    if valid == 0:
        raise RuntimeError(f"slope: no valid timing in {attempts} tries "
                           f"(tunnel stalls)")
    return best


def phase_bench_quick():
    """FIRST thing any tunnel window produces (VERDICT r4 Next #1): a
    driver-reusable headline record in ~3 minutes. Trimmed version of
    bench.py's run_bench — one scanned-step compile, batch 32 then 8,
    8 scan iters — written straight to tools/last_good_bench.jsonl in
    bench.py's record format so _emit_from_chip_session can reuse it even
    if the tunnel never comes back this round."""
    import jax

    from paddle_tpu.models.gpt import GPTConfig

    platform = jax.devices()[0].platform
    on_tpu = platform in ("tpu", "axon")
    # static flash blocks for the FIRST record: a cold autotune cache
    # would spend the window searching 6 fwd+bwd compiles before the
    # step even builds. Since r5 the untuned default IS the measured
    # sweep winner ((512,1024) where it fits — flash_attention.py
    # _tuned_blocks), so this record starts near-tuned; the later
    # autotune+bench phases still supersede it in last_good_bench.jsonl
    from paddle_tpu.core import flags as _flags

    prior_autotune = _flags.get_flags(
        ["FLAGS_use_autotune"])["FLAGS_use_autotune"]
    _flags.set_flags({"FLAGS_use_autotune": 0})
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=1024, fused_head_ce=True)
    np = __import__("numpy")
    rs = np.random.RandomState(0)
    try:
        _bench_quick_body(rs, np, cfg, on_tpu, platform)
    finally:  # restore the operator's setting, not a hardcoded value
        _flags.set_flags({"FLAGS_use_autotune": prior_autotune})


def _bench_quick_body(rs, np, cfg, on_tpu, platform):
    import gc

    import paddle_tpu as P
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.models.gpt import (
        GPTForCausalLM, GPTPretrainingCriterion,
    )

    seq, iters = 1024, 8
    for batch in (32, 8):
        model = opt = step = None
        gc.collect()
        try:
            topology.reset_topology()
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {
                "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                "sep_degree": 1, "sharding_degree": 1}
            fleet.init(is_collective=True, strategy=strategy)
            P.seed(0)
            inner = GPTForCausalLM(cfg)
            model = fleet.distributed_model(inner)
            opt = fleet.distributed_optimizer(P.optimizer.AdamW(
                parameters=model.parameters(), learning_rate=1e-4))
            step = model.build_train_step(
                opt, GPTPretrainingCriterion(model=inner),
                amp_dtype="bfloat16")
            ids = P.to_tensor(
                rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
            labels = P.to_tensor(
                rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
            # only the scanned program is ever timed — compile just it
            losses = step.run_steps(ids, labels, repeat=iters)  # warm
            float(np.asarray(losses._value[-1]))
            t0 = time.perf_counter()
            losses = step.run_steps(ids, labels, repeat=iters)
            final = float(np.asarray(losses._value[-1]))  # D2H = true sync
            dt = time.perf_counter() - t0
            if not np.isfinite(final):
                raise RuntimeError(f"non-finite loss {final}")
            n_params = sum(int(np.prod(p.shape))
                           for p in model.parameters())
            tps = batch * seq * iters / dt
            mfu = tps * 6 * n_params / 197e12
            rec = {"metric": "gpt125m_train_tokens_per_sec_per_chip",
                   "value": round(tps, 1), "unit": "tokens/s",
                   "vs_baseline": round(mfu / 0.45, 4)}
            peak = P.device.max_memory_allocated()
            if peak:
                rec["peak_memory_bytes"] = int(peak)
            log("bench_quick", {**rec, "batch": batch, "loss": round(final, 4),
                                "mfu": round(mfu, 4), "platform": platform})
            if on_tpu:  # never persist a CPU number as reusable
                rec["captured_at"] = time.time()
                with open(GOOD_BENCH, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            return
        except Exception as e:
            log("bench_quick", {"batch": batch,
                                "error": f"{type(e).__name__}: "
                                         f"{str(e)[:200]}"})


def phase_sanity():
    import jax
    import jax.numpy as jnp

    x = jnp.ones((8192, 8192), jnp.bfloat16)
    f = jax.jit(lambda x: x @ x)
    t = slope(f, x)
    tflops = 2 * 8192**3 / t / 1e12
    log("sanity", {"matmul8192_ms": round(t * 1e3, 2),
                   "tflops": round(tflops, 1)})


def phase_sweep():
    import numpy as np

    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention as FA

    rs = np.random.RandomState(0)
    # bench shape + a D=128 LLaMA-class shape (VERDICT r3 Next #2: flash
    # must beat the jnp reference >=1.5x fwd+bwd or it leaves the hot path)
    for (B, H, S, D), pairs in (
            ((32, 12, 1024, 64), [(1024, 1024), (512, 1024), (256, 512),
                                  (512, 512), (256, 256), (128, 128)]),
            ((8, 16, 2048, 128), [(1024, 1024), (512, 1024), (512, 512),
                                  (256, 512)])):
        q = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
        k = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
        v = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
        flops = B * H * 4 * S * S * D * 0.5
        shape_tag = f"B{B}H{H}S{S}D{D}"
        try:  # the bar: XLA's fused-softmax reference attention
            fr = jax.jit(lambda x: FA._ref_attention(x, k, v, None, True))
            tr = slope(fr, q)
            gr = jax.jit(jax.grad(lambda x: FA._ref_attention(
                x, k, v, None, True).astype(jnp.float32).sum()))
            tgr = slope(gr, q)
            log("sweep", {"shape": shape_tag, "blocks": "jnp-ref",
                          "fwd_ms": round(tr * 1e3, 2),
                          "fwdbwd_ms": round(tgr * 1e3, 2)})
        except Exception as e:
            log("sweep", {"shape": shape_tag, "blocks": "jnp-ref",
                          "error": f"{type(e).__name__}: {str(e)[:100]}"})
        for bq, bk in pairs:
            try:
                f = jax.jit(lambda x, bq=bq, bk=bk: FA._flash_core(
                    x, k, v, True, bq, bk))
                t = slope(f, q)
                g = jax.jit(jax.grad(
                    lambda x, bq=bq, bk=bk: FA._flash_core(
                        x, k, v, True, bq, bk).astype(jnp.float32).sum()))
                tg = slope(g, q)
                log("sweep", {
                    "shape": shape_tag, "blocks": f"{bq}x{bk}",
                    "fwd_ms": round(t * 1e3, 2),
                    "fwd_tflops": round(flops / t / 1e12, 1),
                    "fwdbwd_ms": round(tg * 1e3, 2),
                    "fwdbwd_tflops": round(3.5 * flops / tg / 1e12, 1)})
            except Exception as e:
                log("sweep", {"shape": shape_tag, "blocks": f"{bq}x{bk}",
                              "error": f"{type(e).__name__}: "
                                       f"{str(e)[:100]}"})
        # layout A/B: transpose core (incl. its transposes) vs the
        # all-heads-block core reading/writing [B,S,H,D] in place —
        # fwd and full fwd+bwd; the winner becomes FLAGS_flash_layout
        for bq, bk in ((512, 512), (256, 512), (1024, 1024)):
            try:
                f_t = jax.jit(lambda x, bq=bq, bk=bk: FA._flash_core(
                    x, k, v, True, bq, bk))
                f_mh = jax.jit(lambda x, bq=bq, bk=bk: FA._flash_core_mh(
                    x, k, v, True, bq, bk))
                g_t = jax.jit(jax.grad(
                    lambda x, bq=bq, bk=bk: FA._flash_core(
                        x, k, v, True, bq, bk).astype(jnp.float32).sum()))
                g_mh = jax.jit(jax.grad(
                    lambda x, bq=bq, bk=bk: FA._flash_core_mh(
                        x, k, v, True, bq, bk).astype(jnp.float32).sum()))
                tt, tm = slope(f_t, q), slope(f_mh, q)
                gt, gm = slope(g_t, q), slope(g_mh, q)
                log("layout_ab", {
                    "shape": shape_tag, "blocks": f"{bq}x{bk}",
                    "transpose_fwd_ms": round(tt * 1e3, 2),
                    "mh_fwd_ms": round(tm * 1e3, 2),
                    "transpose_fwdbwd_ms": round(gt * 1e3, 2),
                    "mh_fwdbwd_ms": round(gm * 1e3, 2),
                    "mh_fwd_speedup": round(tt / tm, 2),
                    "mh_fwdbwd_speedup": round(gt / gm, 2)})
            except Exception as e:
                log("layout_ab", {"shape": shape_tag,
                                  "blocks": f"{bq}x{bk}",
                                  "error": f"{type(e).__name__}: "
                                           f"{str(e)[:100]}"})


def phase_kernels():
    """fused_norm / rope / decode-attention micro-benchmarks (PERF.md's
    'not yet measured on hardware' list)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    rs = np.random.RandomState(0)

    import importlib

    # fused RMS norm vs XLA-fused jnp at GPT-125M shapes
    FN = importlib.import_module("paddle_tpu.ops.pallas.fused_norm")
    x = jnp.asarray(rs.randn(32 * 1024, 768), jnp.bfloat16)
    w = jnp.asarray(rs.randn(768), jnp.bfloat16)
    try:
        f_pal = jax.jit(
            lambda x: FN.fused_norm_pallas(x, w=w, eps=1e-5, kind="rms"))

        def jnp_rms(x):
            x32 = x.astype(jnp.float32)
            y = x32 * jax.lax.rsqrt(
                jnp.mean(x32 * x32, -1, keepdims=True) + 1e-5)
            return (y * w.astype(jnp.float32)).astype(x.dtype)

        f_jnp = jax.jit(jnp_rms)
        log("kernels", {"op": "rms_norm 32kx768",
                        "pallas_ms": round(slope(f_pal, x) * 1e3, 3),
                        "jnp_ms": round(slope(f_jnp, x) * 1e3, 3)})
    except Exception as e:
        log("kernels", {"op": "rms_norm", "error": str(e)[:150]})

    # rope at bench shape (neox phases)
    try:
        RP = importlib.import_module("paddle_tpu.ops.pallas.rope")
        B, S, H, D = 32, 1024, 12, 64
        qr = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
        inv = 1.0 / (10000.0 ** (np.arange(0, D, 2) / D))
        ph = np.arange(S)[:, None] * inv[None, :]
        cos = jnp.asarray(np.cos(np.concatenate([ph, ph], -1))[None, :,
                                                               None, :],
                          jnp.float32)
        sin = jnp.asarray(np.sin(np.concatenate([ph, ph], -1))[None, :,
                                                               None, :],
                          jnp.float32)
        f_rope = jax.jit(lambda q: RP.rope_pallas(q, cos, sin))
        log("kernels", {"op": f"rope {B}x{S}x{H}x{D}",
                        "pallas_ms": round(slope(f_rope, qr) * 1e3, 3)})
    except Exception as e:
        log("kernels", {"op": "rope", "error": str(e)[:150]})

    # decode attention (paged KV single-token) at serving shape
    try:
        DA = importlib.import_module(
            "paddle_tpu.ops.pallas.decode_attention")
        B, H, S, D = 8, 12, 2048, 64
        qd = jnp.asarray(rs.randn(B, H, D), jnp.bfloat16)
        kc = jnp.asarray(rs.randn(B, H, S, D), jnp.bfloat16)
        vc = jnp.asarray(rs.randn(B, H, S, D), jnp.bfloat16)
        pos = jnp.full((B,), S - 1, jnp.int32)
        f_dec = jax.jit(lambda q: DA.decode_attention(q, kc, vc, pos))
        t = slope(f_dec, qd)
        bytes_rw = 2 * B * H * S * D * 2  # K+V bf16 reads dominate
        log("kernels", {"op": f"decode B{B} S{S}",
                        "pallas_ms": round(t * 1e3, 3),
                        "gbps": round(bytes_rw / t / 1e9, 1)})
    except Exception as e:
        log("kernels", {"op": "decode", "error": str(e)[:150]})


def phase_gqa_ab():
    """GQA grouped kernels vs expanded-KV MHA at a LLaMA-2-class shape:
    the grouped path reads Hq/Hkv x less KV from HBM — prove it."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention as FA

    B, S, HQ, HKV, D = 4, 2048, 32, 8, 128
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, S, HQ, D), jnp.bfloat16)
    k = jnp.asarray(rs.randn(B, S, HKV, D), jnp.bfloat16)
    v = jnp.asarray(rs.randn(B, S, HKV, D), jnp.bfloat16)
    rep = HQ // HKV
    for bq, bk in ((512, 512), (256, 512)):
        try:
            f_g = jax.jit(lambda x, bq=bq, bk=bk: FA._flash_core(
                x, k, v, True, bq, bk))
            f_e = jax.jit(lambda x, bq=bq, bk=bk: FA._flash_core(
                x, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2),
                True, bq, bk))
            g_g = jax.jit(jax.grad(lambda x, bq=bq, bk=bk: FA._flash_core(
                x, k, v, True, bq, bk).astype(jnp.float32).sum()))
            g_e = jax.jit(jax.grad(lambda x, bq=bq, bk=bk: FA._flash_core(
                x, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2),
                True, bq, bk).astype(jnp.float32).sum()))
            log("gqa_ab", {
                "shape": f"B{B}S{S} {HQ}q/{HKV}kv D{D}",
                "blocks": f"{bq}x{bk}",
                "grouped_fwd_ms": round(slope(f_g, q) * 1e3, 2),
                "expanded_fwd_ms": round(slope(f_e, q) * 1e3, 2),
                "grouped_fwdbwd_ms": round(slope(g_g, q) * 1e3, 2),
                "expanded_fwdbwd_ms": round(slope(g_e, q) * 1e3, 2)})
        except Exception as e:
            log("gqa_ab", {"blocks": f"{bq}x{bk}",
                           "error": f"{type(e).__name__}: {str(e)[:120]}"})


def phase_autotune_seed():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention as FA

    for (b, s, h, d) in [(32, 1024, 12, 64), (16, 1024, 12, 64),
                         (8, 1024, 12, 64), (8, 2048, 16, 128)]:
        t0 = time.perf_counter()
        blocks = FA._tuned_blocks(b, s, s, h, d, jnp.bfloat16, True)
        log("autotune", {"sig": f"{b}x{s}x{h}x{d}", "picked": list(blocks),
                         "seconds": round(time.perf_counter() - t0, 1)})


def phase_generate():
    """GPT-125M single-chip decode throughput over the static KV cache
    (serving metric: tokens/s at batch 8, prompt 128, 128 new tokens)."""
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(0)
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=2048)
    model = GPTForCausalLM(cfg)
    model.eval()
    rs = np.random.RandomState(0)
    B, S0, NEW = 8, 128, 128
    prompt = P.to_tensor(rs.randint(0, cfg.vocab_size, (B, S0)), "int32")
    t0 = time.perf_counter()
    out = model.generate(prompt, max_new_tokens=NEW)
    _ = np.asarray(out._value)  # sync
    warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = model.generate(prompt, max_new_tokens=NEW)
    _ = np.asarray(out._value)
    dt = time.perf_counter() - t0
    log("generate", {"warm_s": round(warm, 1), "steady_s": round(dt, 2),
                     "tokens_per_s": round(B * NEW / dt, 1),
                     "ms_per_token_step": round(dt / NEW * 1e3, 2)})


GOOD_BENCH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "last_good_bench.jsonl")


def phase_memory_headroom():
    """Largest single-chip GPT training run (VERDICT r3 Next #8): GPT-760M
    with remat + bf16 AMP + donation; records tokens/s, MFU, and peak HBM
    toward the BASELINE configs 2-3 memory story."""
    import gc

    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    cfg = GPTConfig(vocab_size=50304, hidden_size=1536, num_layers=24,
                    num_heads=16, max_seq_len=1024, recompute=True,
                    fused_head_ce=True)
    seq, iters = 1024, 8
    for batch in (16, 8, 4, 2):
        model = opt = step = None
        gc.collect()
        try:
            topology.reset_topology()
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {
                "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                "sep_degree": 1, "sharding_degree": 1}
            fleet.init(is_collective=True, strategy=strategy)
            P.seed(0)
            inner = GPTForCausalLM(cfg)
            model = fleet.distributed_model(inner)
            opt = fleet.distributed_optimizer(P.optimizer.AdamW(
                parameters=model.parameters(), learning_rate=1e-4))
            step = model.build_train_step(
                opt, GPTPretrainingCriterion(model=inner),
                amp_dtype="bfloat16")
            rs = np.random.RandomState(0)
            ids = P.to_tensor(
                rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
            labels = P.to_tensor(
                rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
            losses = step.run_steps(ids, labels, repeat=iters)  # warmup
            float(np.asarray(losses._value[-1]))
            t0 = time.perf_counter()
            losses = step.run_steps(ids, labels, repeat=iters)
            final = float(np.asarray(losses._value[-1]))
            dt = time.perf_counter() - t0
            n_params = sum(int(np.prod(p.shape))
                           for p in model.parameters())
            tps = batch * seq * iters / dt
            mfu = tps * 6 * n_params / 197e12
            peak = P.device.max_memory_allocated()
            log("memory_headroom", {
                "model": "gpt-760m", "params": n_params, "batch": batch,
                "tokens_per_s": round(tps, 1), "mfu": round(mfu, 4),
                "peak_memory_gb": round(peak / 2**30, 2) if peak else None,
                "loss": round(final, 4)})
            return
        except Exception as e:
            log("memory_headroom", {
                "batch": batch,
                "error": f"{type(e).__name__}: {str(e)[:150]}"})


def phase_decode_quant():
    """weight-only int8 vs bf16 linear at decode GEMV shapes (VERDICT r3
    Next #4): int8 weights halve HBM reads — decode is bandwidth-bound, so
    the kernel must show ~2x or it is overhead."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from paddle_tpu.nn import quant as Q

    rs = np.random.RandomState(0)
    B = 8
    for h_in, h_out, tag in ((2048, 8192, "mlp-up"), (8192, 2048, "mlp-dn"),
                             (2048, 50304, "lm-head")):
        try:
            # slope() chains f(f(x)): use an up+down GEMM pair so shapes
            # round-trip; both weights stream from HBM each call. Weights
            # ride as slope consts (jit args) — closure-captured device
            # arrays become HLO literals and the lm-head pair's ~400 MB
            # of constants blew the remote-compile body limit (HTTP 413)
            w1 = jnp.asarray(rs.randn(h_in, h_out) * 0.02, jnp.float32)
            w2 = jnp.asarray(rs.randn(h_out, h_in) * 0.02, jnp.float32)
            x = jnp.asarray(rs.randn(B, h_in), jnp.bfloat16)
            b1, b2 = w1.astype(jnp.bfloat16), w2.astype(jnp.bfloat16)

            def bf16_pair(x, b1, b2):
                return (x @ b1) @ b2

            def quant_args(algo):
                q1, s1 = (t._value for t in Q.weight_quantize(w1,
                                                              algo=algo))
                q2, s2 = (t._value for t in Q.weight_quantize(w2,
                                                              algo=algo))

                def pair(x, q1, s1, q2, s2, algo=algo):
                    d1 = Q.weight_dequantize.raw(q1, s1, algo,
                                                 jnp.bfloat16, -1)
                    d2 = Q.weight_dequantize.raw(q2, s2, algo,
                                                 jnp.bfloat16, -1)
                    return (x @ d1) @ d2

                return pair, (q1, s1, q2, s2)

            t_bf = slope(bf16_pair, x, n1=8, n2=40, consts=(b1, b2))
            f8, c8 = quant_args("weight_only_int8")
            t_q = slope(f8, x, n1=8, n2=40, consts=c8)
            try:  # best-effort: int4 must not cost the bf16/int8 data
                f4, c4 = quant_args("weight_only_int4")
                t_q4 = slope(f4, x, n1=8, n2=40, consts=c4)
            except Exception:
                t_q4 = None
            bytes_bf = 2 * h_in * h_out * 2  # two bf16 weight streams
            bytes_q = 2 * h_in * h_out  # two int8 weight streams
            bytes_q4 = h_in * h_out  # two packed-nibble streams
            bf_gbps = bytes_bf / t_bf / 1e9
            q_gbps = bytes_q / t_q / 1e9
            q4_gbps = bytes_q4 / t_q4 / 1e9 if t_q4 else None
            # roofline sanity (r4 lesson: 3.8 GB/s meant the harness was
            # timing dispatch, not the kernel): flag implausible numbers
            # in-band so a bad methodology can never pass silently again
            sane = 20.0 < bf_gbps < 1300.0
            log("decode_quant", {
                "shape": f"{tag}-pair {B}x{h_in}x{h_out}",
                "bf16_ms": round(t_bf * 1e3, 3),
                "int8_ms": round(t_q * 1e3, 3),
                "int4_ms": round(t_q4 * 1e3, 3) if t_q4 else None,
                "bf16_gbps": round(bf_gbps, 1),
                "int8_gbps": round(q_gbps, 1),
                "int4_gbps": round(q4_gbps, 1) if t_q4 else None,
                "speedup": round(t_bf / t_q, 2),
                "speedup_int4": round(t_bf / t_q4, 2) if t_q4 else None,
                "roofline_sane": sane})
        except Exception as e:
            log("decode_quant", {"shape": tag,
                                 "error": f"{type(e).__name__}: "
                                          f"{str(e)[:150]}"})


def phase_generate_1p3b():
    """GPT-1.3B-shape single-chip decode throughput, bf16 weights
    (serving metric at a real deployment size)."""
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_1p3b

    P.seed(0)
    cfg = gpt_1p3b()
    model = GPTForCausalLM(cfg)
    model.to(dtype="bfloat16")
    model.eval()
    rs = np.random.RandomState(0)
    B, S0, NEW = 8, 128, 64
    prompt = P.to_tensor(rs.randint(0, cfg.vocab_size, (B, S0)), "int32")
    t0 = time.perf_counter()
    out = model.generate(prompt, max_new_tokens=NEW)
    _ = np.asarray(out._value)
    warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = model.generate(prompt, max_new_tokens=NEW)
    _ = np.asarray(out._value)
    dt = time.perf_counter() - t0
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # decode is HBM-bound: each token step reads all params once
    gbps = n_params * 2 * (NEW / dt) / 1e9
    log("generate_1p3b", {"params": n_params, "warm_s": round(warm, 1),
                          "steady_s": round(dt, 2),
                          "tokens_per_s": round(B * NEW / dt, 1),
                          "ms_per_token_step": round(dt / NEW * 1e3, 2),
                          "weight_stream_gbps": round(gbps, 1)})
    # int8 weight-only serving variant: the decode_quant phase proved
    # ≥1.5x at the isolated mlp GEMV shape — this measures it END TO END
    # on the same model (block linears quantized; embeddings + tied
    # lm-head stay bf16 pending the lm-head pair re-run)
    try:
        from paddle_tpu.nn.quant import WeightOnlyLinear
        from paddle_tpu.quantization import weight_only_quantize

        bf16_tps = B * NEW / dt
        # two-phase atomic swap of every Linear-family sublayer (the
        # embedding + tied lm-head are not Linears and stay bf16)
        weight_only_quantize(model, inplace=True)
        n_q = sum(1 for _, sl in model.named_sublayers()
                  if isinstance(sl, WeightOnlyLinear))
        model.eval()
        out = model.generate(prompt, max_new_tokens=NEW)  # compile+warm
        _ = np.asarray(out._value)
        t0 = time.perf_counter()
        out = model.generate(prompt, max_new_tokens=NEW)
        _ = np.asarray(out._value)
        dq = time.perf_counter() - t0
        log("generate_1p3b", {
            "variant": "weight_only_int8", "quantized_linears": n_q,
            "tokens_per_s": round(B * NEW / dq, 1),
            "ms_per_token_step": round(dq / NEW * 1e3, 2),
            "speedup_vs_bf16": round((B * NEW / dq) / bf16_tps, 2)})
    except Exception as e:
        log("generate_1p3b", {"variant": "weight_only_int8",
                              "error": f"{type(e).__name__}: {str(e)[:200]}"})


def phase_breakdown():
    """Step-cost breakdown at the bench shape (r5: MFU is 27.6% while the
    sanity matmul hits 90% of peak — find the ~300 ms of non-matmul time).
    Times, inside one fori_loop each (slope methodology): fwd-only,
    fwd+bwd over all params, fwd+bwd excluding the tied embedding (its
    grad = CE-head GEMM + gather-bwd scatter — the scatter is the prime
    TPU suspect), and the full train step. Differences localize the cost:
      embed_grad = fwdbwd_all - fwdbwd_no_wte
      optimizer+cast = step - fwdbwd_all
    The loop body depends on the carry through a 1e-12 param perturbation
    so LICM cannot hoist the (otherwise loop-invariant) computation."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    import paddle_tpu as P
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    batch, seq = 32, 1024
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=seq, fused_head_ce=True)
    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
        "sep_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    P.seed(0)
    inner = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(model=inner)
    params, buffers = inner.functional_state()
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq)),
                      jnp.int32)
    labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    wte_key = next(k for k in params if k.endswith("wte.weight"))

    # params ride as slope consts (jit args, not closure constants —
    # 125M params as HLO literals would blow the remote-compile limit)
    keys = sorted(params)
    leaves = tuple(params[k]._value if hasattr(params[k], "_value")
                   else params[k] for k in keys)

    def loss_from(p):
        with _flags.trace_guard():
            with inner.bind_state(p, buffers):
                inner.train()
                out = inner(Tensor(ids))
                return crit(out, Tensor(labels))._value

    def rebuild(t, *lv):
        p = dict(zip(keys, lv))
        p[wte_key] = p[wte_key] + t.ravel()[0] * 1e-12
        return p

    def f_fwd(t, *lv):
        return t + loss_from(rebuild(t, *lv)) * 1e-20

    def f_bwd_all(t, *lv):
        g = jax.grad(lambda p: loss_from(p))(rebuild(t, *lv))
        return t + g[wte_key][0, 0] * 1e-20

    def f_bwd_no_wte(t, *lv):
        p = rebuild(t, *lv)
        wte = p.pop(wte_key)
        g = jax.grad(lambda q: loss_from({**q, wte_key: wte}))(p)
        leaf = next(iter(g.values()))
        return t + leaf.ravel()[0] * 1e-20

    t0 = jnp.zeros((1,), jnp.float32)
    out = {}
    for name, f in (("fwd_ms", f_fwd), ("fwdbwd_ms", f_bwd_all),
                    ("fwdbwd_no_wte_ms", f_bwd_no_wte)):
        try:
            out[name] = round(
                slope(f, t0, n1=2, n2=8, consts=leaves) * 1e3, 2)
        except Exception as e:
            out[name] = f"{type(e).__name__}: {str(e)[:80]}"
    # full train step via run_steps at two repeats (same slope idea)
    try:
        model = fleet.distributed_model(inner)
        opt = fleet.distributed_optimizer(P.optimizer.AdamW(
            parameters=model.parameters(), learning_rate=1e-4))
        step = model.build_train_step(opt, crit, amp_dtype="bfloat16")
        tids = P.to_tensor(np.asarray(ids), "int32")
        tlabels = P.to_tensor(np.asarray(labels), "int32")
        float(np.asarray(step.run_steps(tids, tlabels,
                                        repeat=2)._value[-1]))  # warm
        best = 1e9
        for _ in range(2):
            t1 = time.perf_counter()
            float(np.asarray(step.run_steps(tids, tlabels,
                                            repeat=2)._value[-1]))
            d1 = time.perf_counter() - t1
            t1 = time.perf_counter()
            float(np.asarray(step.run_steps(tids, tlabels,
                                            repeat=8)._value[-1]))
            d2 = time.perf_counter() - t1
            if d2 > d1:
                best = min(best, (d2 - d1) / 6)
        out["step_ms"] = round(best * 1e3, 2)
    except Exception as e:
        out["step_ms"] = f"{type(e).__name__}: {str(e)[:80]}"
    if isinstance(out.get("fwdbwd_ms"), float) and \
            isinstance(out.get("fwdbwd_no_wte_ms"), float):
        out["embed_grad_ms"] = round(
            out["fwdbwd_ms"] - out["fwdbwd_no_wte_ms"], 2)
    if isinstance(out.get("step_ms"), float) and \
            isinstance(out.get("fwdbwd_ms"), float):
        out["opt_overhead_ms"] = round(out["step_ms"] - out["fwdbwd_ms"], 2)
    log("breakdown", {"shape": f"B{batch}S{seq}", **out})


def phase_layout_step_ab():
    """Full-train-step A/B of the flash layouts (docs/ATTENTION.md "The
    layout story"): the chained slope A/B cannot decide layouts because
    back-to-back swapaxes cancel inside the timing loop; only the real
    step pays the transpose cost. Each layout runs as a SUBPROCESS with
    a hard timeout — a pathological Mosaic compile (observed once for
    the flat layout this round: remote compile hung >25 min) must cost
    one variant, not the window."""
    here = os.path.dirname(os.path.abspath(__file__))
    layouts = ("transpose", "flat")
    n_ok = 0
    for layout in layouts:
        t0 = time.perf_counter()
        try:
            r = subprocess.run(
                [sys.executable, os.path.join(here, "step_ab.py"), layout],
                capture_output=True, text=True, timeout=1500)
            line = next((l for l in r.stdout.splitlines()
                         if l.startswith("AB ")), None)
            if line:
                n_ok += 1
                log("layout_step_ab", {
                    "layout": layout, "result": line,
                    "seconds": round(time.perf_counter() - t0, 1)})
            else:
                log("layout_step_ab", {
                    "layout": layout, "rc": r.returncode,
                    "stderr_tail": r.stderr[-200:]})
        except subprocess.TimeoutExpired:
            log("layout_step_ab", {
                "layout": layout,
                "error": "timeout after 1500s (hung remote compile?)"})
        except Exception as e:
            log("layout_step_ab", {
                "layout": layout,
                "error": f"{type(e).__name__}: {str(e)[:200]}"})
    # the phase exists to COMPARE layouts: a half-complete A/B (e.g. the
    # flat compile hanging into its timeout while transpose finished)
    # must rerun next window, not hide behind a done marker
    return n_ok == len(layouts)


def phase_mh_bisect():
    """Localize the real-toolchain rejection of the transpose-free (mh)
    flash kernels (PERF.md r5: local lowering gate green, server-side
    Mosaic HTTP 500 at every block config — the A/B was decided against
    mh by default). Compiles a ladder of progressively richer mh-style
    kernels on the real backend; the first rung that fails names the
    feature the server's Mosaic rejects, which is the fix target."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    import paddle_tpu.ops.pallas.flash_attention as FA

    b, s, h, d = 2, 256, 4, 64
    bq, bk = 128, 128
    # arrays ride as jit ARGUMENTS (not closure captures) — a captured
    # device array bakes into the HLO as a literal and oversized constant
    # payloads already broke this tunnel's remote compile (HTTP 413,
    # fixed in slope(); same rule here)
    q = jnp.zeros((b, s, h, d), jnp.bfloat16)
    qbench = jnp.zeros((4, 1024, 12, 64), jnp.bfloat16)

    def block3d(bi, qi):
        return (bi, qi, 0, 0)

    def rung_copy3d(x):
        def kern(q_ref, o_ref):
            o_ref[...] = q_ref[...]

        return pl.pallas_call(
            kern, grid=(b, s // bq),
            in_specs=[pl.BlockSpec((None, bq, h, d), block3d)],
            out_specs=pl.BlockSpec((None, bq, h, d), block3d),
            out_shape=jax.ShapeDtypeStruct((b, s, h, d), x.dtype))(x)

    def rung_headwalk(x):
        def kern(q_ref, o_ref):
            for hh in range(h):
                o_ref[:, hh, :] = q_ref[:, hh, :] * 2.0

        return pl.pallas_call(
            kern, grid=(b, s // bq),
            in_specs=[pl.BlockSpec((None, bq, h, d), block3d)],
            out_specs=pl.BlockSpec((None, bq, h, d), block3d),
            out_shape=jax.ShapeDtypeStruct((b, s, h, d), x.dtype))(x)

    def rung_lse_out(x):
        def kern(q_ref, o_ref, lse_ref):
            for hh in range(h):
                o_ref[:, hh, :] = q_ref[:, hh, :]
                lse_ref[hh, :, :] = jnp.zeros((bq, 1), jnp.float32)

        return pl.pallas_call(
            kern, grid=(b, s // bq),
            in_specs=[pl.BlockSpec((None, bq, h, d), block3d)],
            out_specs=[pl.BlockSpec((None, bq, h, d), block3d),
                       pl.BlockSpec((None, h, bq, 1),
                                    lambda bi, qi: (bi, 0, qi, 0))],
            out_shape=[jax.ShapeDtypeStruct((b, s, h, d), x.dtype),
                       jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32)])(x)

    def rung_headdot(x):
        def kern(q_ref, k_ref, o_ref):
            for hh in range(h):
                sblk = jax.lax.dot_general(
                    q_ref[:, hh, :], k_ref[pl.ds(0, bk), hh, :],
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                o_ref[:, hh, :] = (
                    sblk[:, :d] * 0.0 + q_ref[:, hh, :].astype(jnp.float32)
                ).astype(o_ref.dtype)

        return pl.pallas_call(
            kern, grid=(b, s // bq),
            in_specs=[pl.BlockSpec((None, bq, h, d), block3d),
                      pl.BlockSpec((None, s, h, d),
                                   lambda bi, qi: (bi, 0, 0, 0))],
            out_specs=pl.BlockSpec((None, bq, h, d), block3d),
            out_shape=jax.ShapeDtypeStruct((b, s, h, d), x.dtype))(x, x)

    def rung_fwd_mh_small(x):
        return FA._fwd_mh(x, x, x, True, bq, bk)[0]

    def rung_fwd_mh_bench(x):
        return FA._fwd_mh(x, x, x, True, 256, 512)[0]

    def rung_bwd_mh_small(x):
        out, lse = FA._fwd_mh(x, x, x, True, bq, bk)
        return FA._bwd_mh(x, x, x, out, lse, x, True, bq, bk)[0]

    rungs = [("copy3d", rung_copy3d, q), ("headwalk", rung_headwalk, q),
             ("lse_out", rung_lse_out, q), ("headdot", rung_headdot, q),
             ("fwd_mh_small", rung_fwd_mh_small, q),
             ("fwd_mh_bench", rung_fwd_mh_bench, qbench),
             ("bwd_mh_small", rung_bwd_mh_small, q)]
    n_ok = 0
    for name, fn, arg in rungs:
        t0 = time.perf_counter()
        try:
            r = jax.jit(fn).lower(arg).compile()
            del r
            n_ok += 1
            log("mh_bisect", {"rung": name, "ok": True,
                              "s": round(time.perf_counter() - t0, 1)})
        except Exception as e:
            log("mh_bisect",
                {"rung": name, "ok": False,
                 "error": f"{type(e).__name__}: {str(e)[:300]}"})
    # a transport-dead tunnel fails every rung with no data; a live
    # bisect always compiles at least copy3d
    return n_ok > 0


def _swin_attention_variant(kind):
    """Ablated WindowAttention.forward bodies for phase_vision_breakdown
    (module-level so the CPU suite can exercise them without hardware).

    Matches the CURRENT WindowAttention contract (ISSUE 10): image-layout
    input ``forward(self, x_img, mask=None, shift=0)`` with roll/window
    partition handled inside — the ablated bodies therefore perform the
    roll + partition/reverse themselves via the reference helpers, so
    the ``identity`` rung still measures exactly the GEMMs + norms +
    partition/roll transposes the PERF.md ablation table is built on."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.dispatch import apply as _apply
    from paddle_tpu.ops.pallas.window_attention import (
        window_partition, window_reverse)

    def forward(self, x_img, mask=None, shift=0):
        n_tok = self.ws * self.ws
        heads = self.num_heads
        hd = self.dim // heads
        dim = self.dim
        ws = self.ws
        B, H, W, _ = x_img.shape
        shift = int(shift)
        qkv = self.qkv(x_img)                      # [B, H, W, 3C]

        def body(qkv_img, bias_tab, mask_v):
            x = qkv_img
            if shift:
                x = jnp.roll(x, (-shift, -shift), axis=(1, 2))
            wins = window_partition(x, ws)         # [B*nW, n_tok, 3C]
            if kind == "identity":
                # keep BOTH projection GEMMs (qkv + proj) AND the
                # roll/partition machinery so the mm_only-identity delta
                # isolates the attention math alone. All three qkv
                # slices are consumed (summed) — a lone [..., :dim]
                # slice would let XLA's slice-of-dot rewrite shrink the
                # qkv GEMM to a third and skew the ablation
                out = (wins[..., :dim] + wins[..., dim:2 * dim]
                       + wins[..., 2 * dim:])
            else:
                Bw = wins.shape[0]
                qkv_ = wins.reshape(Bw, n_tok, 3, heads, hd)
                q, k, v = (qkv_[:, :, i].transpose(0, 2, 1, 3)
                           for i in range(3))
                attn = (q * self.scale) @ k.transpose(0, 1, 3, 2)
                if kind != "mm_only":
                    if mask_v is not None:
                        nw = mask_v.shape[0]
                        attn = attn.reshape(Bw // nw, nw, heads, n_tok,
                                            n_tok) + mask_v[None, :, None]
                        attn = attn.reshape(Bw, heads, n_tok, n_tok)
                    attn = jax.nn.softmax(attn, axis=-1)
                out = (attn @ v).transpose(0, 2, 1, 3).reshape(
                    Bw, n_tok, dim)
            img = window_reverse(out, ws, H, W)    # [B, H, W, C]
            if shift:
                img = jnp.roll(img, (shift, shift), axis=(1, 2))
            return img

        return self.proj(_apply("window_attention", body, qkv,
                                self.rel_bias, mask))

    return forward


def phase_vision_breakdown():
    """Localize the vision-bench MFU gap (r5 hardware: ResNet50 ~9.7%,
    ViT-B ~15%, Swin-T ~3.3% MFU vs GPT-125M's 37.9%). All three share
    the train-step builder + AMP + slope timing with GPT, so the gap is
    model-structure cost. Swin is timed at one fixed batch under three
    attention ablations; differences localize the windowed-attention
    pipeline:
      full − no_bias     = relative-position bias gather+add
      no_bias − mm_only  = softmax (+ shift mask) on [.,h,49,49] tiles
      mm_only − identity = the tiny 49x32x49 batched attention matmuls
      identity           = GEMMs + norms + partition/roll transposes
    ResNet50/ViT-B are re-timed at the same batch for a comparable row."""
    import bench as bench_mod
    from paddle_tpu.vision import models as V
    from paddle_tpu.vision.models import swin as swin_mod

    swin_variant = _swin_attention_variant
    batch = 64
    n_ok = 0
    orig = swin_mod.WindowAttention.forward
    for kind in ("full", "no_bias", "mm_only", "identity"):
        try:
            swin_mod.WindowAttention.forward = (
                orig if kind == "full" else swin_variant(kind))
            r = bench_mod._bench_vision_model(
                lambda: V.swin_t(num_classes=1000), f"swin_{kind}",
                flops_per_image=3 * 4.5e9, batch_candidates=[batch],
                iters=6)
            log("vision_breakdown",
                {"model": f"swin_t[{kind}]", "batch": batch,
                 "images_per_sec": r.get("value"),
                 "ms_per_step": round(batch / r["value"] * 1e3, 2)
                 if r.get("value") else None,
                 "note": r.get("note", "")})
            n_ok += bool(r.get("value"))
        except Exception as e:
            log("vision_breakdown",
                {"model": f"swin_t[{kind}]",
                 "error": f"{type(e).__name__}: {str(e)[:200]}"})
        finally:
            swin_mod.WindowAttention.forward = orig
    for name, factory, fpi in (
            ("resnet50", lambda: V.resnet50(num_classes=1000), 3 * 4.09e9),
            ("vit_b_16", lambda: V.vit_b_16(num_classes=1000), 3 * 17.6e9)):
        try:
            r = bench_mod._bench_vision_model(
                factory, name, flops_per_image=fpi,
                batch_candidates=[batch], iters=6)
            log("vision_breakdown",
                {"model": name, "batch": batch,
                 "images_per_sec": r.get("value"),
                 "ms_per_step": round(batch / r["value"] * 1e3, 2)
                 if r.get("value") else None,
                 "mfu_pct": round((r.get("value") or 0.0) * fpi / 197e12
                                  * 100, 1),
                 "note": r.get("note", "")})
            n_ok += bool(r.get("value"))
        except Exception as e:
            log("vision_breakdown",
                {"model": name,
                 "error": f"{type(e).__name__}: {str(e)[:200]}"})
    return n_ok > 0


def phase_bench():
    t0 = time.perf_counter()
    # op-level trace of the timed GPT run (bench.py honors
    # BENCH_XPROF_DIR): an unattended window leaves the xplane artifact
    # on disk for later per-op analysis (the r3 step-cost table came
    # from exactly this kind of trace)
    xprof_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "xprof_r5")
    env = dict(os.environ, BENCH_XPROF_DIR=xprof_dir)
    r = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                       text=True, timeout=3600, env=env)
    lines = [l for l in r.stdout.splitlines() if l.strip().startswith("{")]
    log("bench", {"seconds": round(time.perf_counter() - t0, 1),
                  "json_lines": lines,
                  "stderr_tail": r.stderr[-500:]})
    # Persist every non-degraded line for bench.py's probe-failure reuse
    # path (VERDICT r3 Next #1): one JSON object per line, timestamped.
    good = []
    for line in lines:
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if obj.get("source") == "chip_session":
            # bench.py reused one of OUR records (probe failed): do not
            # re-persist it with a fresh timestamp — that would reset a
            # stale measurement's age every cycle
            continue
        if not obj.get("degraded") and obj.get("value", 0) > 0:
            obj["captured_at"] = time.time()
            good.append(obj)
    if good:
        with open(GOOD_BENCH, "a") as f:
            for obj in good:
                f.write(json.dumps(obj) + "\n")
    # success = the subprocess completed AND emitted results; a dead
    # tunnel (rc != 0, no lines) must not write a done marker
    return r.returncode == 0 and bool(lines)


PHASES = {"bench_quick": phase_bench_quick,
          "breakdown": phase_breakdown,
          "vision_breakdown": phase_vision_breakdown,
          "sanity": phase_sanity, "sweep": phase_sweep,
          "kernels": phase_kernels, "gqa_ab": phase_gqa_ab,
          "autotune": phase_autotune_seed,
          "generate": phase_generate, "decode_quant": phase_decode_quant,
          "generate_1p3b": phase_generate_1p3b,
          "memory_headroom": phase_memory_headroom,
          "mh_bisect": phase_mh_bisect, "bench": phase_bench,
          "layout_step_ab": phase_layout_step_ab}


def _completed_phases(max_age_s=24 * 3600):
    """Phases with a fresh completion marker in the log. Consecutive
    SHORT windows must make cumulative progress: without this, every
    watcher-triggered run restarts at bench_quick and a series of
    5-minute windows never reaches the later phases. A phase that
    crashed or was cut mid-run leaves no marker and reruns."""
    done = set()
    try:
        with open(LOG) as f:
            for line in f:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if e.get("done") and "phase" in e and \
                        time.time() - e.get("at", 0) <= max_age_s:
                    done.add(e["phase"])
    except OSError:
        pass
    return done


def main():
    # persistent XLA compile cache, shared with bench.py: the first
    # window pays the compiles, every later window (and the driver's
    # end-of-round bench run) reuses them
    from paddle_tpu.backend_guard import enable_persistent_compile_cache

    enable_persistent_compile_cache(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_tpu_cache"))
    # order (VERDICT r4 Next #1 — budget the first 3 minutes of any
    # window): 1. bench_quick lands a driver-reusable headline record,
    # 2. the flash fwd+bwd sweep + layout A/B decide the kernel story,
    # then sanity/kernels/full-bench, then the heavier serving/memory
    # phases. An early tunnel drop costs the least important data.
    args = [a for a in sys.argv[1:] if a != "--force"]
    force = "--force" in sys.argv[1:]
    names = args or ["bench_quick", "sweep", "sanity", "kernels",
                     "autotune", "bench", "breakdown", "gqa_ab",
                     "decode_quant", "generate",
                     "generate_1p3b", "memory_headroom",
                     "vision_breakdown", "mh_bisect",
                     "layout_step_ab"]
    done = set() if (force or args) else _completed_phases()
    for n in names:
        if n in done:
            print(f"[skip] {n}: completed within 24h "
                  "(pass phases explicitly or --force to rerun)",
                  flush=True)
            continue
        try:
            ok = PHASES[n]()
            # None = raise-through phase (reaching here IS success);
            # phases that swallow per-item errors return an explicit
            # bool so an all-failed run never writes a marker
            if ok is None or ok:
                log(n, {"done": True, "at": time.time()})
            else:
                log(n, {"error": "phase produced no successful "
                                 "measurements (no done marker)"})
        except Exception as e:
            log(n, {"error": f"{type(e).__name__}: {str(e)[:300]}"})


if __name__ == "__main__":
    main()
