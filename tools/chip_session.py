"""One-shot TPU measurement session: run everything perf-related while the
chip is reachable, append results to tools/chip_session_log.json as each
phase lands (the tunnel can drop at any time — nothing waits on anything
it doesn't need).

Phases:
  1. sanity matmul (chip + timing-method check)
  2. flash fwd and fwd+bwd block sweep at the bench shape
  3. autotune-seed: run _tuned_blocks for the bench + ViT signatures so
     the on-disk cache is hot for bench.py
  4. bench.py subprocess (headline + secondary JSON lines)

Usage: python tools/chip_session.py [phase...]   (default: all)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, ".")

LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "chip_session_log.jsonl")


def log(phase, payload):
    # JSONL append: crash-safe — a tunnel drop mid-write can at worst
    # truncate the LAST line, never clobber earlier measurements
    entry = {"t": time.strftime("%H:%M:%S"), "phase": phase, **payload}
    with open(LOG, "a") as f:
        f.write(json.dumps(entry) + "\n")
    print(f"[{entry['t']}] {phase}: {payload}", flush=True)


def sync(x):
    import numpy as np

    import jax

    return float(np.asarray(jax.device_get(x.ravel()[0:1]), np.float32)[0])


def slope(f, x, n1=4, n2=16, reps=2):
    def chain(n):
        y = x
        for _ in range(n):
            y = f(y)
        sync(y)

    chain(2)
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter(); chain(n1); d1 = time.perf_counter() - t0
        t0 = time.perf_counter(); chain(n2); d2 = time.perf_counter() - t0
        best = min(best, (d2 - d1) / (n2 - n1))
    return best


def phase_sanity():
    import jax
    import jax.numpy as jnp

    x = jnp.ones((8192, 8192), jnp.bfloat16)
    f = jax.jit(lambda x: x @ x)
    t = slope(f, x)
    tflops = 2 * 8192**3 / t / 1e12
    log("sanity", {"matmul8192_ms": round(t * 1e3, 2),
                   "tflops": round(tflops, 1)})


def phase_sweep():
    import numpy as np

    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention as FA

    B, H, S, D = 32, 12, 1024, 64
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
    k = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
    v = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
    flops = B * H * 4 * S * S * D * 0.5
    for bq, bk in [(1024, 1024), (512, 1024), (256, 512), (512, 512),
                   (256, 256), (128, 128)]:
        try:
            f = jax.jit(lambda x, bq=bq, bk=bk: FA._flash_core(
                x, k, v, True, bq, bk))
            t = slope(f, q)
            g = jax.jit(jax.grad(lambda x, bq=bq, bk=bk: FA._flash_core(
                x, k, v, True, bq, bk).astype(jnp.float32).sum()))
            tg = slope(g, q)
            log("sweep", {"blocks": f"{bq}x{bk}",
                          "fwd_ms": round(t * 1e3, 2),
                          "fwd_tflops": round(flops / t / 1e12, 1),
                          "fwdbwd_ms": round(tg * 1e3, 2),
                          "fwdbwd_tflops": round(3.5 * flops / tg / 1e12, 1)})
        except Exception as e:
            log("sweep", {"blocks": f"{bq}x{bk}",
                          "error": f"{type(e).__name__}: {str(e)[:100]}"})


def phase_kernels():
    """fused_norm / rope / decode-attention micro-benchmarks (PERF.md's
    'not yet measured on hardware' list)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    rs = np.random.RandomState(0)

    import importlib

    # fused RMS norm vs XLA-fused jnp at GPT-125M shapes
    FN = importlib.import_module("paddle_tpu.ops.pallas.fused_norm")
    x = jnp.asarray(rs.randn(32 * 1024, 768), jnp.bfloat16)
    w = jnp.asarray(rs.randn(768), jnp.bfloat16)
    try:
        f_pal = jax.jit(
            lambda x: FN.fused_norm_pallas(x, w=w, eps=1e-5, kind="rms"))

        def jnp_rms(x):
            x32 = x.astype(jnp.float32)
            y = x32 * jax.lax.rsqrt(
                jnp.mean(x32 * x32, -1, keepdims=True) + 1e-5)
            return (y * w.astype(jnp.float32)).astype(x.dtype)

        f_jnp = jax.jit(jnp_rms)
        log("kernels", {"op": "rms_norm 32kx768",
                        "pallas_ms": round(slope(f_pal, x) * 1e3, 3),
                        "jnp_ms": round(slope(f_jnp, x) * 1e3, 3)})
    except Exception as e:
        log("kernels", {"op": "rms_norm", "error": str(e)[:150]})

    # rope at bench shape (neox phases)
    try:
        RP = importlib.import_module("paddle_tpu.ops.pallas.rope")
        B, S, H, D = 32, 1024, 12, 64
        qr = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
        inv = 1.0 / (10000.0 ** (np.arange(0, D, 2) / D))
        ph = np.arange(S)[:, None] * inv[None, :]
        cos = jnp.asarray(np.cos(np.concatenate([ph, ph], -1))[None, :,
                                                               None, :],
                          jnp.float32)
        sin = jnp.asarray(np.sin(np.concatenate([ph, ph], -1))[None, :,
                                                               None, :],
                          jnp.float32)
        f_rope = jax.jit(lambda q: RP.rope_pallas(q, cos, sin))
        log("kernels", {"op": f"rope {B}x{S}x{H}x{D}",
                        "pallas_ms": round(slope(f_rope, qr) * 1e3, 3)})
    except Exception as e:
        log("kernels", {"op": "rope", "error": str(e)[:150]})

    # decode attention (paged KV single-token) at serving shape
    try:
        DA = importlib.import_module(
            "paddle_tpu.ops.pallas.decode_attention")
        B, H, S, D = 8, 12, 2048, 64
        qd = jnp.asarray(rs.randn(B, H, D), jnp.bfloat16)
        kc = jnp.asarray(rs.randn(B, H, S, D), jnp.bfloat16)
        vc = jnp.asarray(rs.randn(B, H, S, D), jnp.bfloat16)
        pos = jnp.full((B,), S - 1, jnp.int32)
        f_dec = jax.jit(lambda q: DA.decode_attention(q, kc, vc, pos))
        t = slope(f_dec, qd)
        bytes_rw = 2 * B * H * S * D * 2  # K+V bf16 reads dominate
        log("kernels", {"op": f"decode B{B} S{S}",
                        "pallas_ms": round(t * 1e3, 3),
                        "gbps": round(bytes_rw / t / 1e9, 1)})
    except Exception as e:
        log("kernels", {"op": "decode", "error": str(e)[:150]})


def phase_autotune_seed():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention as FA

    for (b, s, h, d) in [(32, 1024, 12, 64), (16, 1024, 12, 64),
                         (8, 1024, 12, 64)]:
        t0 = time.perf_counter()
        blocks = FA._tuned_blocks(b, s, s, h, d, jnp.bfloat16, True)
        log("autotune", {"sig": f"{b}x{s}x{h}x{d}", "picked": list(blocks),
                         "seconds": round(time.perf_counter() - t0, 1)})


def phase_generate():
    """GPT-125M single-chip decode throughput over the static KV cache
    (serving metric: tokens/s at batch 8, prompt 128, 128 new tokens)."""
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(0)
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=2048)
    model = GPTForCausalLM(cfg)
    model.eval()
    rs = np.random.RandomState(0)
    B, S0, NEW = 8, 128, 128
    prompt = P.to_tensor(rs.randint(0, cfg.vocab_size, (B, S0)), "int32")
    t0 = time.perf_counter()
    out = model.generate(prompt, max_new_tokens=NEW)
    _ = np.asarray(out._value)  # sync
    warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = model.generate(prompt, max_new_tokens=NEW)
    _ = np.asarray(out._value)
    dt = time.perf_counter() - t0
    log("generate", {"warm_s": round(warm, 1), "steady_s": round(dt, 2),
                     "tokens_per_s": round(B * NEW / dt, 1),
                     "ms_per_token_step": round(dt / NEW * 1e3, 2)})


GOOD_BENCH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "last_good_bench.jsonl")


def phase_bench():
    t0 = time.perf_counter()
    r = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                       text=True, timeout=3600)
    lines = [l for l in r.stdout.splitlines() if l.strip().startswith("{")]
    log("bench", {"seconds": round(time.perf_counter() - t0, 1),
                  "json_lines": lines,
                  "stderr_tail": r.stderr[-500:]})
    # Persist every non-degraded line for bench.py's probe-failure reuse
    # path (VERDICT r3 Next #1): one JSON object per line, timestamped.
    good = []
    for line in lines:
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if obj.get("source") == "chip_session":
            # bench.py reused one of OUR records (probe failed): do not
            # re-persist it with a fresh timestamp — that would reset a
            # stale measurement's age every cycle
            continue
        if not obj.get("degraded") and obj.get("value", 0) > 0:
            obj["captured_at"] = time.time()
            good.append(obj)
    if good:
        with open(GOOD_BENCH, "a") as f:
            for obj in good:
                f.write(json.dumps(obj) + "\n")


PHASES = {"sanity": phase_sanity, "sweep": phase_sweep,
          "kernels": phase_kernels, "autotune": phase_autotune_seed,
          "generate": phase_generate, "bench": phase_bench}


def main():
    names = sys.argv[1:] or ["sanity", "sweep", "kernels", "autotune",
                             "generate", "bench"]
    for n in names:
        try:
            PHASES[n]()
        except Exception as e:
            log(n, {"error": f"{type(e).__name__}: {str(e)[:300]}"})


if __name__ == "__main__":
    main()
