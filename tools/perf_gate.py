"""Perf-regression gate + trace merge for bench telemetry.

Closes the observability loop (ISSUE 2): BENCH_* numbers stop being
trend data a human eyeballs and become an enforced floor.

Gate mode (default):
    python tools/perf_gate.py results.json [--baseline tools/last_good_bench.jsonl]
        [--tolerance 0.10] [--metric-tolerance METRIC=FRAC ...] [--update]

  `results.json` is whatever `bench.py --telemetry` printed: JSON lines
  (one per metric, headline last), a single object, or an array.  Each
  row's `value` is compared against the freshest non-degraded baseline
  row for the same metric: higher-is-better metrics (throughputs) fail
  when value < baseline*(1-tol); lower-is-better (``*_ms`` / rows
  flagged ``lower_better``) fail when value > baseline*(1+tol).
  Headline rows carrying an embedded telemetry block also gate the
  derived `<metric>.mfu` (higher-better) and `<metric>.steady_wall_ms`
  (lower-better) series once the baseline knows them.  Degraded
  (CPU-proxy) current rows are skipped — a proxy number must never be
  judged against an on-chip floor.  Exit codes: 0 pass, 2 regression,
  1 usage/IO error.  `--update` appends the current non-degraded rows
  to the baseline (rolling the floor forward after a verified win).

Check mode:
    python tools/perf_gate.py --check-only [--baseline PATH]
  Validates that the baseline parses and every row is gateable — the
  fast CI smoke (wired as a non-slow test).

Merge mode:
    python tools/perf_gate.py --merge-trace out.json
        [--spans tracer.json ...] [--step-stats steps.jsonl ...]
        [--flight flight.jsonl ...]
  Folds span-tracer exports (Chrome JSON or trace_event JSONL),
  step-stats JSONL, and flight-recorder dumps into ONE Perfetto file:
  each source family gets its own process row so unrelated clocks never
  falsely align.

stdlib-only on purpose: the gate must run in CI contexts (and on hosts)
without importing jax-heavy paddle_tpu.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "last_good_bench.jsonl")
DEFAULT_STATIC_BUDGET = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "perf_budget.json")
DEFAULT_TOLERANCE = 0.10

# pids for merged-trace source families (span events keep the pid the
# tracer recorded — theirs was a real process)
_PID_STEPS = 9001
_PID_FLIGHT = 9002


# ------------------------------ loading ------------------------------

def _iter_json_values(text):
    """Yield parsed JSON values from `text`: JSON-lines first, falling
    back to one whole-document parse (object or array)."""
    vals, bad = [], 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            vals.append(json.loads(line))
        except ValueError:
            bad += 1
    if vals and not bad:
        return vals
    try:
        whole = json.loads(text)
    except ValueError:
        return vals
    return whole if isinstance(whole, list) else [whole]


def _metric_rows(values):
    return [v for v in values
            if isinstance(v, dict) and isinstance(v.get("metric"), str)
            and isinstance(v.get("value"), (int, float))
            and not isinstance(v.get("value"), bool)]


def load_results(path):
    """Gateable rows from a bench output file, with derived telemetry
    metrics (mfu, steady wall) expanded from embedded telemetry blocks."""
    with open(path) as f:
        rows = _metric_rows(_iter_json_values(f.read()))
    out = list(rows)
    for r in rows:
        tele = r.get("telemetry")
        if not isinstance(tele, dict):
            continue
        ss = tele.get("step_stats")
        if not isinstance(ss, dict):
            continue
        base = r["metric"]
        if isinstance(ss.get("mfu"), (int, float)):
            out.append({"metric": base + ".mfu", "value": float(ss["mfu"]),
                        "unit": "mfu", "degraded": r.get("degraded", False)})
        wall = ss.get("wall_ms")
        if isinstance(wall, dict) and \
                isinstance(wall.get("mean"), (int, float)):
            out.append({"metric": base + ".steady_wall_ms",
                        "value": float(wall["mean"]), "unit": "ms",
                        "lower_better": True,
                        "degraded": r.get("degraded", False)})
    return out


def load_baseline(path):
    """{metric: row} — freshest (captured_at, then file order)
    non-degraded, non-zero row per metric."""
    best = {}
    with open(path) as f:
        rows = _metric_rows(_iter_json_values(f.read()))
    for i, r in enumerate(rows):
        if r.get("degraded") or r["value"] <= 0:
            continue
        m = r["metric"]
        key = (r.get("captured_at", 0), i)
        if m not in best or key >= best[m][0]:
            best[m] = (key, r)
    return {m: r for m, (_k, r) in best.items()}


def load_static_budget(path):
    """{metric: row} from the pt_lint perf-audit budget file
    (tools/perf_budget.json): each budgeted program metric becomes a
    lower-better baseline row named ``static.<program>.<metric>`` with
    ZERO tolerance — a budget is a hard ceiling, not a floor with
    slack. Merged next to the measured bench floors so one perf_gate
    run judges both views; the static rows only gate when the results
    file actually carries them (``pt_lint --perf --emit-static``)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    budgets = data.get("budgets", {})
    if not isinstance(budgets, dict):
        return {}
    out = {}
    for prog, vals in budgets.items():
        if not isinstance(vals, dict):
            continue
        for name, v in vals.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                m = f"static.{prog}.{name}"
                out[m] = {"metric": m, "value": v,
                          "lower_better": True, "tolerance": 0.0}
    return out


def _lower_better(row, base_row):
    if row.get("lower_better") or (base_row or {}).get("lower_better"):
        return True
    return row["metric"].endswith("_ms")


# ------------------------------ gating ------------------------------

def gate(results, baseline, tolerance=DEFAULT_TOLERANCE,
         metric_tolerances=None):
    """Compare result rows to baseline rows.  Returns (failures, report)
    where report is a list of human-readable lines covering every row."""
    metric_tolerances = metric_tolerances or {}
    failures, report = [], []
    for r in results:
        m = r["metric"]
        if r.get("degraded"):
            report.append(f"SKIP  {m}: degraded run (value {r['value']}) — "
                          "proxy numbers are not judged against the floor")
            continue
        base = baseline.get(m)
        if base is None:
            report.append(f"NEW   {m}: {r['value']} (no baseline; "
                          "--update to start gating it)")
            continue
        # row-level tolerance (static budget rows pin it to 0) loses to
        # an explicit --metric-tolerance, wins over the global default
        row_tol = (base or {}).get("tolerance", tolerance)
        tol = float(metric_tolerances.get(m, row_tol))
        bv, cv = float(base["value"]), float(r["value"])
        if _lower_better(r, base):
            floor = bv * (1.0 + tol)
            ok = cv <= floor
            direction = "above"
        else:
            floor = bv * (1.0 - tol)
            ok = cv >= floor
            direction = "below"
        delta = (cv - bv) / bv if bv else 0.0
        line = (f"{'PASS' if ok else 'FAIL'}  {m}: {cv} vs baseline {bv} "
                f"({delta:+.2%}, tolerance {tol:.0%})")
        if not ok:
            line += f" — {direction} the gated floor {floor:.4g}"
            failures.append(line)
        report.append(line)
    return failures, report


def update_baseline(results, path):
    """Append the current non-degraded rows to the baseline JSONL (the
    telemetry block is dropped — the baseline stores gateable facts, not
    provenance payloads)."""
    now = time.time()
    n = 0
    with open(path, "a") as f:
        for r in results:
            if r.get("degraded") or r["value"] <= 0:
                continue
            if r["metric"].startswith("static."):
                continue  # owned by tools/perf_budget.json, not the
                # bench floor (--update must not fork the budget)
            row = {k: v for k, v in r.items() if k != "telemetry"}
            row["captured_at"] = now
            f.write(json.dumps(row) + "\n")
            n += 1
    return n


def check_baseline(path):
    """Errors that would make the baseline un-gateable (the --check-only
    CI smoke)."""
    errors = []
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        return [f"cannot read baseline {path}: {e}"]
    n_rows = 0
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError as e:
            errors.append(f"line {i + 1}: not JSON ({e})")
            continue
        if not isinstance(obj, dict):
            errors.append(f"line {i + 1}: not an object")
            continue
        if not isinstance(obj.get("metric"), str):
            errors.append(f"line {i + 1}: missing metric name")
        v = obj.get("value")
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            errors.append(f"line {i + 1}: missing numeric value")
        n_rows += 1
    if n_rows == 0:
        errors.append(f"baseline {path} has no metric rows")
    return errors


# ------------------------------ merging ------------------------------

def _load_jsonl(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def _span_events(path):
    """Events from a tracer export: Chrome JSON ({"traceEvents": [...]})
    or JSONL of trace_event lines."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and isinstance(doc.get("traceEvents"), list):
        return [e for e in doc["traceEvents"] if isinstance(e, dict)]
    events = []
    for obj in _iter_json_values(text):
        if isinstance(obj, dict) and obj.get("phase") == "trace_event":
            events.append({k: v for k, v in obj.items()
                           if k not in ("phase", "t")})
    return events


def _step_events(path):
    """step_stats JSONL -> per-run frame events (walls accumulated from
    0 in record order: the stream has no sub-second timestamps, so the
    reconstruction preserves durations and order, not absolute time)."""
    events, cursor, tids = [], {}, {}
    for e in _load_jsonl(path):
        if not isinstance(e, dict) or e.get("phase") != "step_stats":
            continue
        run = str(e.get("run_id", "?"))
        tid = tids.setdefault(run, len(tids) + 1)
        n = int(e.get("n_steps", 1))
        wall_us = float(e.get("wall_ms", 0)) * 1e3 * n
        t0 = cursor.get(run, 0.0)
        cursor[run] = t0 + wall_us
        step = e.get("step", 0)
        # mirror StepTimer's own frame naming: an n-step compiled scan is
        # one block, not one anomalously slow step
        name = "compile+step" if e.get("compile") else (
            f"step {step}" if n == 1 else f"steps {step}..{step + n - 1}")
        args = {k: e[k] for k in ("step", "n_steps", "wall_ms", "compile",
                                  "tokens_per_s", "mfu") if k in e}
        events.append({"name": name, "cat": "step", "ph": "X",
                       "ts": round(t0, 3), "dur": round(wall_us, 3),
                       "pid": _PID_STEPS, "tid": tid, "args": args})
    meta = [{"name": "thread_name", "ph": "M", "pid": _PID_STEPS,
             "tid": tid, "args": {"name": f"steps:{run}"}}
            for run, tid in tids.items()]
    return meta + events


def _flight_events(path):
    """flight dump JSONL -> instant events (epoch walls normalized so the
    first event sits at ts 0)."""
    rows = [e for e in _load_jsonl(path)
            if isinstance(e, dict) and e.get("kind")
            and e.get("kind") != "flight.dump"]
    if not rows:
        return []
    t0 = min(float(e.get("t", 0)) for e in rows)
    events = []
    for e in rows:
        args = {k: v for k, v in e.items() if k not in ("kind", "t", "seq")}
        events.append({"name": str(e["kind"]), "cat": "flight", "ph": "i",
                       "s": "t",
                       "ts": round((float(e.get("t", t0)) - t0) * 1e6, 3),
                       "pid": _PID_FLIGHT, "tid": 1, "args": args})
    return events


def merge_trace(out_path, spans=(), step_stats=(), flight=()):
    """Fold the three stream families into one Perfetto-loadable file."""
    events = []
    for p in spans:
        events.extend(_span_events(p))
    steps = []
    for p in step_stats:
        steps.extend(_step_events(p))
    flights = []
    for p in flight:
        flights.extend(_flight_events(p))
    meta = []
    if steps:
        meta.append({"name": "process_name", "ph": "M", "pid": _PID_STEPS,
                     "tid": 0, "args": {"name": "step_stats (reconstructed "
                                        "timeline)"}})
    if flights:
        meta.append({"name": "process_name", "ph": "M", "pid": _PID_FLIGHT,
                     "tid": 0, "args": {"name": "flight recorder"}})
        meta.append({"name": "thread_name", "ph": "M", "pid": _PID_FLIGHT,
                     "tid": 1, "args": {"name": "events"}})
    doc = {"traceEvents": events + meta + steps + flights,
           "displayTimeUnit": "ms",
           "otherData": {"merged_from": {
               "spans": list(spans), "step_stats": list(step_stats),
               "flight": list(flight)}}}
    d = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(d, exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(doc, f, default=str)
    return out_path


# ------------------------------ CLI ------------------------------

def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="perf_gate",
        description="perf-regression gate + trace merge (see module doc)")
    p.add_argument("results", nargs="?", help="bench output to gate")
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument("--static-budget", default=DEFAULT_STATIC_BUDGET,
                   help="pt_lint perf-audit budget file merged into the "
                        "baseline as zero-tolerance static.* rows "
                        "(default tools/perf_budget.json; '' disables)")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="allowed fractional drop (default 0.10)")
    p.add_argument("--metric-tolerance", action="append", default=[],
                   metavar="METRIC=FRAC",
                   help="per-metric tolerance override (repeatable)")
    p.add_argument("--update", action="store_true",
                   help="append current rows to the baseline")
    p.add_argument("--check-only", action="store_true",
                   help="validate the baseline file and exit")
    p.add_argument("--merge-trace", metavar="OUT",
                   help="write a merged Perfetto file instead of gating")
    p.add_argument("--spans", nargs="*", default=[],
                   help="span-tracer exports (chrome JSON or JSONL)")
    p.add_argument("--step-stats", nargs="*", default=[],
                   help="step_stats JSONL streams")
    p.add_argument("--flight", nargs="*", default=[],
                   help="flight-recorder dump JSONL files")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = _parse_args(list(sys.argv[1:] if argv is None else argv))

    if args.merge_trace:
        try:
            out = merge_trace(args.merge_trace, spans=args.spans,
                              step_stats=args.step_stats,
                              flight=args.flight)
        except OSError as e:
            print(f"perf_gate: merge failed: {e}", file=sys.stderr)
            return 1
        with open(out) as f:
            n = len(json.load(f)["traceEvents"])
        print(f"perf_gate: merged {n} events -> {out}")
        return 0

    if args.check_only:
        errors = check_baseline(args.baseline)
        if errors:
            print(f"perf_gate: baseline {args.baseline} INVALID:")
            for e in errors[:20]:
                print(f"  - {e}")
            return 1
        base = load_baseline(args.baseline)
        n_static = 0
        if args.static_budget:
            if os.path.exists(args.static_budget):
                static = load_static_budget(args.static_budget)
                if not static:
                    print(f"perf_gate: static budget "
                          f"{args.static_budget} INVALID (no gateable "
                          f"budget entries)")
                    return 1
                n_static = len(static)
            elif args.static_budget != DEFAULT_STATIC_BUDGET:
                print(f"perf_gate: static budget {args.static_budget} "
                      f"missing")
                return 1
        print(f"perf_gate: baseline OK — {len(base)} gateable metrics "
              f"({args.baseline}), {n_static} static budget rows")
        return 0

    if not args.results:
        print("perf_gate: results file required (or --check-only / "
              "--merge-trace)", file=sys.stderr)
        return 1

    per_metric = {}
    for spec in args.metric_tolerance:
        if "=" not in spec:
            print(f"perf_gate: bad --metric-tolerance {spec!r} "
                  "(want METRIC=FRAC)", file=sys.stderr)
            return 1
        m, frac = spec.split("=", 1)
        try:
            per_metric[m] = float(frac)
        except ValueError:
            print(f"perf_gate: bad tolerance in {spec!r}", file=sys.stderr)
            return 1

    try:
        results = load_results(args.results)
    except OSError as e:
        print(f"perf_gate: cannot read results: {e}", file=sys.stderr)
        return 1
    if not results:
        print(f"perf_gate: no metric rows in {args.results}",
              file=sys.stderr)
        return 1
    try:
        baseline = load_baseline(args.baseline)
    except OSError as e:
        print(f"perf_gate: cannot read baseline: {e}", file=sys.stderr)
        return 1
    # static budgets sit next to the measured floors: a results file
    # carrying `pt_lint --perf --emit-static` rows is judged against the
    # committed budget in the same run that gates the bench. Same error
    # discipline as --check-only: a typo'd path or an empty budget must
    # fail, not silently gate nothing (static rows would all read NEW)
    if args.static_budget:
        if os.path.exists(args.static_budget):
            static = load_static_budget(args.static_budget)
            if not static:
                print(f"perf_gate: static budget {args.static_budget} "
                      f"INVALID (no gateable budget entries)",
                      file=sys.stderr)
                return 1
            baseline.update(static)
        elif args.static_budget != DEFAULT_STATIC_BUDGET:
            print(f"perf_gate: static budget {args.static_budget} "
                  f"missing", file=sys.stderr)
            return 1

    failures, report = gate(results, baseline, tolerance=args.tolerance,
                            metric_tolerances=per_metric)
    for line in report:
        print(line)
    if args.update:
        n = update_baseline(results, args.baseline)
        print(f"perf_gate: baseline updated (+{n} rows)")
    if failures:
        print(f"perf_gate: {len(failures)} regression(s) beyond tolerance",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
