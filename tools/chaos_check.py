#!/usr/bin/env python
"""Chaos smoke: a short train loop under seeded-random fault injection
that must RECOVER, not merely survive.

What it does (all CPU, all deterministic given --seed):

  1. builds a tiny dp=2 `DistributedTrainStep` with the NaN guard armed
     and a `CheckpointManager` attached (keep-last-2, CRC'd, atomic);
  2. arms probabilistic faults at train.step (NaN poison), plus periodic
     torn/corrupt checkpoint writes;
  3. runs N steps, checkpointing every few: NaN steps must be skipped
     (state preserved), guard escalation must roll back through the
     checkpoint rotation, torn/corrupt saves must never take down the
     restore path;
  4. asserts at the end: loss finite, every injected fault accounted
     for in the metrics registry, at least one recovery event fired.

Exit 0 = recovered; exit 1 = a reflex failed.  CI runs this alongside
the `chaos`-marked pytest matrix (kept out of tier-1 — see pytest.ini).

Usage:  JAX_PLATFORMS=cpu python tools/chaos_check.py [--steps 40]
        [--seed 0] [--ckpt-every 5] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

# runnable as `python tools/chaos_check.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()


def run_chaos(steps=40, seed=0, ckpt_every=5, root=None):
    """Run the loop; returns a report dict (importable from tests)."""
    import numpy as np

    import paddle_tpu as P
    import paddle_tpu.nn as nn
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    from paddle_tpu.observability import metrics
    from paddle_tpu.resilience import StepGuard, faults

    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    obs.attach(crash_hook=False)
    P.seed(0)
    model = fleet.distributed_model(nn.Linear(16, 4))
    opt = P.optimizer.SGD(parameters=model.parameters(), learning_rate=0.05)
    guard = StepGuard(max_consecutive_bad=2, name="chaos")
    step = model.build_train_step(opt, nn.MSELoss(), guard=guard)
    root = root or tempfile.mkdtemp(prefix="chaos_ckpt_")
    step.attach_checkpoint_manager(CheckpointManager(root, keep_last_k=2))

    P.seed(2)
    x = P.randn([8, 16])
    y = P.randn([8, 4])

    faults.clear()
    # the random-but-seeded matrix: ~20% NaN steps, and every 3rd
    # checkpoint write torn or corrupted (alternating via two rules)
    faults.inject("train.step", kind="nan", p=0.2, seed=seed, times=None)
    faults.inject("checkpoint.write", kind="torn", every=5, seed=seed,
                  times=None)
    faults.inject("checkpoint.write", kind="corrupt", every=7, seed=seed,
                  times=None)

    losses, save_failures = [], 0
    step(x, y)  # step 0 clean-ish; ensures a state exists
    step.save_checkpoint()  # guaranteed good restore point
    try:
        for i in range(steps):
            losses.append(float(step(x, y)))
            if (i + 1) % ckpt_every == 0:
                try:
                    step.save_checkpoint()
                except faults.InjectedFault:
                    save_failures += 1  # torn save: rotation still valid
    finally:
        faults.clear()

    # health probe: one guaranteed-fault-free step — a skipped NaN step
    # reports a NaN *loss* by design (state untouched), so run health is
    # judged on what the preserved state produces, not on the last
    # injection's cosmetics
    final_loss = float(step(x, y))

    snap = metrics.snapshot()["counters"]
    obs.detach()
    res = {k: v for k, v in snap.items()
           if k.startswith("resilience.") and v}
    injected = sum(v for k, v in snap.items()
                   if k.startswith("resilience.faults"))
    skipped = sum(v for k, v in snap.items()
                  if k.startswith("resilience.skipped_steps"))
    final_finite = bool(np.isfinite(final_loss))
    nan_steps = sum(1 for v in losses if not np.isfinite(v))
    report = {
        "steps": steps,
        "seed": seed,
        "injected_faults": injected,
        "nan_steps_seen": nan_steps,
        "skipped_steps": skipped,
        "rollbacks": snap.get("resilience.rollbacks", 0),
        "torn_saves": save_failures,
        "final_loss": final_loss,
        "final_loss_finite": final_finite,
        "guard": guard.state_dict(),
        "resilience_counters": res,
        # "recovered" = the run ended healthy AND the reflexes actually
        # fired on the injected faults (a chaos run with no faults hit
        # is a broken chaos run, not a pass)
        "recovered": (final_finite and injected > 0
                      and skipped + snap.get("resilience.rollbacks", 0) > 0),
    }
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ckpt-every", type=int, default=5)
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON on stdout")
    args = ap.parse_args(argv)
    report = run_chaos(steps=args.steps, seed=args.seed,
                       ckpt_every=args.ckpt_every)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        for k in ("steps", "injected_faults", "nan_steps_seen",
                  "skipped_steps", "rollbacks", "torn_saves",
                  "final_loss", "recovered"):
            print(f"{k:>18}: {report[k]}")
    if not report["recovered"]:
        print("CHAOS CHECK FAILED: run did not recover", file=sys.stderr)
        return 1
    print("chaos check: recovered OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
