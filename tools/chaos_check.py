#!/usr/bin/env python
"""Chaos smoke: scenarios that must RECOVER, not merely survive.

Scenarios (--scenario, all CPU, all deterministic given --seed):

  * `train` (default): a short dp=2 train loop with the NaN guard armed
    and a CRC'd keep-last-2 `CheckpointManager`, under probabilistic
    NaN-step poison plus periodic torn/corrupt checkpoint writes — NaN
    steps must be skipped, escalation must roll back through the
    rotation, and the run must end healthy.
  * `overload`: an `InferenceServer` with a deliberately slow predictor
    takes more concurrent requests than max_inflight + queue_depth —
    every ADMITTED request must complete, the excess must be shed with
    429/503 + Retry-After, the shed count must match the
    `resilience.shed_requests` counters exactly, the same sheds must
    surface in the SLO report under their reason labels (ISSUE 7), and
    `GET /metrics` must serve histogram `_bucket{le=...}` series under
    the load.
  * `preemption`: a real SIGTERM lands mid-train-loop — the guarded
    step must write a checkpoint that passes `verify_checkpoint()`,
    exit via `TrainingPreempted`, and a fresh step must resume from it
    and train on to a finite loss.
  * `engine`: the continuous-batching engine under abandonment —
    sequences cancelled mid-decode, a client killed mid-stream, and a
    burst past admission capacity.  Every freed page must return to
    the pool (no leak), surviving sequences' outputs must be
    bit-identical to an uninterrupted run, and the sheds must surface
    in the SLO report under their reason labels.
  * `prefix`: the engine with PREFIX CACHING on, under a shared-prefix
    tenant workload on a deliberately tight pool — cancels mid-decode,
    a client killed mid-stream over HTTP, and enough page pressure to
    force the LRU idle-prefix reclaim tier.  Zero page leak AND zero
    refcount leak (after drain + cache clear the pool is EMPTY and the
    refcount table is empty), survivors bit-identical to a cold-cache
    (caching-disabled) replay, and a POISONED `X-Prefix-Fingerprint`
    header through a 2-replica router degrades to at worst a cache
    miss — never a wrong-token stream (the radix index matches real
    token values; the fingerprint is routing metadata only).
  * `surge`: a 10× OPEN-LOOP traffic step (tools/loadgen.py: Poisson
    arrivals, shared-prefix tenants, misbehaving clients) against a
    1-replica toy fleet with the SLO-driven `Autoscaler` attached —
    the surge must be absorbed with ZERO admitted-request failures
    and bounded p99, ≥1 scale-up must land mid-surge, and the
    ramp-down must drain replicas back to min size strictly through
    the zero-loss protocol (drain_mark before drain_sigterm, exit 0,
    zero replayed tokens — position-dependent toy tokens assert it).
  * `fleet`: a 3-replica `ReplicaFleet` behind the admission-aware
    `Router` under a concurrent mixed /predict + /generate burst;
    one replica is killed -9 and another SIGTERM-drained MID-BURST.
    Zero admitted-request failures (failover under the same
    X-Request-Id), zero replayed stream tokens (every delivered
    stream is an exact prefix of the deterministic expected
    sequence), every killed replica's sequence accounted (failed
    over, cleanly interrupted with a resumable prefix, or politely
    shed), and the fleet must RECOVER to full capacity after the
    supervisor relaunches both replicas — proven by a final all-ok
    burst.  Router failover/ejection counters and the
    `router.replicas{state}` gauges must be visible in the telemetry
    snapshot AND in a `tools/telemetry_agg.py` rollup of the fleet's
    dumps.  ISSUE 16: every replica's tenant-ledger book and the
    rollup's fleet merge must conserve (Σ per-tenant decode tokens +
    `~other` == `engine.tokens`) despite the kill/drain, the router's
    per-tenant ok counts must equal the clients' own tallies, and a
    10k-distinct-tenant sweep must stay within the K-entry bound.
  * `resume` (ISSUE 20): kill -9 one replica of a 3-replica GPT fleet
    mid-burst — the router must RESUME every broken stream on a
    survivor (prompt + delivered prefix resubmitted under the same
    X-Request-Id, first-token divergence check armed) so that ZERO
    streams surface as interrupted, zero tokens replay, and every
    stream is bit-exact with a local same-seed reference engine.
    Resume legs must ride the survivors' warmed radix prefix cache
    (`serving.resume_prefill{cache=hit|partial}` in the fleet rollup),
    the `router.stream_resumes`/`router.resume_gap_ms` series must
    survive `telemetry_agg`, and every replica book + the fleet merge
    must still bill resumed tokens exactly once.

Both `fleet` and `surge` additionally prove the metering plane's
bounded cardinality and conservation under churn; `surge` cross-checks
the loadgen per-tenant breakdown against the router's edge ledger and
reads the live `/debug/tenants` fleet merge; `prefix` gates per-tenant
prefix-saved attribution on that same surface.

Exit 0 = recovered; exit 1 = a reflex failed.  CI runs this alongside
the `chaos`-marked pytest matrix (kept out of tier-1 — see pytest.ini).

Usage:  JAX_PLATFORMS=cpu python tools/chaos_check.py [--scenario train]
        [--steps 40] [--seed 0] [--ckpt-every 5] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

import numpy as np

# runnable as `python tools/chaos_check.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()


def run_chaos(steps=40, seed=0, ckpt_every=5, root=None):
    """Run the loop; returns a report dict (importable from tests)."""
    import numpy as np

    import paddle_tpu as P
    import paddle_tpu.nn as nn
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    from paddle_tpu.observability import metrics
    from paddle_tpu.resilience import StepGuard, faults

    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    obs.attach(crash_hook=False)
    P.seed(0)
    model = fleet.distributed_model(nn.Linear(16, 4))
    opt = P.optimizer.SGD(parameters=model.parameters(), learning_rate=0.05)
    guard = StepGuard(max_consecutive_bad=2, name="chaos")
    step = model.build_train_step(opt, nn.MSELoss(), guard=guard)
    root = root or tempfile.mkdtemp(prefix="chaos_ckpt_")
    step.attach_checkpoint_manager(CheckpointManager(root, keep_last_k=2))

    P.seed(2)
    x = P.randn([8, 16])
    y = P.randn([8, 4])

    faults.clear()
    # the random-but-seeded matrix: ~20% NaN steps, and every 3rd
    # checkpoint write torn or corrupted (alternating via two rules)
    faults.inject("train.step", kind="nan", p=0.2, seed=seed, times=None)
    faults.inject("checkpoint.write", kind="torn", every=5, seed=seed,
                  times=None)
    faults.inject("checkpoint.write", kind="corrupt", every=7, seed=seed,
                  times=None)

    losses, save_failures = [], 0
    step(x, y)  # step 0 clean-ish; ensures a state exists
    step.save_checkpoint()  # guaranteed good restore point
    try:
        for i in range(steps):
            losses.append(float(step(x, y)))
            if (i + 1) % ckpt_every == 0:
                try:
                    step.save_checkpoint()
                except faults.InjectedFault:
                    save_failures += 1  # torn save: rotation still valid
    finally:
        faults.clear()

    # health probe: one guaranteed-fault-free step — a skipped NaN step
    # reports a NaN *loss* by design (state untouched), so run health is
    # judged on what the preserved state produces, not on the last
    # injection's cosmetics
    final_loss = float(step(x, y))

    snap = metrics.snapshot()["counters"]
    obs.detach()
    res = {k: v for k, v in snap.items()
           if k.startswith("resilience.") and v}
    injected = sum(v for k, v in snap.items()
                   if k.startswith("resilience.faults"))
    skipped = sum(v for k, v in snap.items()
                  if k.startswith("resilience.skipped_steps"))
    final_finite = bool(np.isfinite(final_loss))
    nan_steps = sum(1 for v in losses if not np.isfinite(v))
    report = {
        "steps": steps,
        "seed": seed,
        "injected_faults": injected,
        "nan_steps_seen": nan_steps,
        "skipped_steps": skipped,
        "rollbacks": snap.get("resilience.rollbacks", 0),
        "torn_saves": save_failures,
        "final_loss": final_loss,
        "final_loss_finite": final_finite,
        "guard": guard.state_dict(),
        "resilience_counters": res,
        # "recovered" = the run ended healthy AND the reflexes actually
        # fired on the injected faults (a chaos run with no faults hit
        # is a broken chaos run, not a pass)
        "recovered": (final_finite and injected > 0
                      and skipped + snap.get("resilience.rollbacks", 0) > 0),
    }
    return report


class _SlowEchoPredictor:
    """Stdlib+numpy predictor stub: sleeps `service_time` then echoes
    its input — a deterministic stand-in for a saturated device queue
    (no jax / saved model needed for the overload scenario)."""

    def __init__(self, service_time=0.05):
        self.service_time = float(service_time)

    def get_input_names(self):
        return ["x"]

    def get_output_names(self):
        return ["y"]

    def run(self, inputs):
        import time

        time.sleep(self.service_time)
        return [np.asarray(inputs[0])]


def run_overload(requests=24, max_inflight=2, queue_depth=3,
                 service_time=0.05, seed=0):
    """Overload chaos: fire `requests` concurrent clients at a server
    sized for max_inflight + queue_depth of them.  Returns a report;
    `recovered` means zero admitted-request failures, every excess
    request shed with a retryable status + Retry-After, and the shed
    count agreeing with `resilience.shed_requests` exactly."""
    import threading
    import urllib.error

    from paddle_tpu import observability as obs
    from paddle_tpu.inference.serving import InferenceClient, InferenceServer
    from paddle_tpu.observability import metrics

    obs.attach(crash_hook=False)
    metrics.reset()
    srv = InferenceServer(
        predictor=_SlowEchoPredictor(service_time),
        max_inflight=max_inflight, queue_depth=queue_depth,
        request_retries=1, request_timeout=30.0).start()
    results = []
    lock = threading.Lock()

    def one(i):
        client = InferenceClient(srv.address, timeout=30.0, retries=0)
        x = np.full((2, 2), float(i), np.float32)
        try:
            out = client.predict(x=x)
            ok = bool(np.array_equal(out["y"], x))
            row = ("ok" if ok else "corrupt", None, None)
        except urllib.error.HTTPError as e:
            row = ("shed" if e.code in (429, 503) else "error",
                   e.code, e.headers.get("Retry-After"))
        except Exception as e:  # noqa: BLE001 — report, don't crash
            row = ("error", type(e).__name__, None)
        with lock:
            results.append(row)

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # the scrape plane under load (ISSUE 7): /metrics must expose real
    # histogram buckets, and the SLO report must carry the sheds WITH
    # their reason labels — the router/autoscaler's input signals
    import urllib.request as _urlreq

    with _urlreq.urlopen(srv.address + "/metrics", timeout=10) as r:
        metrics_text = r.read().decode()
    slo_report = srv.slo.report(publish_gauges=False)
    drained = srv.shutdown()
    snap = metrics.snapshot()
    obs.detach()
    ok_n = sum(1 for r in results if r[0] == "ok")
    shed = [r for r in results if r[0] == "shed"]
    errors = [r for r in results if r[0] in ("error", "corrupt")]
    shed_counted = sum(v for k, v in snap["counters"].items()
                       if k.startswith("resilience.shed_requests"))
    slo_ep = slo_report.get("endpoints", {}).get("predict", {})
    slo_shed_reasons = {
        k.split(":", 1)[1]: v
        for k, v in slo_ep.get("errors_by_reason", {}).items()
        if k.startswith("shed:")}
    report = {
        "scenario": "overload",
        "requests": requests,
        "capacity": max_inflight + queue_depth,
        "completed": ok_n,
        "shed": len(shed),
        "shed_with_retry_after": sum(1 for r in shed if r[2] is not None),
        "shed_counter": shed_counted,
        "slo_shed_reasons": slo_shed_reasons,
        "slo_burn_rate": slo_ep.get("burn_rate"),
        "metrics_has_buckets": '_bucket{' in metrics_text,
        "admitted_failures": len(errors),
        "failure_detail": sorted({f"{r[0]}:{r[1]}" for r in errors}),
        "drained": bool(drained),
        "socket_closed": srv._httpd.socket.fileno() == -1,
        # every request either completed or was shed politely; the
        # counter agrees; at least one of each actually happened (an
        # overload run with no sheds did not exercise overload); the
        # sheds are visible in the SLO report under known reason labels
        # and the scrape plane serves histogram buckets
        "recovered": (len(errors) == 0 and ok_n > 0 and len(shed) > 0
                      and len(shed) == shed_counted
                      and all(r[2] is not None for r in shed)
                      and sum(slo_shed_reasons.values()) == shed_counted
                      and all(k in ("queue_full", "queue_timeout", "deadline",
                                  "draining")
                              for k in slo_shed_reasons)
                      and '_bucket{' in metrics_text
                      and bool(drained)),
    }
    return report


def run_preemption(steps=12, seed=0, preempt_at=5, root=None):
    """Preemption chaos: deliver a REAL SIGTERM mid-loop; the guarded
    step must checkpoint (verified), raise TrainingPreempted, and a
    fresh step must resume from the checkpoint and keep training."""
    import signal as _signal

    import paddle_tpu as P
    import paddle_tpu.nn as nn
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.distributed.checkpoint import (
        CheckpointManager, verify_checkpoint,
    )
    from paddle_tpu.observability import metrics
    from paddle_tpu.resilience.preemption import (
        PreemptionGuard, TrainingPreempted,
    )

    def build_step(mgr):
        topology.reset_topology()
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                                   "pp_degree": 1, "sep_degree": 1,
                                   "sharding_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        P.seed(0)
        model = fleet.distributed_model(nn.Linear(16, 4))
        opt = P.optimizer.SGD(parameters=model.parameters(),
                              learning_rate=0.05)
        step = model.build_train_step(opt, nn.MSELoss(), guard=True)
        step.attach_checkpoint_manager(mgr)
        return step

    obs.attach(crash_hook=False)
    metrics.reset()
    root = root or tempfile.mkdtemp(prefix="chaos_preempt_")
    mgr = CheckpointManager(root, keep_last_k=2)
    step = build_step(mgr)
    P.seed(seed + 1)
    x = P.randn([8, 16])
    y = P.randn([8, 4])

    guard = PreemptionGuard().install()
    step.attach_preemption_guard(guard)
    preempted = verified = None
    steps_before = 0
    try:
        for i in range(steps):
            if i == preempt_at:
                # a real signal, handled at the next safe point
                os.kill(os.getpid(), _signal.SIGTERM)
            float(step(x, y))
            steps_before += 1
    except TrainingPreempted as e:
        preempted = e
        if e.checkpoint_dir is not None:
            verified = verify_checkpoint(e.checkpoint_dir)
    finally:
        guard.uninstall()

    resumed_losses = []
    if preempted is not None and verified is not None:
        step2 = build_step(mgr)
        restored_step = step2.rollback()  # newest verified checkpoint
        for _ in range(steps - steps_before):
            resumed_losses.append(float(step2(x, y)))
    else:
        restored_step = None

    snap = metrics.snapshot()["counters"]
    obs.detach()
    report = {
        "scenario": "preemption",
        "steps": steps,
        "preempt_at": preempt_at,
        "steps_before_preemption": steps_before,
        "preempted": preempted is not None,
        "reason": getattr(preempted, "reason", None),
        "checkpoint_dir": getattr(preempted, "checkpoint_dir", None),
        "checkpoint_verified": verified is not None,
        "restored_step": restored_step,
        "resumed_steps": len(resumed_losses),
        "final_loss": resumed_losses[-1] if resumed_losses else None,
        "signals_counted": snap.get(
            "preemption.signals{signal=SIGTERM}", 0),
        "emergency_checkpoints": snap.get("preemption.checkpoints", 0),
        "recovered": (preempted is not None and verified is not None
                      and bool(resumed_losses)
                      and bool(np.isfinite(resumed_losses[-1]))),
    }
    return report


def _build_engine_model(seed=0):
    import paddle_tpu as P
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(seed)
    cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=96)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def run_engine_chaos(seed=0, n_seqs=8, new_tokens=10,
                     kv_precision=None):
    """Engine chaos: cancel/abandon sequences mid-decode, kill a client
    mid-stream, and shed past saturation.  `recovered` means: zero page
    leak after every scenario, survivors bit-identical to an
    uninterrupted run, the mid-stream kill actually cancelled its
    sequence, and the sheds are visible in the SLO report under known
    reason labels.

    ``kv_precision='int8'`` (ISSUE 12) reruns the whole scenario with
    the quantized page pool: the uninterrupted reference is then a
    quantized engine too, so "survivors bit-identical" asserts the
    quantized tier's run-to-run determinism under cancels/kills — the
    tier's documented contract (tokens within rtol of bf16, bit-stable
    per run)."""
    import http.client
    import threading
    import time
    import urllib.error

    import numpy as np

    from paddle_tpu import observability as obs
    from paddle_tpu.inference.engine import EngineConfig, InferenceEngine
    from paddle_tpu.inference.serving import InferenceClient, InferenceServer
    from paddle_tpu.observability import metrics

    obs.attach(crash_hook=False)
    metrics.reset()
    model = _build_engine_model(seed)
    rs = np.random.RandomState(seed)
    prompts = [rs.randint(0, 256, (3 + (i * 5) % 17,)).astype(np.int32)
               for i in range(n_seqs)]
    # prefix_cache off: this scenario's leak assertions are the PR 8
    # zero-pages-after-drain contract WITHOUT the cache layer (the
    # cache deliberately retains committed pages); --scenario prefix
    # asserts the cache-aware version
    ecfg = dict(page_size=8, max_slots=4, decode_chunk=2, max_seq_len=96,
                kv_precision=kv_precision, prefix_cache=False)

    # 1. uninterrupted reference run
    ref_engine = InferenceEngine(model, EngineConfig(**ecfg))
    refs = ref_engine.generate(prompts, max_new_tokens=new_tokens)
    ref_leak = ref_engine.pool.used_pages

    # 2. cancel/abandon mid-decode: same prompts, fresh engine; after a
    # few steps cancel three — two running, one (usually) still waiting
    eng = InferenceEngine(model, EngineConfig(**ecfg))
    handles = [eng.submit(p, max_new_tokens=new_tokens) for p in prompts]
    for _ in range(3):
        eng.step()
    cancel_ids = [handles[1].request_id, handles[2].request_id,
                  handles[n_seqs - 1].request_id]
    for rid in cancel_ids:
        eng.cancel(rid)
    idle = 0
    while any(not h.done.is_set() for h in handles) and idle < 2000:
        idle = idle if eng.step() else idle + 1
    survivors_ok = all(
        np.array_equal(h.result(timeout=1.0), refs[i])
        for i, h in enumerate(handles)
        if h.request_id not in cancel_ids)
    cancelled_ok = all(handles[i].cancelled or
                       handles[i].done.is_set()
                       for i in (1, 2, n_seqs - 1))
    cancel_leak = eng.pool.used_pages

    # 3. kill a client mid-stream over HTTP: the server must cancel the
    # sequence and reclaim its pages while a polite client completes
    srv_engine = InferenceEngine(model, EngineConfig(**ecfg))
    srv = InferenceServer(engine=srv_engine, request_timeout=60.0,
                          queue_depth=0).start()
    host, port = srv._httpd.server_address[:2]
    conn = http.client.HTTPConnection(host, port, timeout=30)
    body = json.dumps({"input_ids": [int(x) for x in prompts[0]],
                       "max_new_tokens": 80})
    # baseline BEFORE the kill: scenario 2's explicit cancels already
    # incremented the global counter, and the assertion below must see
    # a NEW cancellation, not theirs
    cancelled_before = metrics.snapshot()["counters"].get(
        "engine.sequences{event=cancelled}", 0)
    conn.request("POST", "/generate", body=body,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    first_line = resp.fp.readline()           # stream is live
    resp.close()                              # client dies mid-stream
    conn.close()
    # a well-behaved client rides alongside and must be unaffected
    cli = InferenceClient(srv.address, timeout=60.0, retries=0)
    polite = cli.generate(prompts[1], max_new_tokens=new_tokens)
    polite_ok = np.array_equal(polite["output_ids"], refs[1])
    # wait for the server to notice the dead socket and cancel
    deadline = time.time() + 30.0
    kill_cancelled = False
    while time.time() < deadline:
        snap = metrics.snapshot()["counters"]
        if snap.get("engine.sequences{event=cancelled}",
                    0) > cancelled_before and \
                srv_engine.pool.used_pages == 0:
            kill_cancelled = True
            break
        time.sleep(0.1)
    stream_leak = srv_engine.pool.used_pages

    # 4. shed past true saturation: more concurrent streams than
    # slots + queue — the excess must shed 429 and land in the SLO
    # report under its reason label
    results = []
    lock = threading.Lock()

    def one(i):
        c = InferenceClient(srv.address, timeout=60.0, retries=0)
        try:
            r = c.generate(prompts[i % len(prompts)],
                           max_new_tokens=new_tokens)
            row = ("ok", r["finish_reason"])
        except urllib.error.HTTPError as e:
            row = ("shed" if e.code in (429, 503) else "error", e.code)
        except Exception as e:  # noqa: BLE001 — report, don't crash
            row = ("error", type(e).__name__)
        with lock:
            results.append(row)

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    slo_report = srv.slo.report(publish_gauges=False)
    drained = srv.shutdown()
    final_leak = srv_engine.pool.used_pages
    snap = metrics.snapshot()["counters"]
    obs.detach()

    ok_n = sum(1 for r in results if r[0] == "ok")
    shed_n = sum(1 for r in results if r[0] == "shed")
    err_n = sum(1 for r in results if r[0] == "error")
    slo_ep = slo_report.get("endpoints", {}).get("generate", {})
    slo_shed_reasons = {
        k.split(":", 1)[1]: v
        for k, v in slo_ep.get("errors_by_reason", {}).items()
        if k.startswith("shed:")}
    report = {
        "scenario": "engine" if kv_precision is None
        else f"engine[kv={kv_precision}]",
        "kv_precision": kv_precision or "full",
        "sequences": n_seqs,
        "ref_page_leak": ref_leak,
        "survivors_bit_identical": bool(survivors_ok),
        "cancelled_resolved": bool(cancelled_ok),
        "cancel_page_leak": cancel_leak,
        "stream_kill_cancelled": bool(kill_cancelled),
        "stream_kill_first_line": bool(first_line),
        "stream_page_leak": stream_leak,
        "polite_client_ok": bool(polite_ok),
        "burst_ok": ok_n,
        "burst_shed": shed_n,
        "burst_errors": err_n,
        "slo_shed_reasons": slo_shed_reasons,
        "cancelled_counter": snap.get(
            "engine.sequences{event=cancelled}", 0),
        "drained": bool(drained),
        "final_page_leak": final_leak,
        "recovered": (
            ref_leak == 0 and cancel_leak == 0 and stream_leak == 0
            and final_leak == 0 and bool(survivors_ok)
            and bool(cancelled_ok) and bool(kill_cancelled)
            and bool(first_line) and bool(polite_ok)
            and err_n == 0 and ok_n > 0 and shed_n > 0
            and sum(slo_shed_reasons.values()) >= shed_n
            and all(k in ("queue_full", "queue_timeout", "deadline",
                                  "draining")
                    for k in slo_shed_reasons)),
    }
    return report


def run_prefix_chaos(seed=0, new_tokens=8):
    """Prefix-cache chaos (ISSUE 13): shared-prefix tenants on a TIGHT
    pool with cancels, a mid-stream client kill, and cache-pressure
    eviction — then a poisoned-fingerprint pass through a 2-replica
    router.  `recovered` asserts zero page AND refcount leak (pool
    EMPTY after drain + cache clear), survivors bit-identical to a
    cold-cache replay, real cache hits during the burst, pressure
    actually exercised (idle-prefix reclaim or recompute eviction),
    and that a wrong fingerprint never changes a single token."""
    import http.client
    import time

    import numpy as np

    from paddle_tpu import observability as obs
    from paddle_tpu.inference.engine import EngineConfig, InferenceEngine
    from paddle_tpu.inference.router import Router
    from paddle_tpu.inference.serving import InferenceServer
    from paddle_tpu.observability import metrics

    obs.attach(crash_hook=False)
    metrics.reset()
    model = _build_engine_model(seed)
    rs = np.random.RandomState(seed)
    # two tenants, 2-page (16-token) system prompts, unique suffixes
    sysp = [rs.randint(0, 256, (16,)).astype(np.int32)
            for _ in range(2)]
    prompts = [np.concatenate([
        sysp[i % 2],
        rs.randint(0, 256, (3 + i % 5,)).astype(np.int32)])
        for i in range(8)]
    base = dict(page_size=8, max_slots=4, decode_chunk=2,
                max_seq_len=96)

    # cold-cache reference: the SAME engine configuration with caching
    # disabled — the contract is "the cache may change WHEN tokens
    # appear, never WHICH"
    ref_eng = InferenceEngine(model, EngineConfig(
        **base, prefix_cache=False))
    refs = ref_eng.generate(prompts, max_new_tokens=new_tokens)
    ref_leak = ref_eng.pool.used_pages

    # 1. shared-prefix burst under pressure + cancels mid-decode
    eng = InferenceEngine(model, EngineConfig(**base, num_pages=15))
    handles = [eng.submit(p, max_new_tokens=new_tokens)
               for p in prompts]
    for _ in range(3):
        eng.step()
    cancel_ids = [handles[2].request_id, handles[5].request_id]
    for rid in cancel_ids:
        eng.cancel(rid)
    idle = 0
    while any(not h.done.is_set() for h in handles) and idle < 2000:
        idle = idle if eng.step() else idle + 1
    survivors_ok = all(
        np.array_equal(h.result(timeout=1.0), refs[i])
        for i, h in enumerate(handles)
        if h.request_id not in cancel_ids)
    cache_stats = eng.prefix_cache_stats()
    pool_stats = eng.pool.stats()
    # after drain every live page belongs to the cache alone (one ref
    # each); clearing it must empty the pool AND the refcount table
    no_live_refs = pool_stats["logical_pages"] == pool_stats["used"]
    eng.clear_prefix_cache()
    drain_leak = eng.pool.used_pages
    ref_leak_count = len(eng.pool.ref_counts())
    seq_evictions = metrics.snapshot()["counters"].get(
        "engine.sequences{event=evicted}", 0)
    pressure_ok = (cache_stats.get("evicted_pages", 0) > 0
                   or seq_evictions > 0)

    # 2. poisoned fingerprint through a 2-replica router + a client
    # killed mid-stream: the wrong header may cost cache locality,
    # never a token
    servers = []
    replicas = {}
    for i in range(2):
        e = InferenceEngine(model, EngineConfig(**base))
        s = InferenceServer(engine=e, request_timeout=60.0,
                            queue_depth=0).start()
        servers.append(s)
        replicas[f"r{i}"] = s.address
    router = Router(replicas=replicas, probe_interval=0.1,
                    request_timeout=60.0).start()
    rhost, rport = router._httpd.server_address[:2]
    poisoned_ok = True
    for i, p in enumerate(prompts[:4]):
        conn = http.client.HTTPConnection(rhost, rport, timeout=30)
        body = json.dumps({"input_ids": [int(x) for x in p],
                           "max_new_tokens": new_tokens})
        conn.request("POST", "/generate", body=body, headers={
            "Content-Type": "application/json",
            # the two system prompts alternate: each tenant's SECOND
            # request re-prefills its shared prefix from the cache, so
            # /debug/tenants must attribute the saved tokens to it
            "X-Tenant-Id": f"tenant-{i % 2}",
            # fingerprint of NOTHING this prompt shares: must route
            # somewhere and still stream the exact reference tokens
            "X-Prefix-Fingerprint": "feedfacefeedface"})
        resp = conn.getresponse()
        out = None
        for line in resp:
            line = line.strip()
            if not line:
                continue
            evt = json.loads(line)
            if evt.get("done"):
                out = evt.get("output_ids")
                break
        conn.close()
        if out is None or not np.array_equal(
                np.asarray(out, np.int32), refs[i]):
            poisoned_ok = False
    # per-tenant prefix-saved attribution over the LIVE fleet
    # (ISSUE 16): each tenant's shared 16-token (2-page) system prompt
    # is prefilled twice against ONE pinned replica (the router's
    # affinity/least-loaded choice between equally-idle replicas is
    # probe-timing dependent, and this gate is about metering, not
    # routing) — the second request must ride the radix cache, and the
    # router's /debug/tenants fleet merge must show BOTH tenants with
    # computed prefill AND nonzero prefill_saved_tokens, books
    # conserved
    import urllib.request as _urlreq

    from paddle_tpu.observability import tenant_ledger as _tl
    for i, p in enumerate(prompts[4:8]):
        conn = http.client.HTTPConnection(
            *servers[0]._httpd.server_address[:2], timeout=30)
        conn.request("POST", "/generate", body=json.dumps({
            "input_ids": [int(x) for x in p],
            "max_new_tokens": new_tokens}),
            headers={"Content-Type": "application/json",
                     "X-Tenant-Id": f"tenant-{i % 2}"})
        resp = conn.getresponse()
        for line in resp:
            line = line.strip()
            if line and json.loads(line).get("done"):
                break
        conn.close()
    with _urlreq.urlopen(router.address + "/debug/tenants",
                         timeout=10) as r:
        tenant_debug = json.loads(r.read())
    fleet_rows = (tenant_debug.get("fleet") or {}).get("tenants") or {}
    attribution_ok = all(
        fleet_rows.get(f"tenant-{i}", {}).get("prefill_tokens", 0) > 0
        and fleet_rows.get(f"tenant-{i}", {})
        .get("prefill_saved_tokens", 0) >= 16
        for i in range(2))
    tenant_conserves = not _tl.conservation_delta(
        tenant_debug.get("fleet") or {})
    # kill a client mid-stream through the router: the replica must
    # cancel the sequence and reclaim its (non-cache) pages
    cancelled_before = metrics.snapshot()["counters"].get(
        "engine.sequences{event=cancelled}", 0)
    conn = http.client.HTTPConnection(rhost, rport, timeout=30)
    conn.request("POST", "/generate", body=json.dumps({
        "input_ids": [int(x) for x in prompts[0]],
        # long enough to be mid-stream at the kill, small enough to
        # fit prompt+new under max_seq_len (an oversized request would
        # 400 at the door and nothing would ever need cancelling)
        "max_new_tokens": 60}),
        headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    first_line = resp.fp.readline()
    resp.close()
    conn.close()
    deadline = time.time() + 30.0
    kill_cancelled = False
    while time.time() < deadline:
        snap = metrics.snapshot()["counters"]
        if snap.get("engine.sequences{event=cancelled}",
                    0) > cancelled_before and all(
                s.engine.pool.stats()["logical_pages"]
                == s.engine.pool.stats()["used"] for s in servers):
            kill_cancelled = True
            break
        time.sleep(0.1)
    router.shutdown()
    replica_leaks = []
    for s in servers:
        s.shutdown()
        s.engine.clear_prefix_cache()
        replica_leaks.append(s.engine.pool.used_pages)
    obs.detach()

    report = {
        "scenario": "prefix",
        "sequences": len(prompts),
        "ref_page_leak": ref_leak,
        "survivors_bit_identical": bool(survivors_ok),
        "cache_hits": cache_stats.get("hits", 0),
        "cache_evicted_pages": cache_stats.get("evicted_pages", 0),
        "sequence_evictions": seq_evictions,
        "pressure_exercised": bool(pressure_ok),
        "no_live_refs_after_drain": bool(no_live_refs),
        "drain_page_leak": drain_leak,
        "refcount_leak": ref_leak_count,
        "poisoned_fingerprint_ok": bool(poisoned_ok),
        "stream_kill_first_line": bool(first_line),
        "stream_kill_cancelled": bool(kill_cancelled),
        "replica_page_leaks": replica_leaks,
        "tenant_attribution": {
            t: {f: row.get(f, 0) for f in ("prefill_tokens",
                                           "prefill_saved_tokens")}
            for t, row in fleet_rows.items()
            if t.startswith("tenant-")},
        "tenant_attribution_ok": bool(attribution_ok),
        "tenant_conserves": bool(tenant_conserves),
        "recovered": (
            ref_leak == 0 and bool(survivors_ok)
            and cache_stats.get("hits", 0) > 0 and bool(pressure_ok)
            and bool(no_live_refs) and drain_leak == 0
            and ref_leak_count == 0 and bool(poisoned_ok)
            and bool(first_line) and bool(kill_cancelled)
            and all(n == 0 for n in replica_leaks)
            and bool(attribution_ok) and bool(tenant_conserves)),
    }
    return report


def run_fleet_chaos(seed=0, n_replicas=3, n_predict=12, n_generate=9,
                    new_tokens=40, token_time=0.02, service_time=0.02):
    """Fleet chaos (ISSUE 9): mixed concurrent burst over a 3-replica
    fleet; kill -9 one replica and SIGTERM-drain another mid-burst.
    `recovered` means zero admitted-request failures, zero replayed
    stream tokens, every stream accounted, and full capacity restored
    (final burst all-ok) — with the router's failover/ejection story
    visible in the telemetry snapshot and the telemetry_agg rollup."""
    import glob as _glob
    import subprocess as _subprocess
    import tempfile as _tempfile
    import threading
    import time as _time
    import urllib.error

    from paddle_tpu import observability as obs
    from paddle_tpu.inference.fleet import ReplicaFleet, toy_token
    from paddle_tpu.inference.serving import (
        InferenceClient, StreamInterrupted,
    )
    from paddle_tpu.observability import metrics
    from paddle_tpu.observability.export import TelemetryExporter

    obs.attach(crash_hook=False)
    metrics.reset()
    obs.attach(crash_hook=False)  # re-declare the schema post-reset
    tel_dir = _tempfile.mkdtemp(prefix="chaos_fleet_tel_")
    # fast exporter dumps + sampler frames (ISSUE 15): the continuity
    # gate below asserts the aggregated fleet timeseries has no gap
    # longer than 2 sampling intervals for surviving replicas — a
    # replica kill must not blind the telemetry plane of the others.
    # Via replica_env (not os.environ): no process-global mutation to
    # restore, and RELAUNCHED replicas inherit the fast intervals too
    ts_interval = 0.4
    fleet = ReplicaFleet(
        num_replicas=n_replicas, kind="toy", token_time=token_time,
        service_time=service_time, launch_timeout=60,
        telemetry_dir=tel_dir,
        replica_env={"PADDLE_TPU_TELEMETRY_INTERVAL": "0.5",
                     "PADDLE_TPU_TIMESERIES_INTERVAL_S":
                         str(ts_interval)})
    fleet.start()
    results = []
    lock = threading.Lock()
    rs = np.random.RandomState(seed)
    prompts = [rs.randint(0, 200, (3 + i % 5,)).tolist()
               for i in range(n_generate)]

    def one_predict(i):
        # every client carries a tenant identity (ISSUE 16): the
        # client-side ok counts per tenant reconcile against the
        # router's ledger below
        tenant = f"tenant-{i % 3}"
        cli = InferenceClient(fleet.router.address, timeout=30,
                              retries=1, tenant_id=tenant)
        x = np.full((2, 2), float(i), np.float32)
        try:
            out = cli.predict(x=x)
            ok = bool(np.array_equal(out["y"], x))
            row = ("predict", "ok" if ok else "corrupt", None, tenant)
        except urllib.error.HTTPError as e:
            row = ("predict",
                   "shed" if e.code in (429, 503) else "error",
                   e.headers.get("Retry-After"), tenant)
        except Exception as e:  # noqa: BLE001 — report, don't crash
            row = ("predict", "error", type(e).__name__, tenant)
        with lock:
            results.append(row)

    def one_generate(i):
        tenant = f"tenant-{i % 3}"
        cli = InferenceClient(fleet.router.address, timeout=30,
                              retries=1, tenant_id=tenant)
        prompt = prompts[i]
        expected = [toy_token(prompt, k) for k in range(new_tokens)]
        try:
            r = cli.generate(prompt, max_new_tokens=new_tokens)
            exact = r["tokens"] == expected
            row = ("generate", "ok" if exact else "replayed", None,
                   tenant)
        except StreamInterrupted as e:
            # the clean mid-stream cut: a strict prefix, resumable
            prefix_ok = (e.tokens == expected[:len(e.tokens)]
                         and list(e.output_ids)
                         == list(prompt) + e.tokens)
            row = ("generate",
                   "interrupted" if prefix_ok else "replayed",
                   len(e.tokens), tenant)
        except urllib.error.HTTPError as e:
            row = ("generate",
                   "shed" if e.code in (429, 503) else "error",
                   e.code, tenant)
        except Exception as e:  # noqa: BLE001 — report, don't crash
            row = ("generate", "error", type(e).__name__, tenant)
        with lock:
            results.append(row)

    threads = [threading.Thread(target=one_predict, args=(i,))
               for i in range(n_predict)]
    threads += [threading.Thread(target=one_generate, args=(i,))
                for i in range(n_generate)]
    rs.shuffle(threads)
    for i, t in enumerate(threads):
        t.start()
        _time.sleep(0.01)
        if i == len(threads) // 3:
            fleet.kill_replica(0)          # kill -9 mid-burst
        if i == len(threads) // 2:
            fleet.drain_replica(1)         # SIGTERM (drain-first)
    for t in threads:
        t.join(timeout=60)
    # recovery: the supervisor relaunches both; full capacity returns
    recovered_capacity = fleet.wait_ready(n=n_replicas, timeout=30)
    final = []

    def final_one(i):
        cli = InferenceClient(fleet.router.address, timeout=30,
                              retries=1)
        prompt = prompts[i % len(prompts)]
        try:
            r = cli.generate(prompt, max_new_tokens=5)
            final.append(r["tokens"]
                         == [toy_token(prompt, k) for k in range(5)])
        except Exception:  # noqa: BLE001 — report, don't crash
            final.append(False)

    fthreads = [threading.Thread(target=final_one, args=(i,))
                for i in range(n_replicas * 2)]
    for t in fthreads:
        t.start()
    for t in fthreads:
        t.join(timeout=30)
    # the router process's own dump joins the replicas' in tel_dir
    TelemetryExporter(outdir=tel_dir, run_id="router").dump_once(
        reason="chaos_final")
    # the router's edge ledger (ISSUE 16), read BEFORE the adversarial
    # sweep below evicts the burst tenants from its top-K table
    router_ledger = fleet.router.tenant_ledger
    router_tenants_snap = (router_ledger.snapshot()
                           if router_ledger is not None else {})
    # bounded cardinality under adversarial identity churn: 10k
    # distinct tenant ids against the LIVE router ledger must stay at
    # O(K) entries with the books still balancing
    sweep_n = 10_000
    sweep_snap = {}
    if router_ledger is not None:
        for i in range(sweep_n):
            router_ledger.record_request(f"sweep-{i}", "ok")
        sweep_snap = router_ledger.snapshot()
    snap = metrics.snapshot()
    fleet.stop()
    obs.detach()

    counters = snap["counters"]
    gauges = snap["gauges"]
    by = {}
    for kind, status, _extra, _tenant in results:
        by.setdefault(kind, {}).setdefault(status, 0)
        by[kind][status] += 1
    pred = by.get("predict", {})
    gen = by.get("generate", {})
    errors = (pred.get("error", 0) + pred.get("corrupt", 0)
              + gen.get("error", 0) + gen.get("replayed", 0))
    accounted = sum(gen.values()) == n_generate and \
        sum(pred.values()) == n_predict

    # per-replica rollup through tools/telemetry_agg.py (ISSUE 9
    # acceptance: router counters/gauges merged across the fleet dumps)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import telemetry_agg
    finally:
        sys.path.pop(0)
    roll = telemetry_agg.rollup(telemetry_agg.load_dumps(tel_dir))
    roll_has_router = any(k.startswith("router.replicas")
                          for k in roll.get("gauges", {})) and \
        "router.ejections" in roll.get("counters", {})
    # ISSUE 15: the per-token latency histogram made it through the
    # fleet rollup with percentiles
    itl_roll = roll.get("histograms", {}).get(
        "serving.itl_ms{endpoint=generate}") or {}
    itl_in_rollup = itl_roll.get("count", 0) > 0 and "p50" in itl_roll
    # telemetry CONTINUITY under the replica kill (ISSUE 15 satellite):
    # every replica process's aggregated timeseries must be internally
    # gap-free (no gap > 2 sampling intervals) — the kill ends the
    # victim's series but must not hole anyone's
    gap_bound = 2.0 * ts_interval + 0.05  # scheduling jitter slack
    ts_procs = roll.get("timeseries", {}).get("per_process", {})
    replica_series = {ident: series for ident, series in ts_procs.items()
                      if ":r" in ident and series}
    continuity = {}
    for ident, series in replica_series.items():
        walls = sorted(next(iter(series.values()))["wall"])
        worst = max((b - a for a, b in zip(walls, walls[1:])),
                    default=0.0)
        continuity[ident] = {"frames": len(walls),
                             "worst_gap_s": round(worst, 3)}
    survivors = [ident for ident, c in continuity.items()
                 if c["frames"] >= 3]
    continuity_ok = bool(survivors) and all(
        continuity[ident]["worst_gap_s"] <= gap_bound
        for ident in survivors)
    # the killed replica's dump stream still validates schema-clean
    # through tools/analyze_chip_log.py (exit 0 = no schema errors)
    analyze = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "analyze_chip_log.py")
    dumps_clean = True
    for path in sorted(_glob.glob(os.path.join(tel_dir,
                                               "telemetry_*.jsonl"))):
        rc = _subprocess.run(
            [sys.executable, analyze, path],
            stdout=_subprocess.DEVNULL,
            stderr=_subprocess.DEVNULL).returncode
        if rc != 0:
            dumps_clean = False

    # tenant metering gates (ISSUE 16), under the kill/drain chaos:
    #   a) every replica book conserves internally (Σ tracked + other
    #      == totals) AND its decode total equals the engine.tokens
    #      counter read inside the same snapshot — the in-lock pairing
    #      means a kill mid-stream can never skew a dump;
    #   b) the telemetry_agg fleet merge of the replica books conserves
    #      too (Σ tenant decode tokens + other == engine.tokens
    #      fleet-wide);
    #   c) the client-side ok counts per tenant equal the router
    #      ledger's ok books exactly (failovers/retries collapse to the
    #      one final outcome on both sides);
    #   d) the 10k-distinct-id sweep above stayed within K entries.
    _tl = obs.tenant_ledger
    roll_tenants = roll.get("tenants") or {}
    replica_books = {ident: s
                     for ident, s in (roll_tenants.get("per_process")
                                      or {}).items() if ":r" in ident}
    tenant_replicas_conserve = bool(replica_books) and all(
        not _tl.conservation_delta(s)
        and s.get("metrics_engine_tokens")
        == s.get("totals", {}).get("decode_tokens")
        for s in replica_books.values())
    fleet_book = roll_tenants.get("fleet") or {}
    tenant_fleet_conserves = bool(fleet_book) \
        and not _tl.conservation_delta(fleet_book) \
        and fleet_book.get("metrics_engine_tokens") \
        == fleet_book.get("totals", {}).get("decode_tokens")
    client_ok = {}
    for _kind, status, _extra, tenant in results:
        if status == "ok":
            client_ok[tenant] = client_ok.get(tenant, 0) + 1
    router_ok = {
        t: e["requests"]["ok"]
        for t, e in (router_tenants_snap.get("tenants") or {}).items()
        if t.startswith("tenant-") and "ok" in (e.get("requests") or {})}
    tenant_client_match = client_ok == router_ok
    tenant_sweep_bounded = bool(sweep_snap) \
        and sweep_snap.get("tracked", 1 << 30) <= sweep_snap.get("k", 0) \
        and sweep_snap.get("distinct_seen", 0) >= sweep_n \
        and not _tl.conservation_delta(sweep_snap)

    report = {
        "scenario": "fleet",
        "replicas": n_replicas,
        "predict": pred,
        "generate": gen,
        "admitted_failures": errors,
        "streams_accounted": accounted,
        "ejections": counters.get("router.ejections", 0),
        "failovers": counters.get("router.failovers", 0),
        "readmissions": counters.get("router.readmissions", 0),
        "router_gauges": {k: v for k, v in gauges.items()
                          if k.startswith("router.replicas")},
        "recovered_capacity": bool(recovered_capacity),
        "final_burst_ok": sum(bool(x) for x in final),
        "rollup_processes": roll.get("processes", []),
        "rollup_has_router": bool(roll_has_router),
        "itl_in_rollup": bool(itl_in_rollup),
        "timeseries_continuity": continuity,
        "continuity_ok": bool(continuity_ok),
        "dumps_schema_clean": bool(dumps_clean),
        "tenant_replicas_conserve": bool(tenant_replicas_conserve),
        "tenant_fleet_conserves": bool(tenant_fleet_conserves),
        "tenant_client_ok": client_ok,
        "tenant_router_ok": router_ok,
        "tenant_client_match": bool(tenant_client_match),
        "tenant_sweep": {"distinct": sweep_snap.get("distinct_seen"),
                         "tracked": sweep_snap.get("tracked"),
                         "k": sweep_snap.get("k")},
        "tenant_sweep_bounded": bool(tenant_sweep_bounded),
        "fleet_events": [e["kind"] for e in fleet.events],
        "recovered": (
            errors == 0 and accounted
            and pred.get("ok", 0) > 0 and gen.get("ok", 0) > 0
            and counters.get("router.ejections", 0) >= 1
            and counters.get("router.readmissions", 0) >= 2
            and bool(recovered_capacity)
            and len(final) == n_replicas * 2 and all(final)
            and gauges.get("router.replicas{state=up}") == n_replicas
            and bool(roll_has_router)
            and bool(itl_in_rollup) and bool(continuity_ok)
            and bool(dumps_clean)
            and bool(tenant_replicas_conserve)
            and bool(tenant_fleet_conserves)
            and bool(tenant_client_match)
            and bool(tenant_sweep_bounded)
            # the drain-first ordering actually held for the SIGTERM
            and fleet.events.index(
                next(e for e in fleet.events
                     if e["kind"] == "drain_mark"))
            < fleet.events.index(
                next(e for e in fleet.events
                     if e["kind"] == "drain_sigterm"))),
    }
    return report


def run_resume_chaos(seed=0, n_replicas=3, n_generate=12,
                     new_tokens=72, max_waves=3):
    """Mid-stream failover chaos (ISSUE 20): kill -9 one replica of a
    3-replica GPT fleet mid-burst.  `recovered` means ZERO interrupted
    streams and zero replayed tokens — every stream, including the
    router-resumed ones, is bit-exact with a local same-seed reference
    engine (the greedy determinism contract end to end) — with at
    least one resume established (`router.stream_resumes{outcome=ok}`)
    and none diverged, the resumed legs riding the survivors' radix
    prefix cache (`serving.resume_prefill{cache=hit|partial}`), the
    resume-gap histogram populated, and every replica book + the fleet
    merge still conserving decode tokens exactly once across the
    broken-and-resumed streams.  Because a kill may land between
    streams (nothing in flight → plain zero-token failover, nothing to
    resume), the burst runs in up to `max_waves` waves, each killing a
    different live replica, until a resume is observed."""
    import glob as _glob
    import tempfile as _tempfile
    import threading
    import time as _time
    import urllib.error

    from paddle_tpu import observability as obs
    from paddle_tpu.inference.fleet import ReplicaFleet, _build_gpt_engine
    from paddle_tpu.inference.serving import (
        InferenceClient, StreamInterrupted,
    )
    from paddle_tpu.observability import metrics
    from paddle_tpu.observability.export import TelemetryExporter

    obs.attach(crash_hook=False)
    metrics.reset()
    obs.attach(crash_hook=False)  # re-declare the schema post-reset
    tel_dir = _tempfile.mkdtemp(prefix="chaos_resume_tel_")
    fleet = ReplicaFleet(
        num_replicas=n_replicas, kind="gpt", max_slots=4,
        request_timeout=60.0, launch_timeout=180,
        telemetry_dir=tel_dir,
        replica_env={"PADDLE_TPU_TELEMETRY_INTERVAL": "0.5"})
    fleet.start()
    rs = np.random.RandomState(seed)
    # every prompt opens with one shared 16-token (2-page) system
    # prefix: the resume leg's tail-prefill re-walks it through the
    # survivor's radix cache (warmed below), so resumes land hit/partial
    sysp = rs.randint(0, 250, (16,)).tolist()

    # the greedy-determinism oracle: the SAME seeded model the replicas
    # build — what an uninterrupted stream would have said, bit-exact
    ref_eng = _build_gpt_engine(seed=0)

    def expected(prompt, n):
        out = ref_eng.generate([np.asarray(prompt, np.int32)],
                               max_new_tokens=n)[0]
        return [int(t) for t in np.asarray(out)[len(prompt):]]

    # warm EVERY replica's radix cache with the shared prefix directly
    # (bypassing router affinity, which would pin one replica): any
    # survivor a stream resumes onto already holds the prefix pages
    for view in fleet.router.replica_views():
        InferenceClient(view["address"], timeout=60, retries=1,
                        tenant_id="warm").generate(
            sysp + [3, 1], max_new_tokens=2)

    results = []
    lock = threading.Lock()
    delivered_counts = [0] * n_generate  # tokens seen at client edge

    def _note_token(i):
        with lock:
            delivered_counts[i] += 1

    def one_generate(i, prompt, exp):
        tenant = f"tenant-{i % 3}"
        cli = InferenceClient(fleet.router.address, timeout=60,
                              retries=1, tenant_id=tenant)
        try:
            r = cli.generate(prompt, max_new_tokens=new_tokens,
                             on_token=lambda _t: _note_token(i))
            row = ("ok" if r["tokens"] == exp else "replayed",
                   int(r.get("resumed", 0) or 0), tenant)
        except StreamInterrupted as e:
            prefix_ok = (e.tokens == exp[:len(e.tokens)]
                         and list(e.output_ids)
                         == list(prompt) + list(e.tokens))
            row = ("interrupted" if prefix_ok else "replayed",
                   0, tenant)
        except urllib.error.HTTPError as e:
            row = ("shed" if e.code in (429, 503) else "error",
                   0, tenant)
        except Exception as e:  # noqa: BLE001 — report, don't crash
            row = (f"error:{type(e).__name__}", 0, tenant)
        with lock:
            results.append(row)

    def busiest_rank(fallback):
        # the kill must land on a replica with streams IN FLIGHT or
        # there is nothing to resume — target the router's live
        # inflight books (ids are "r<rank>", stable across relaunches)
        best, best_n = fallback, -1
        for v in fleet.router.replica_views():
            n = sum((v.get("inflight") or {}).values())
            if n > best_n:
                best, best_n = int(v["id"][1:]), n
        return best

    waves_run = 0
    for wave in range(max_waves):
        waves_run += 1
        rr = np.random.RandomState(seed + 101 * wave)
        prompts = [sysp + rr.randint(0, 250, (3 + i % 5,)).tolist()
                   for i in range(n_generate)]
        exps = [expected(p, new_tokens) for p in prompts]
        with lock:
            delivered_counts[:] = [0] * n_generate
        threads = [threading.Thread(target=one_generate,
                                    args=(i, prompts[i], exps[i]))
                   for i in range(n_generate)]
        for t in threads:
            t.start()
            _time.sleep(0.02)
        # the kill must land MID-stream (a zero-delivered break takes
        # the plain failover path and proves nothing about resume), so
        # wait until the burst is OBSERVABLY flowing — enough streams
        # past their second token that every replica is mid-delivery —
        # rather than guessing a wall-clock offset that machine load
        # would invalidate; then kill -9 the most-loaded replica
        flow_deadline = _time.monotonic() + 60.0
        while _time.monotonic() < flow_deadline:
            with lock:
                flowing = sum(1 for c in delivered_counts if c >= 2)
            if flowing >= 2 * n_replicas:
                break
            _time.sleep(0.02)
        fleet.kill_replica(busiest_rank(wave % n_replicas))
        for t in threads:
            t.join(timeout=120)
        # supervisor respawn: full capacity before the next wave
        fleet.wait_ready(n=n_replicas, timeout=120)
        if metrics.snapshot()["counters"].get(
                "router.stream_resumes{outcome=ok}", 0) >= 1:
            break

    # the router process's own dump (stream_resumes counters + the
    # resume-gap histogram live HERE) joins the replica dumps
    TelemetryExporter(outdir=tel_dir, run_id="router").dump_once(
        reason="chaos_final")
    snap = metrics.snapshot()
    fleet.stop()
    obs.detach()

    counters = snap["counters"]
    by = {}
    resumed_streams = 0
    for status, resumed, _tenant in results:
        by.setdefault(status, 0)
        by[status] += 1
        if resumed:
            resumed_streams += 1
    launched = waves_run * n_generate
    resumes_ok = counters.get("router.stream_resumes{outcome=ok}", 0)
    resumes_div = counters.get(
        "router.stream_resumes{outcome=diverged}", 0)
    gap_hist = snap["histograms"].get("router.resume_gap_ms", {})

    # fleet rollup: resume counters/hist must survive telemetry_agg,
    # and every replica book + the merge still conserves exactly-once
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import telemetry_agg
    finally:
        sys.path.pop(0)
    roll = telemetry_agg.rollup(telemetry_agg.load_dumps(tel_dir))
    roll_c = roll.get("counters", {})
    roll_resumes_ok = roll_c.get(
        "router.stream_resumes{outcome=ok}", 0)
    # serving.resume_prefill lives in the REPLICA processes — it only
    # reaches us through their telemetry dumps, never the local snap
    roll_prefill_warm = sum(
        roll_c.get(f"serving.resume_prefill{{cache={c}}}", 0)
        for c in ("hit", "partial"))
    roll_gap = roll.get("histograms", {}).get(
        "router.resume_gap_ms") or {}
    _tl = obs.tenant_ledger
    roll_tenants = roll.get("tenants") or {}
    replica_books = {ident: s
                     for ident, s in (roll_tenants.get("per_process")
                                      or {}).items() if ":r" in ident}
    books_conserve = bool(replica_books) and all(
        not _tl.conservation_delta(s)
        and s.get("metrics_engine_tokens")
        == s.get("totals", {}).get("decode_tokens")
        for s in replica_books.values())
    fleet_book = roll_tenants.get("fleet") or {}
    fleet_conserves = bool(fleet_book) \
        and not _tl.conservation_delta(fleet_book) \
        and fleet_book.get("metrics_engine_tokens") \
        == fleet_book.get("totals", {}).get("decode_tokens")

    report = {
        "scenario": "resume",
        "replicas": n_replicas,
        "waves": waves_run,
        "streams": launched,
        "by_status": by,
        "resumed_streams_client": resumed_streams,
        "stream_resumes_ok": resumes_ok,
        "stream_resumes_diverged": resumes_div,
        "resume_gap_count": gap_hist.get("count", 0),
        "rollup_resumes_ok": roll_resumes_ok,
        "rollup_prefill_warm": roll_prefill_warm,
        "rollup_gap_count": roll_gap.get("count", 0),
        "books_conserve": bool(books_conserve),
        "fleet_conserves": bool(fleet_conserves),
        "recovered": (
            # the tentpole bar: replica death invisible — every stream
            # finished ok and bit-exact, NONE interrupted or replayed
            by.get("ok", 0) == launched
            and len(results) == launched
            and resumes_ok >= 1 and resumes_div == 0
            and resumed_streams >= 1
            and gap_hist.get("count", 0) >= 1
            and roll_resumes_ok >= 1
            and roll_prefill_warm >= 1
            and roll_gap.get("count", 0) >= 1
            and bool(books_conserve) and bool(fleet_conserves)),
    }
    return report


def run_surge_chaos(seed=0, base_rps=4.0, surge_mult=10.0, warm_s=3.0,
                    surge_s=10.0, cool_s=6.0, max_replicas=3,
                    p99_bound_ms=15000.0):
    """Surge chaos (ISSUE 14): a 10× OPEN-LOOP traffic step against an
    autoscaled 1-replica toy fleet.  The loadgen workload is
    shared-prefix tenants, mixed predict/generate, with misbehaving
    clients (mid-stream disconnects, Retry-After ignorers, oversized
    bodies) riding along.  `recovered` means: ZERO admitted-request
    failures (sheds are polite, never failures; every delivered stream
    an exact prefix of the deterministic toy sequence — one replayed
    token anywhere fails the run), bounded ok-request p99, ≥1 scale-up
    observed mid-surge, and the ramp-down drains replicas strictly
    through the zero-loss protocol (drain_mark before drain_sigterm,
    exit 0) back to min size — with the autoscaler/capacity telemetry
    visible on the router's /debug/telemetry plane.  The lifecycle
    gate (ISSUE 17) additionally requires every mid-surge scale-up to
    leave a complete monotone spawn-phase record and every scale-up
    decision event to carry `observed_spawn_ms`."""
    import time as _time
    import urllib.request as _urlreq

    from paddle_tpu import observability as obs
    from paddle_tpu.inference.autoscaler import Autoscaler
    from paddle_tpu.inference.fleet import ReplicaFleet, toy_token
    from paddle_tpu.observability import metrics

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import loadgen
    finally:
        sys.path.pop(0)

    obs.attach(crash_hook=False)
    metrics.reset()
    obs.attach(crash_hook=False)  # re-declare the schema post-reset
    # a short SLO window so the scenario's ramp-down is observable in
    # seconds: with the 5-minute default the surge's (correct, polite)
    # sheds would keep the burn rate above the scale-down bar long
    # after the traffic left — production wants exactly that caution,
    # a chaos run wants to SEE the drain
    prev_window = os.environ.get("PADDLE_TPU_SLO_WINDOW")
    os.environ["PADDLE_TPU_SLO_WINDOW"] = "10.0"
    fleet = scaler = None
    try:
        fleet = ReplicaFleet(num_replicas=1, kind="toy",
                             token_time=0.02, service_time=0.02,
                             max_slots=4, launch_timeout=60,
                             monitor_interval=0.1)
        fleet.start()
        # occ_up raised 0.7 → 0.9 vs PR 14: the PREDICTIVE signal
        # (ISSUE 15 — sustained positive occupancy/queue derivative
        # from the timeseries plane) is now the intended early
        # trigger; the threshold rules stay as the safety net.  The
        # gate below asserts the first scale-up is predictive and
        # strictly precedes the burn-threshold crossing in the event
        # log — the "earlier than burn-only" proof inside ONE run.
        scaler = Autoscaler(
            fleet, min_replicas=1, max_replicas=max_replicas,
            burn_up=2.0, occ_up=0.9, occ_down=0.15,
            up_sustain=2, down_sustain=8, cooldown_s=2.0,
            interval=0.2, drain_grace=5.0,
            deriv_up=0.08, queue_deriv_up=1.5,
            # floor 0.1 (not the 0.3 default): the predictive streak
            # must start building the moment the surge slope appears,
            # ticks before occupancy can reach the 0.9 threshold —
            # otherwise a steep leap could let the threshold rule win
            deriv_window_s=3.0, deriv_floor=0.1)
        scaler.start()
        workload = loadgen.SharedPrefixWorkload(
            seed=seed, tenants=3, system_prompt_tokens=16,
            suffix_tokens=(3, 6), generate_frac=0.7,
            max_new_tokens=20, misbehave_disconnect=0.04,
            misbehave_ignore_retry=0.04, misbehave_oversize=0.02)
        phases = loadgen.surge_phases(
            base_rps=base_rps, surge_mult=surge_mult, warm_s=warm_s,
            surge_s=surge_s, cool_s=cool_s)
        runner = loadgen.OpenLoopRunner(
            fleet.router.address, workload, phases, seed=seed,
            expected_token=toy_token, timeout=30.0, max_retries=2)
        load_report = runner.run()
        # ramp-down: idle traffic → the autoscaler must drain back to
        # min size through the zero-loss protocol, on its own
        deadline = _time.monotonic() + 45.0
        while _time.monotonic() < deadline and \
                fleet.replica_count() > 1:
            _time.sleep(0.2)
        returned_to_min = fleet.replica_count() == 1
        # the telemetry plane (ISSUE 14 satellite): autoscaler +
        # capacity gauges must be visible on /debug/telemetry
        with _urlreq.urlopen(fleet.router.address + "/debug/telemetry",
                             timeout=10) as r:
            debug_snap = json.loads(r.read())
        # tenant metering over the LIVE fleet (ISSUE 16): the router's
        # /debug/tenants merges the surviving replicas' books
        with _urlreq.urlopen(fleet.router.address + "/debug/tenants",
                             timeout=10) as r:
            tenant_debug = json.loads(r.read())
        # replica lifecycle over the fleet (ISSUE 17): the joined
        # spawn records, fetched AFTER the ramp-down removed the
        # surge replicas — which is exactly what the records being
        # DURABLE (attached at first probe-up) must survive
        with _urlreq.urlopen(fleet.router.address + "/debug/lifecycle",
                             timeout=10) as r:
            lifecycle_debug = json.loads(r.read())
        # bounded cardinality under identity churn: 10k distinct ids
        # against the live router ledger (AFTER the debug snapshot —
        # the sweep evicts the real tenants from the top-K table)
        sweep_n = 10_000
        sweep_snap = {}
        if fleet.router.tenant_ledger is not None:
            for i in range(sweep_n):
                fleet.router.tenant_ledger.record_request(
                    f"sweep-{i}", "ok")
            sweep_snap = fleet.router.tenant_ledger.snapshot()
        scaler.stop()
        snap = metrics.snapshot()
    finally:
        if prev_window is None:
            os.environ.pop("PADDLE_TPU_SLO_WINDOW", None)
        else:
            os.environ["PADDLE_TPU_SLO_WINDOW"] = prev_window
        if scaler is not None:
            scaler.stop()
        if fleet is not None:
            fleet.stop()
        obs.detach()

    s = load_report.summary()
    counters, gauges = snap["counters"], snap["gauges"]
    scale_ups = [e for e in scaler.events
                 if e["kind"] in ("scale_up", "scale_up_predictive")]
    scale_downs = [e for e in scaler.events
                   if e["kind"] == "scale_down"]
    # the leading-vs-lagging proof (ISSUE 15): the FIRST scale-up must
    # land with burn still under the bar AND strictly precede the
    # burn-threshold crossing in the ordered event log (if burn never
    # crossed, it beat the burn-only baseline by definition — that
    # baseline would not have scaled at all), and the predictive
    # signal must have actually fired this run (≥1 up_predictive).
    # The first up is normally the predictive one (reported below),
    # but a steep-enough occupancy leap can legitimately let the
    # threshold rule win the same tick — the gate pins the ordering
    # CLAIM, not which growth rule's label won a tie.
    event_kinds = [e["kind"] for e in scaler.events]
    first_up_idx = next(
        (i for i, k in enumerate(event_kinds)
         if k in ("scale_up", "scale_up_predictive")), None)
    burn_cross_idx = next(
        (i for i, k in enumerate(event_kinds)
         if k == "burn_threshold_crossed"), None)
    predictive_first = (
        first_up_idx is not None
        and scaler.events[first_up_idx].get("burn_rate", 0.0)
        < scaler.burn_up
        and (burn_cross_idx is None or first_up_idx < burn_cross_idx))
    # every removed rank drained in the load-bearing order: rotation
    # out (drain_mark) strictly before SIGTERM, exit 0 — the zero-loss
    # contract the autoscaler must never violate
    kinds = [(e["kind"], e.get("rank")) for e in fleet.events]
    removed = [e for e in fleet.events if e["kind"] == "replica_removed"]
    drain_order_ok = bool(removed) and all(
        ("drain_mark", e["rank"]) in kinds
        and ("drain_sigterm", e["rank"]) in kinds
        and kinds.index(("drain_mark", e["rank"]))
        < kinds.index(("drain_sigterm", e["rank"]))
        and e.get("rc") == 0
        for e in removed)
    # lifecycle gate (ISSUE 17): every mid-surge scale-up must have
    # yielded a COMPLETE, MONOTONE joined phase record (no phase
    # missing, no negative duration — validate_record pins both), and
    # every scale-up decision event must carry the observed
    # spawn->routable estimate (r0's launch completed before the
    # scaler's first tick, so even the first scale-up has a sample)
    lc_records = {r.get("rank"): r for r in
                  (lifecycle_debug.get("fleet", {}).get("records")
                   or []) if isinstance(r, dict)}
    lc_problems = {}
    for e in scale_ups:
        rec = lc_records.get(e["rank"])
        probs = (obs.lifecycle.validate_record(rec)
                 if rec is not None else ["record missing"])
        if probs:
            lc_problems[e["rank"]] = probs
    lifecycle_ok = bool(scale_ups) and not lc_problems
    observed_spawn_logged = bool(scale_ups) and all(
        e.get("observed_spawn_ms") is not None for e in scale_ups)
    gen_p99 = (s["latency_ms"].get("generate") or {}).get("p99")
    debug_gauges = debug_snap.get("metrics", {}).get("gauges", {})
    telemetry_ok = (
        "autoscaler.replicas{state=actual}" in debug_gauges
        and "router.capacity{endpoint=generate}" in debug_gauges
        and "slo" in debug_snap
        # the time dimension is live on the router's debug plane
        and debug_snap.get("timeseries", {}).get("samples", 0) > 0)
    # cross-check surface (ISSUE 15 satellite): the client-side ITL
    # percentiles next to the surge phase breakdown — the server-side
    # serving.itl_ms histograms live in the replicas' own /metrics
    client_itl = s.get("itl_ms")
    phases_ok = all(ph in s.get("phases", {})
                    for ph in ("warm", "surge", "cool"))
    # tenant metering gates (ISSUE 16): the router's edge book must
    # agree with the loadgen's own per-tenant breakdown EXACTLY on ok
    # counts (retried sheds bill per hop attempt, but each client row
    # that ends ok is exactly one router ok); the /debug/tenants fleet
    # merge and the router book must both conserve; and the 10k-id
    # sweep must have stayed within K entries
    _tl = obs.tenant_ledger
    expected_tenants = {loadgen.tenant_name(i) for i in range(3)}
    router_book = tenant_debug.get("router") or {}
    fleet_book = tenant_debug.get("fleet") or {}
    router_rows = router_book.get("tenants") or {}
    tenants_tracked = expected_tenants.issubset(router_rows)
    client_ok = {t: st["status"].get("ok", 0)
                 for t, st in (s.get("tenants") or {}).items()
                 if st["status"].get("ok", 0)}
    router_ok = {t: e["requests"]["ok"]
                 for t, e in router_rows.items()
                 if t in expected_tenants
                 and "ok" in (e.get("requests") or {})}
    tenant_client_match = client_ok == router_ok
    tenant_conserves = (not _tl.conservation_delta(router_book)
                        and not _tl.conservation_delta(fleet_book)
                        and fleet_book.get("totals", {})
                        .get("decode_tokens", 0) > 0)
    tenant_sweep_bounded = bool(sweep_snap) \
        and sweep_snap.get("tracked", 1 << 30) <= sweep_snap.get("k", 0) \
        and sweep_snap.get("distinct_seen", 0) >= sweep_n \
        and not _tl.conservation_delta(sweep_snap)
    report = {
        "scenario": "surge",
        "phases": [f"{p.name}:{p.duration_s}s@{p.rps}rps"
                   for p in phases],
        "requests": s["requests"],
        "ok": s["ok"],
        "shed": s["shed"],
        "interrupted": s["interrupted"],
        "abandoned": s["abandoned"],
        "client_errors": s["client_errors"],
        "replayed": s["replayed"],
        "admitted_failures": s["admitted_failures"],
        "failure_detail": s["failure_detail"],
        "tokens": s["tokens"],
        "latency_ms": s["latency_ms"],
        "scale_ups": len(scale_ups),
        "scale_downs": len(scale_downs),
        "peak_replicas": scaler.peak_replicas,
        "returned_to_min": bool(returned_to_min),
        "drain_order_ok": bool(drain_order_ok),
        "decisions": {a: counters.get(
            f"autoscaler.decisions{{action={a}}}", 0)
            for a in ("up", "up_predictive", "down", "hold")},
        "first_scale_up": (None if first_up_idx is None
                           else event_kinds[first_up_idx]),
        "first_scale_up_idx": first_up_idx,
        "burn_crossed_idx": burn_cross_idx,
        "predictive_first": bool(predictive_first),
        "client_itl_ms": client_itl,
        "client_tpot_ms": s.get("tpot_ms"),
        "phase_breakdown": s.get("phases"),
        "telemetry_ok": bool(telemetry_ok),
        "tenants_tracked": bool(tenants_tracked),
        "tenant_client_ok": client_ok,
        "tenant_router_ok": router_ok,
        "tenant_client_match": bool(tenant_client_match),
        "tenant_conserves": bool(tenant_conserves),
        "tenant_sweep": {"distinct": sweep_snap.get("distinct_seen"),
                         "tracked": sweep_snap.get("tracked"),
                         "k": sweep_snap.get("k")},
        "tenant_sweep_bounded": bool(tenant_sweep_bounded),
        "lifecycle_ok": bool(lifecycle_ok),
        "lifecycle_problems": lc_problems,
        "lifecycle_phases": {
            rank: {k: round(v, 2)
                   for k, v in (rec.get("phases_ms") or {}).items()}
            for rank, rec in sorted(lc_records.items())
            if isinstance(rank, int)},
        "observed_spawn_ms_logged": bool(observed_spawn_logged),
        "observed_spawn_ms": (scale_ups[-1].get("observed_spawn_ms")
                              if scale_ups else None),
        "recovered": (
            s["admitted_failures"] == 0 and s["replayed"] == 0
            and s["ok"] > 0 and s["shed"] + s["ok"] > 0
            and len(scale_ups) >= 1 and scaler.peak_replicas >= 2
            and gen_p99 is not None and gen_p99 <= p99_bound_ms
            and len(scale_downs) >= 1 and bool(returned_to_min)
            and bool(drain_order_ok)
            and bool(predictive_first)
            and counters.get(
                "autoscaler.decisions{action=up_predictive}", 0) >= 1
            and counters.get("autoscaler.decisions{action=down}", 0) >= 1
            and gauges.get("autoscaler.replicas{state=actual}") == 1
            and client_itl is not None and bool(phases_ok)
            and bool(telemetry_ok)
            and bool(tenants_tracked)
            and bool(tenant_client_match)
            and bool(tenant_conserves)
            and bool(tenant_sweep_bounded)
            and bool(lifecycle_ok)
            and bool(observed_spawn_logged)),
    }
    return report


def _qos_engine_preemption(seed=0, new_tokens=12, kv_precision=None):
    """In-process preemption bit-identity (ISSUE 18): fill every decode
    slot with FREE-class sequences, let them decode a few chunks, then
    submit PAID ones — the scheduler must preempt the free youngest
    through the SAME recompute-eviction path pressure uses, the paid
    requests must run, and every preempted free stream must finish
    bit-identical to an unloaded reference, re-admitted WARM from the
    prefix cache.  Pool AND refcount table empty after drain + cache
    clear.  ``kv_precision='int8'`` reruns the contract on the
    quantized tier."""
    import numpy as np

    from paddle_tpu.inference.engine import EngineConfig, InferenceEngine
    from paddle_tpu.observability import metrics

    model = _build_engine_model(seed)
    rs = np.random.RandomState(seed + 7)
    # prompts span >1 page so the prefix cache can retain their
    # prefill — the warm-resume gate below needs cacheable prompts
    free_prompts = [rs.randint(0, 256, (12 + 2 * i,)).astype(np.int32)
                    for i in range(4)]
    paid_prompts = [rs.randint(0, 256, (11 + 2 * i,)).astype(np.int32)
                    for i in range(2)]
    base = dict(page_size=8, max_slots=4, decode_chunk=2,
                max_seq_len=96, kv_precision=kv_precision)

    # unloaded reference with the cache OFF: preemption (and the cache)
    # may change WHEN a victim's tokens appear, never WHICH
    ref_eng = InferenceEngine(model, EngineConfig(
        **base, prefix_cache=False))
    free_refs = ref_eng.generate(free_prompts, max_new_tokens=new_tokens)
    paid_refs = ref_eng.generate(paid_prompts, max_new_tokens=new_tokens)
    ref_leak = ref_eng.pool.used_pages

    pre = metrics.snapshot()["counters"].get(
        "qos.preemptions{class=free}", 0)
    eng = InferenceEngine(model, EngineConfig(**base, prefix_cache=True))
    free_handles = [eng.submit(p, max_new_tokens=new_tokens,
                               priority_class="free")
                    for p in free_prompts]
    for _ in range(4):
        eng.step()      # free fills all 4 slots, decodes a few chunks
    paid_handles = [eng.submit(p, max_new_tokens=new_tokens,
                               priority_class="paid")
                    for p in paid_prompts]
    handles = free_handles + paid_handles
    idle = 0
    while any(not h.done.is_set() for h in handles) and idle < 2000:
        idle = idle if eng.step() else idle + 1
    free_ok = all(np.array_equal(h.result(timeout=1.0), free_refs[i])
                  for i, h in enumerate(free_handles))
    paid_ok = all(np.array_equal(h.result(timeout=1.0), paid_refs[i])
                  for i, h in enumerate(paid_handles))

    ring = eng.decisions.events()
    preempts = [e for e in ring if e.get("kind") == "evict_preempt"]
    # the policy rule: a preemption victim is NEVER a class peer or
    # better — here every victim must be free, evicted FOR a paid
    victims_free = bool(preempts) and all(
        e.get("victim_class") == "free"
        and e.get("for_class") == "paid" for e in preempts)
    mid_decode = any(e.get("generated", 0) > 0 for e in preempts)
    # warm resume: every preempted request's RE-admission (evictions>0)
    # must ride the radix cache, not recompute its prefix cold
    victim_ids = {e.get("request_id") for e in preempts}
    readmits = [e for e in ring
                if e.get("kind") == "admit"
                and e.get("request_id") in victim_ids
                and e.get("evictions", 0) > 0]
    warm_resume = bool(readmits) and all(
        e.get("cache_state") in ("hit", "partial") for e in readmits)
    preempt_count = metrics.snapshot()["counters"].get(
        "qos.preemptions{class=free}", 0) - pre

    # drain accounting: after completion every live page belongs to the
    # cache alone; clearing it must empty pool AND refcount table
    pool_stats = eng.pool.stats()
    no_live_refs = pool_stats["logical_pages"] == pool_stats["used"]
    eng.clear_prefix_cache()
    drain_leak = eng.pool.used_pages
    refcount_leak = len(eng.pool.ref_counts())

    return {
        "kv_precision": kv_precision or "bf16",
        "free_streams_bit_identical": bool(free_ok),
        "paid_streams_bit_identical": bool(paid_ok),
        "preempt_events": len(preempts),
        "preemptions_counted": preempt_count,
        "victims_all_free_for_paid": bool(victims_free),
        "preempted_mid_decode": bool(mid_decode),
        "warm_resume": bool(warm_resume),
        "ref_page_leak": ref_leak,
        "drain_page_leak": drain_leak,
        "refcount_leak": refcount_leak,
        "recovered": (
            bool(free_ok) and bool(paid_ok) and len(preempts) >= 1
            and preempt_count >= 1 and bool(victims_free)
            and bool(mid_decode) and bool(warm_resume)
            and ref_leak == 0 and drain_leak == 0
            and refcount_leak == 0 and bool(no_live_refs)),
    }


def run_qos_chaos(seed=0, base_rps=4.0, surge_mult=10.0, warm_s=3.0,
                  surge_s=10.0, cool_s=6.0, paid_p99_bound_ms=15000.0):
    """QoS chaos (ISSUE 18): a two-class 10× surge against a BOUNDED
    autoscaled toy fleet (max 2 replicas — the point is degradation
    under real scarcity, not scaling out of it), plus the in-process
    preemption bit-identity contract on both KV tiers.  `recovered`
    means: the paid tier holds bounded p99 with ZERO admitted failures
    and zero replays; the free tier degrades GRACEFULLY (sheds counted
    — and strictly more than paid's — zero failures, zero replays);
    per-class SLO rows are live on the router's /debug/telemetry;
    every autoscaler decision event carries the paid-class burn it
    acted on; preempted free streams resume bit-identical (bf16 AND
    int8 KV) warm from the prefix cache; and zero page/refcount leak
    after drain."""
    import time as _time
    import urllib.request as _urlreq

    from paddle_tpu import observability as obs
    from paddle_tpu.inference.autoscaler import Autoscaler
    from paddle_tpu.inference.fleet import ReplicaFleet, toy_token
    from paddle_tpu.observability import metrics

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import loadgen
    finally:
        sys.path.pop(0)

    obs.attach(crash_hook=False)
    metrics.reset()
    obs.attach(crash_hook=False)  # re-declare the schema post-reset
    prev_window = os.environ.get("PADDLE_TPU_SLO_WINDOW")
    os.environ["PADDLE_TPU_SLO_WINDOW"] = "10.0"
    fleet = scaler = None
    try:
        fleet = ReplicaFleet(num_replicas=1, kind="toy",
                             token_time=0.02, service_time=0.02,
                             max_slots=4, launch_timeout=60,
                             monitor_interval=0.1)
        fleet.start()
        # a BOUNDED fleet: one spare replica, then the surge must be
        # absorbed by class policy — shed free first, keep paid whole
        scaler = Autoscaler(
            fleet, min_replicas=1, max_replicas=2,
            burn_up=2.0, occ_up=0.9, occ_down=0.15,
            up_sustain=2, down_sustain=8, cooldown_s=2.0,
            interval=0.2, drain_grace=5.0)
        scaler.start()
        # half the tenant cohort paid, half free — no misbehaving
        # clients: every shed here is pure CLASS policy
        workload = loadgen.SharedPrefixWorkload(
            seed=seed, tenants=4, system_prompt_tokens=16,
            suffix_tokens=(3, 6), generate_frac=0.7,
            max_new_tokens=20,
            class_split={"paid": 0.5, "free": 0.5})
        phases = loadgen.surge_phases(
            base_rps=base_rps, surge_mult=surge_mult, warm_s=warm_s,
            surge_s=surge_s, cool_s=cool_s)
        runner = loadgen.OpenLoopRunner(
            fleet.router.address, workload, phases, seed=seed,
            expected_token=toy_token, timeout=30.0, max_retries=2)
        load_report = runner.run()
        deadline = _time.monotonic() + 45.0
        while _time.monotonic() < deadline and \
                fleet.replica_count() > 1:
            _time.sleep(0.2)
        returned_to_min = fleet.replica_count() == 1
        with _urlreq.urlopen(fleet.router.address + "/debug/telemetry",
                             timeout=10) as r:
            debug_snap = json.loads(r.read())
        scaler.stop()
        snap = metrics.snapshot()
    finally:
        if prev_window is None:
            os.environ.pop("PADDLE_TPU_SLO_WINDOW", None)
        else:
            os.environ["PADDLE_TPU_SLO_WINDOW"] = prev_window
        if scaler is not None:
            scaler.stop()
        if fleet is not None:
            fleet.stop()
        obs.detach()

    s = load_report.summary()
    counters = snap["counters"]
    paid = s["classes"].get("paid") or {}
    free = s["classes"].get("free") or {}
    paid_p99 = (paid.get("latency_ms") or {}).get("p99")
    # graceful degradation, per tier: paid NEVER fails once admitted
    # and its sheds (allowed under total exhaustion) stay strictly
    # below free's — free absorbs the surge, politely
    paid_ok = (paid.get("admitted", 0) > 0
               and paid.get("admitted_failures", 1) == 0
               and paid.get("status", {}).get("replayed", 0) == 0
               and paid_p99 is not None
               and paid_p99 <= paid_p99_bound_ms)
    free_ok = (free.get("shed", 0) > 0
               and free.get("admitted_failures", 1) == 0
               and free.get("status", {}).get("replayed", 0) == 0)
    class_policy_ok = free.get("shed", 0) > paid.get("shed", 0)
    # the shed ledger: class-labelled sheds visible fleet-wide
    shed_free_counted = counters.get("qos.shed{class=free}", 0) > 0
    # per-class SLO rows on the router's debug plane, for BOTH tiers
    slo_eps = (debug_snap.get("slo") or {}).get("endpoints") or {}
    slo_classes_ok = any(
        set((ep.get("classes") or {})) >= {"paid", "free"}
        for ep in slo_eps.values())
    # every decision event logs the paid-class burn it acted on — and
    # the surge must have produced at least one actual decision
    events = [e for e in scaler.events if e.get("kind") != "tick_error"]
    paid_burn_logged = bool(events) and all(
        "paid_burn_rate" in e for e in events)
    scale_ups = [e for e in events
                 if e["kind"] in ("scale_up", "scale_up_predictive")]

    # in-process preemption bit-identity, both KV tiers
    obs.attach(crash_hook=False)
    metrics.reset()
    obs.attach(crash_hook=False)
    try:
        engine_bf16 = _qos_engine_preemption(seed=seed)
        engine_int8 = _qos_engine_preemption(seed=seed,
                                             kv_precision="int8")
    finally:
        obs.detach()

    report = {
        "scenario": "qos",
        "phases": [f"{p.name}:{p.duration_s}s@{p.rps}rps"
                   for p in phases],
        "requests": s["requests"],
        "ok": s["ok"],
        "shed": s["shed"],
        "replayed": s["replayed"],
        "admitted_failures": s["admitted_failures"],
        "failure_detail": s["failure_detail"],
        "classes": s["classes"],
        "paid_p99_ms": paid_p99,
        "paid_ok": bool(paid_ok),
        "free_graceful": bool(free_ok),
        "free_sheds_exceed_paid": bool(class_policy_ok),
        "qos_shed_counters": {
            c: counters.get(f"qos.shed{{class={c}}}", 0)
            for c in ("paid", "free", "batch")},
        "slo_classes_on_debug_plane": bool(slo_classes_ok),
        "scale_ups": len(scale_ups),
        "peak_replicas": scaler.peak_replicas,
        "returned_to_min": bool(returned_to_min),
        "decision_events": len(events),
        "paid_burn_rate_logged": bool(paid_burn_logged),
        "engine": engine_bf16,
        "engine_int8": engine_int8,
        "recovered": (
            bool(paid_ok) and bool(free_ok) and bool(class_policy_ok)
            and s["replayed"] == 0 and bool(shed_free_counted)
            and bool(slo_classes_ok) and bool(paid_burn_logged)
            and len(scale_ups) >= 1 and bool(returned_to_min)
            and bool(engine_bf16["recovered"])
            and bool(engine_int8["recovered"])),
    }
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario",
                    choices=("train", "overload", "preemption", "engine",
                             "fleet", "prefix", "surge", "qos",
                             "resume"),
                    default="train")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ckpt-every", type=int, default=5)
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON on stdout")
    args = ap.parse_args(argv)
    if args.scenario == "overload":
        report = run_overload(seed=args.seed)
    elif args.scenario == "engine":
        report = run_engine_chaos(seed=args.seed)
        # the quantized page pool rides the SAME chaos (ISSUE 12):
        # zero page leak and survivors bit-identical (run-to-run
        # determinism of the int8 tier) must hold under cancels/kills
        q = run_engine_chaos(seed=args.seed, kv_precision="int8")
        report["quantized_pool"] = q
        report["recovered"] = bool(report["recovered"]
                                   and q["recovered"])
    elif args.scenario == "fleet":
        report = run_fleet_chaos(seed=args.seed)
    elif args.scenario == "resume":
        report = run_resume_chaos(seed=args.seed)
    elif args.scenario == "surge":
        report = run_surge_chaos(seed=args.seed)
    elif args.scenario == "qos":
        report = run_qos_chaos(seed=args.seed)
    elif args.scenario == "prefix":
        report = run_prefix_chaos(seed=args.seed)
    elif args.scenario == "preemption":
        report = run_preemption(steps=min(args.steps, 12), seed=args.seed)
    else:
        report = run_chaos(steps=args.steps, seed=args.seed,
                           ckpt_every=args.ckpt_every)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        for k, v in report.items():
            if k != "resilience_counters":
                print(f"{k:>24}: {v}")
    if not report["recovered"]:
        print(f"CHAOS CHECK FAILED ({args.scenario}): run did not "
              "recover", file=sys.stderr)
        return 1
    print(f"chaos check ({args.scenario}): recovered OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
