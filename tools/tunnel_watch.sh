#!/bin/bash
# Session-long TPU tunnel watcher (VERDICT r3 Next #1).
#
# The round-3 datapoint was lost because bench.py probed the tunnel once,
# for ~7.5 min, at the one moment the driver ran it — and the tunnel was
# down. This watcher inverts that: it probes cheaply every few minutes for
# the WHOLE session, and whenever the tunnel is up it runs the full
# chip_session evidence set (sanity, kernel sweeps, autotune seed,
# generate, bench). Successful bench JSON lines are persisted to
# tools/last_good_bench.jsonl, which bench.py reuses (with
# "source": "chip_session") when the live probe fails at capture time.
#
# Usage: nohup bash tools/tunnel_watch.sh &   (idempotent: lockfile)
set -u
cd "$(dirname "$0")/.."
LOCK=tools/.tunnel_watch.lock
exec 9>"$LOCK"
if ! flock -n 9; then
    echo "tunnel_watch already running" >&2
    exit 0
fi
LOG=tools/tunnel_watch.log
PROBE='import sys
sys.path.insert(0, ".")
from paddle_tpu.backend_guard import probe_default_backend
p = probe_default_backend(timeout=90.0, retries=1)
sys.exit(0 if p is not None and p[0] in ("tpu", "axon") else 1)'

STATE=tools/tunnel_state.json
echo "[$(date +%H:%M:%S)] tunnel_watch start" >>"$LOG"
CAPTURES=0
while true; do
    if python -c "$PROBE" >>"$LOG" 2>&1; then
        printf '{"status": "up", "t": %s}\n' "$(date +%s)" >"$STATE"
        echo "[$(date +%H:%M:%S)] tunnel UP — running chip_session" >>"$LOG"
        timeout 5400 python tools/chip_session.py >>"$LOG" 2>&1
        rc=$?
        echo "[$(date +%H:%M:%S)] chip_session rc=$rc" >>"$LOG"
        # durability: measured data must survive even if this session
        # ends before anyone commits by hand (the r5 session-2 checkout
        # wiped the r5 session-1 capture). git locks serialize against
        # concurrent builder commits; a transient failure just retries
        # next capture.
        git add tools/chip_session_log.jsonl tools/last_good_bench.jsonl \
            2>>"$LOG" && \
            git commit -q -m "chip_session: captured measurement data (auto-commit by tunnel_watch)" \
                >>"$LOG" 2>&1 || true
        CAPTURES=$((CAPTURES + 1))
        # evidence captured — re-refresh at a slow cadence so later
        # captures stay fresh without hogging the chip
        sleep 2400
    else
        printf '{"status": "down", "t": %s}\n' "$(date +%s)" >"$STATE"
        echo "[$(date +%H:%M:%S)] tunnel down" >>"$LOG"
        sleep 150
    fi
done
