#!/usr/bin/env python
"""Open-loop traffic generator: the adversary an autoscaler must survive.

Every hand-rolled burst loop in bench/chaos before this was CLOSED
loop: N threads each fire, wait for the response, fire again — so the
moment the server saturates, the *offered load falls to match* and the
overload the test meant to produce quietly disappears.  An autoscaler
tested that way passes while idle capacity burns and real surges shed
forever.  This generator is OPEN loop (ISSUE 14): arrivals follow a
Poisson process whose rate is a function of time ONLY — a saturated
server slows completions, never arrivals — which is the only honest
way to produce the failure modes elasticity must absorb.

Pieces (all importable — tests, bench, and chaos share ONE workload
definition instead of three burst loops):

  * `Phase(name, duration_s, rps)` — a flat-rate segment.
    `surge_phases()` builds the warm → 10× step → cool-down shape the
    surge chaos scenario gates on; `diurnal_phases()` builds a
    sampled sinusoid (the boring-day shape).
  * `SharedPrefixWorkload` — a seeded tenant population: each tenant
    owns a page-aligned shared system prompt (exercises the prefix
    cache + affinity routing of ISSUE 13 under churn), requests are a
    predict/generate mix, and a configurable fraction MISBEHAVE:
    disconnect mid-stream, ignore Retry-After (hammer straight back),
    or send oversized garbage bodies.  `arrivals(phases, rng)` yields
    the open-loop Poisson schedule; `schedule_burst(n, window_s)`
    yields a fixed-count arrival spread for capacity benches.
  * `OpenLoopRunner` — fires a schedule at an address (router or bare
    replica), one thread per arrival AT its arrival time, well-behaved
    clients honoring Retry-After with bounded retries; classifies
    every outcome, and — given `expected_token` (e.g. the fleet's
    deterministic `toy_token`) — verifies each delivered stream is an
    EXACT PREFIX of the true sequence, so one replayed or skipped
    token during a drain/failover is caught as `replayed`.
  * `LoadReport.summary()` — counts by kind/status, latency
    percentiles, tokens/s, and `admitted_failures` (errors + corrupt
    responses + replays; sheds and deliberate client misbehavior are
    NOT failures — shedding politely is correct behavior).  ISSUE 15:
    client-side `itl_ms` (p50/p95/p99 over every inter-token gap) and
    `tpot_ms` (per-stream mean time/output token) percentiles — the
    cross-check for the server's `serving.itl_ms` histogram — plus a
    per-phase (warm/surge/cool) `phases` breakdown with each phase's
    status counts and ok-latency percentiles.  ISSUE 16: every request
    carries an `X-Tenant-Id: tenant-<i>` header and the summary adds a
    per-tenant `tenants` breakdown — the client-side ground truth the
    chaos gates reconcile against the server's tenant ledger.

The client side is stdlib-only (http.client + json); numpy is imported
lazily only to build/parse /predict npz bodies, and nothing here
imports paddle_tpu — the generator drives a fleet from outside, like
traffic does.

Usage:
  python tools/loadgen.py http://127.0.0.1:8866 \
      [--base-rps 5] [--surge-mult 10] [--warm-s 3] [--surge-s 10]
      [--cool-s 6] [--diurnal] [--seed 0] [--generate-frac 0.7]
      [--tenants 4] [--json]
"""
from __future__ import annotations

import argparse
import hashlib
import http.client
import json
import math
import random
import struct
import threading
import time
import urllib.parse

__all__ = ["Phase", "surge_phases", "diurnal_phases",
           "SharedPrefixWorkload", "OpenLoopRunner", "LoadReport",
           "prefix_fingerprint", "tenant_name"]


def tenant_name(idx):
    """The X-Tenant-Id a spec's integer `tenant` index is stamped as —
    one definition shared by the runner and the chaos gates that
    cross-check client rows against the server's tenant ledger
    (ISSUE 16)."""
    return f"tenant-{int(idx)}"


class Phase:
    """One flat-rate segment of the arrival schedule."""

    __slots__ = ("name", "duration_s", "rps")

    def __init__(self, name, duration_s, rps):
        self.name = str(name)
        self.duration_s = float(duration_s)
        self.rps = float(rps)

    def __repr__(self):
        return f"Phase({self.name!r}, {self.duration_s}s, {self.rps}rps)"


def surge_phases(base_rps=5.0, surge_mult=10.0, warm_s=3.0,
                 surge_s=10.0, cool_s=6.0, cool_rps=None):
    """warm → STEP to surge_mult× → cool: the shape
    `chaos_check --scenario surge` gates on.  The step is deliberately
    instantaneous (no ramp): a ramp gives the autoscaler early warning
    a real traffic step does not."""
    if cool_rps is None:
        cool_rps = base_rps / 2.0
    return [Phase("warm", warm_s, base_rps),
            Phase("surge", surge_s, base_rps * surge_mult),
            Phase("cool", cool_s, cool_rps)]


def diurnal_phases(base_rps=4.0, peak_mult=2.5, period_s=20.0,
                   steps=10):
    """A sampled sinusoid over one period: rate swings between
    base_rps and base_rps*peak_mult — the boring-day shape that a
    scale-down path has to ride without flapping."""
    out = []
    for i in range(int(steps)):
        frac = 0.5 - 0.5 * math.cos(2.0 * math.pi * i / steps)
        rate = base_rps * (1.0 + (peak_mult - 1.0) * frac)
        out.append(Phase(f"diurnal{i}", period_s / steps, rate))
    return out


def _assign_classes(tenants, class_split):
    """Deterministic tenant->class cohort assignment.  `class_split`
    maps class name -> fraction (need not sum to 1; fractions are
    normalized); tenants fill contiguous cohorts in the split's
    declared order.  With no split every tenant maps to None (no
    header stamped — the server's default tier applies)."""
    if not class_split:
        return [None] * int(tenants)
    bounds, acc = [], 0.0
    for cls, frac in class_split.items():
        acc += max(0.0, float(frac))
        bounds.append((str(cls), acc))
    total = acc or 1.0
    out = []
    for i in range(int(tenants)):
        x = (i + 0.5) / max(1, int(tenants)) * total
        out.append(next((cls for cls, b in bounds if x <= b),
                        bounds[-1][0]))
    return out


def prefix_fingerprint(ids, tokens=64, granule=16):
    """stdlib twin of `InferenceClient.prefix_fingerprint` (same sha1
    over little-endian int64 tokens, same page-granule floor), so
    loadgen traffic exercises the router's prefix-affinity path exactly
    as real clients do.  Returns None for prompts too short to share a
    page."""
    ids = [int(x) for x in ids]
    n = min(int(tokens), (len(ids) // int(granule)) * int(granule))
    if n <= 0:
        return None
    return hashlib.sha1(
        struct.pack(f"<{n}q", *ids[:n])).hexdigest()[:16]


class SharedPrefixWorkload:
    """Seeded request population over shared-prefix tenants.

    Each tenant owns a `system_prompt_tokens`-long shared prefix
    (page-aligned by construction when the engine page size divides
    it); every request appends a unique suffix — the PR 13 cache gets
    real hits and the router's affinity map gets real tenants.
    `generate_frac` of requests stream /generate, the rest are
    /predict echoes.  Misbehavior fractions are cumulative slices of
    [0,1): a request is assigned exactly one behavior."""

    def __init__(self, seed=0, tenants=4, system_prompt_tokens=16,
                 suffix_tokens=(3, 8), vocab=200, generate_frac=0.75,
                 max_new_tokens=12, predict_shape=(2, 2),
                 misbehave_disconnect=0.0, misbehave_ignore_retry=0.0,
                 misbehave_oversize=0.0, class_split=None):
        self.seed = int(seed)
        self.vocab = int(vocab)
        self.generate_frac = float(generate_frac)
        self.max_new_tokens = int(max_new_tokens)
        self.predict_shape = tuple(predict_shape)
        self.suffix_tokens = (int(suffix_tokens[0]),
                              int(suffix_tokens[1]))
        self.misbehave_disconnect = float(misbehave_disconnect)
        self.misbehave_ignore_retry = float(misbehave_ignore_retry)
        self.misbehave_oversize = float(misbehave_oversize)
        rng = random.Random(self.seed)
        self.tenant_prompts = [
            [rng.randrange(self.vocab)
             for _ in range(int(system_prompt_tokens))]
            for _ in range(int(tenants))]
        # QoS class cohorts (ISSUE 18): a class is a property of the
        # TENANT (the billing entity buys a tier), not the request —
        # `class_split` maps class -> fraction of tenants, assigned as
        # contiguous deterministic cohorts so the same seed always
        # yields the same paid/free/batch population.  None (default)
        # stamps no X-Priority-Class header at all.
        self.tenant_classes = _assign_classes(
            len(self.tenant_prompts), class_split)
        self._counter = 0

    def sample(self, rng):
        """One request spec (plain dict — JSON-able, transport-free)."""
        self._counter += 1
        r = rng.random()
        behavior = "well_behaved"
        edge = self.misbehave_disconnect
        if r < edge:
            behavior = "disconnect"
        elif r < (edge := edge + self.misbehave_ignore_retry):
            behavior = "ignore_retry_after"
        elif r < edge + self.misbehave_oversize:
            behavior = "oversize"
        kind = ("generate" if rng.random() < self.generate_frac
                else "predict")
        tenant = rng.randrange(len(self.tenant_prompts))
        suffix = [rng.randrange(self.vocab) for _ in range(
            rng.randint(*self.suffix_tokens))]
        return {
            "id": self._counter,
            "kind": kind,
            "behavior": behavior,
            "tenant": tenant,
            "priority_class": self.tenant_classes[tenant],
            "prompt": list(self.tenant_prompts[tenant]) + suffix,
            "max_new_tokens": self.max_new_tokens,
            "value": float(self._counter % 97),
            "shape": self.predict_shape,
        }

    def arrivals(self, phases, rng=None):
        """The open-loop Poisson schedule: yields (t_offset_s, spec)
        with exponential inter-arrival times at each phase's rate.
        Arrival times are a function of the phases and the seed ONLY —
        never of how the server is coping."""
        rng = rng or random.Random(self.seed)
        base = 0.0
        for ph in phases:
            end = base + ph.duration_s
            if ph.rps <= 0.0:
                base = end
                continue
            t = base
            while True:
                t += rng.expovariate(ph.rps)
                if t >= end:
                    break
                spec = self.sample(rng)
                spec["phase"] = ph.name  # per-phase latency breakdown
                yield t, spec
            base = end

    def schedule_burst(self, n, window_s=0.25, rng=None):
        """Fixed-count arrival spread: `n` requests uniformly inside
        `window_s` — the capacity-bench shape (deterministic request
        COUNT, still open-loop: the spread never waits on completions)."""
        rng = rng or random.Random(self.seed)
        out = []
        for i in range(int(n)):
            spec = self.sample(rng)
            spec["phase"] = "burst"
            out.append((i * window_s / max(1, n), spec))
        return out


class LoadReport:
    """Everything the runner observed, with the accounting the chaos
    gate and the bench both read."""

    def __init__(self, rows, wall_s):
        self.rows = list(rows)
        self.wall_s = float(wall_s)

    _FAILURES = ("error", "corrupt", "replayed")

    @staticmethod
    def _pcts(vals):
        vals = sorted(vals)
        return {"p50": round(_quantile(vals, 0.50), 2),
                "p95": round(_quantile(vals, 0.95), 2),
                "p99": round(_quantile(vals, 0.99), 2),
                "max": round(vals[-1], 2), "n": len(vals)}

    def summary(self):
        by_kind: dict = {}
        status: dict = {}
        lat: dict = {"predict": [], "generate": []}
        tokens = 0
        all_gaps = []              # every inter-token gap, all streams
        tpot = []                  # per-stream mean time/output token
        phases: dict = {}
        tenants: dict = {}
        classes: dict = {}
        for row in self.rows:
            # per-priority-class breakdown (ISSUE 18): what EACH tier
            # experienced — admitted/shed counts and latency
            # percentiles per class are the client-side ground truth
            # the qos chaos gate asserts graceful degradation against
            cls = row.get("priority_class")
            if cls:
                cstat = classes.setdefault(cls, {
                    "requests": 0, "status": {}, "tokens": 0,
                    "_lat": []})
                cstat["requests"] += 1
                cstat["status"][row["status"]] = \
                    cstat["status"].get(row["status"], 0) + 1
                cstat["tokens"] += row.get("tokens", 0) or 0
                if row["status"] == "ok" \
                        and row.get("latency_s") is not None:
                    cstat["_lat"].append(row["latency_s"] * 1e3)
            # per-tenant breakdown (ISSUE 16): what THIS client billed
            # each X-Tenant-Id — the ground truth the chaos gates
            # cross-check against the server-side tenant ledger
            tstat = tenants.setdefault(tenant_name(row["tenant"]), {
                "requests": 0, "status": {}, "tokens": 0})
            tstat["requests"] += 1
            tstat["status"][row["status"]] = \
                tstat["status"].get(row["status"], 0) + 1
            tstat["tokens"] += row.get("tokens", 0) or 0
            k, s = row["kind"], row["status"]
            by_kind.setdefault(k, {}).setdefault(s, 0)
            by_kind[k][s] += 1
            status[s] = status.get(s, 0) + 1
            tokens += row.get("tokens", 0) or 0
            if s == "ok" and row.get("latency_s") is not None:
                lat.setdefault(k, []).append(row["latency_s"] * 1e3)
            # client-side ITL/TPOT (ISSUE 15): gaps from every stream
            # that delivered ≥2 tokens — including interrupted ones
            # (their delivered prefix waited like any other); the
            # cross-check for the server's serving.itl_ms histogram
            gaps = row.get("itl_ms")
            if gaps and s in ("ok", "interrupted", "abandoned"):
                all_gaps.extend(gaps)
                tpot.append(sum(gaps) / len(gaps))
            ph = row.get("phase") or "unphased"
            pstat = phases.setdefault(ph, {
                "requests": 0, "status": {}, "tokens": 0, "_lat": []})
            pstat["requests"] += 1
            pstat["status"][s] = pstat["status"].get(s, 0) + 1
            pstat["tokens"] += row.get("tokens", 0) or 0
            if s == "ok" and row.get("latency_s") is not None:
                pstat["_lat"].append(row["latency_s"] * 1e3)
        latency = {}
        for k, vals in lat.items():
            if vals:
                latency[k] = self._pcts(vals)
        phase_out = {}
        for ph, pstat in phases.items():
            row = {k: v for k, v in pstat.items() if k != "_lat"}
            if pstat["_lat"]:
                row["latency_ms"] = self._pcts(pstat["_lat"])
            row["admitted_failures"] = sum(
                pstat["status"].get(s, 0) for s in self._FAILURES)
            phase_out[ph] = row
        class_out = {}
        for cls, cstat in sorted(classes.items()):
            row = {k: v for k, v in cstat.items() if k != "_lat"}
            if cstat["_lat"]:
                row["latency_ms"] = self._pcts(cstat["_lat"])
            row["admitted"] = cstat["status"].get("ok", 0)
            row["shed"] = cstat["status"].get("shed", 0)
            row["admitted_failures"] = sum(
                cstat["status"].get(s, 0) for s in self._FAILURES)
            class_out[cls] = row
        return {
            "requests": len(self.rows),
            "wall_s": round(self.wall_s, 3),
            "by_kind": by_kind,
            "status": status,
            "ok": status.get("ok", 0),
            "shed": status.get("shed", 0),
            "interrupted": status.get("interrupted", 0),
            # streams that absorbed ≥1 mid-stream failover (ISSUE 20):
            # the router resumed them invisibly — every token still
            # verified incrementally and the final record still had to
            # be the exact prompt+tokens prefix, so a resumed "ok" is a
            # REAL ok, never a laundered admitted failure
            "resumed_streams": sum(
                1 for r in self.rows if (r.get("resumed") or 0) > 0),
            "abandoned": status.get("abandoned", 0),
            "client_errors": status.get("client_error", 0),
            "replayed": status.get("replayed", 0),
            "admitted_failures": sum(status.get(s, 0)
                                     for s in self._FAILURES),
            "failure_detail": sorted(
                {f"{r['kind']}:{r['status']}:{r.get('detail')}"
                 for r in self.rows if r["status"] in self._FAILURES}),
            "tokens": tokens,
            "tokens_per_sec": round(tokens / self.wall_s, 1)
            if self.wall_s > 0 else 0.0,
            "latency_ms": latency,
            # client-observed per-token latency: every inter-token gap
            # pooled (itl_ms) and the per-stream mean (tpot_ms)
            "itl_ms": self._pcts(all_gaps) if all_gaps else None,
            "tpot_ms": self._pcts(tpot) if tpot else None,
            "phases": phase_out,
            "tenants": dict(sorted(tenants.items())),
            "classes": class_out,
        }


def _quantile(sorted_vals, q):
    n = len(sorted_vals)
    pos = q * (n - 1)
    i, frac = int(pos), pos - int(pos)
    if frac == 0.0 or i + 1 >= n:
        return float(sorted_vals[min(i, n - 1)])
    return float(sorted_vals[i]) + frac * (
        float(sorted_vals[i + 1]) - float(sorted_vals[i]))


class OpenLoopRunner:
    """Fire a schedule at `address`, one thread per arrival at its
    scheduled time.  Well-behaved clients retry 429/503 up to
    `max_retries` times honoring (a clamped) Retry-After;
    `ignore_retry_after` clients retry instantly — the misbehavior the
    edge admission has to absorb.  `expected_token(prompt, i)`
    (optional) turns every stream into a replay detector."""

    def __init__(self, address, workload, phases=None, seed=None,
                 expected_token=None, timeout=30.0, max_retries=2,
                 max_retry_wait=2.0, oversize_bytes=1 << 20):
        u = urllib.parse.urlparse(address if "//" in address
                                  else "http://" + address)
        self.host, self.port = u.hostname, u.port
        self.workload = workload
        self.phases = phases
        self.seed = workload.seed if seed is None else int(seed)
        self.expected_token = expected_token
        self.timeout = float(timeout)
        self.max_retries = max(0, int(max_retries))
        self.max_retry_wait = float(max_retry_wait)
        self.oversize_bytes = int(oversize_bytes)
        self._rows = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def run(self, schedule=None):
        """Execute the schedule (default: the workload's Poisson
        arrivals over `phases`).  Returns a LoadReport once every fired
        request resolved (bounded by per-request timeouts)."""
        if schedule is None:
            rng = random.Random(self.seed)
            schedule = list(self.workload.arrivals(self.phases, rng))
        with self._lock:
            self._rows = []
        threads = []
        t0 = time.monotonic()
        for t_at, spec in schedule:
            delay = (t0 + t_at) - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=self._fire, args=(spec,),
                                  daemon=True,
                                  name=f"loadgen-{spec['id']}")
            th.start()
            threads.append(th)
        # every request resolves within timeout + retries; the join
        # budget covers the worst chain with slack
        deadline = time.monotonic() + self.timeout * (
            self.max_retries + 1) + self.max_retry_wait * (
            self.max_retries + 1) + 30.0
        for th in threads:
            th.join(timeout=max(0.1, deadline - time.monotonic()))
        wall = time.monotonic() - t0
        with self._lock:
            rows = list(self._rows)
        return LoadReport(rows, wall)

    # ------------------------------------------------------------------
    def _record(self, spec, status, latency_s=None, tokens=0,
                detail=None, itl_ms=None, resumed=0):
        with self._lock:
            self._rows.append({
                "id": spec["id"], "kind": spec["kind"],
                "behavior": spec["behavior"], "tenant": spec["tenant"],
                "phase": spec.get("phase"),
                "priority_class": spec.get("priority_class"),
                "status": status, "latency_s": latency_s,
                "tokens": tokens, "detail": detail,
                "itl_ms": itl_ms, "resumed": resumed})

    def _fire(self, spec):
        t0 = time.monotonic()
        itl = None
        resumed = 0
        try:
            if spec["behavior"] == "oversize":
                status, tokens, detail = self._oversize(spec), 0, None
            elif spec["kind"] == "generate":
                status, tokens, detail, itl, resumed = \
                    self._generate(spec)
            else:
                status, detail = self._predict(spec)
                tokens = 0
        except Exception as e:  # noqa: BLE001 — report, don't crash
            status, tokens = "error", 0
            detail = f"{type(e).__name__}: {e}"
        self._record(spec, status, latency_s=time.monotonic() - t0,
                     tokens=tokens, detail=detail, itl_ms=itl,
                     resumed=resumed)

    def _retry_wait(self, headers):
        """Defensive Retry-After parse, clamped into
        [0.05, max_retry_wait] (same discipline as InferenceClient)."""
        try:
            ra = float(headers.get("Retry-After", 0.5))
        except (TypeError, ValueError):
            ra = 0.5
        if not math.isfinite(ra):
            ra = 0.5
        return min(max(ra, 0.05), self.max_retry_wait)

    def _connect(self):
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)

    # --- /generate (ndjson stream, stdlib parse) ----------------------
    def _generate(self, spec):
        body = json.dumps({
            "input_ids": spec["prompt"],
            "max_new_tokens": spec["max_new_tokens"]}).encode()
        headers = {"Content-Type": "application/json",
                   "X-Tenant-Id": tenant_name(spec["tenant"])}
        if spec.get("priority_class"):
            headers["X-Priority-Class"] = spec["priority_class"]
        fp = prefix_fingerprint(spec["prompt"])
        if fp is not None:
            headers["X-Prefix-Fingerprint"] = fp
        attempts = self.max_retries + 1
        last = ("error", 0, "no attempt ran", None, 0)
        for attempt in range(attempts):
            conn = self._connect()
            try:
                conn.request("POST", "/generate", body=body,
                             headers=headers)
                resp = conn.getresponse()
                if resp.status in (429, 503):
                    wait = self._retry_wait(dict(resp.headers))
                    resp.read()
                    last = ("shed", 0, f"http {resp.status}", None, 0)
                    if attempt < attempts - 1:
                        if spec["behavior"] != "ignore_retry_after":
                            time.sleep(wait)
                        continue
                    return last
                if resp.status != 200:
                    return (("client_error" if resp.status == 400
                             else "error"), 0, f"http {resp.status}",
                            None, 0)
                return self._consume_stream(spec, resp, conn)
            except OSError as e:
                last = ("error", 0, f"{type(e).__name__}: {e}",
                        None, 0)
            finally:
                conn.close()
        return last

    def _consume_stream(self, spec, resp, conn):
        """Read the ndjson stream; verify tokens against
        `expected_token` as they arrive and stamp every arrival — the
        CLIENT-side inter-token gaps (ISSUE 15) that cross-check the
        server's `serving.itl_ms` histogram in the surge scenario.
        Disconnect clients bail after the first token — the server
        must notice the dead socket and cancel the sequence (its pages
        return to the pool).  A `"resumed": n` on the final record
        (ISSUE 20) is counted, not trusted: a resumed stream earns
        "ok" exactly like any other — every token verified
        incrementally, final `output_ids` an exact prompt+tokens
        match — so a replay or invention across the resume seam is
        caught the same way.  Returns (status, n_tokens, detail,
        itl_ms_list, resumed)."""
        prompt, tokens = spec["prompt"], []
        gaps = []
        last_t = None
        for line in resp:
            line = line.strip()
            if not line:
                continue
            evt = json.loads(line)
            if "token" in evt:
                now = time.monotonic()
                if last_t is not None:
                    gaps.append((now - last_t) * 1e3)
                last_t = now
                tok = int(evt["token"])
                tokens.append(tok)
                # incremental: each token is checked ONCE as it
                # arrives (earlier ones already passed), so a stream
                # costs O(n) expected_token calls, not O(n^2)
                if self.expected_token is not None and \
                        tok != self.expected_token(prompt,
                                                   len(tokens) - 1):
                    return "replayed", len(tokens), \
                        f"token {len(tokens) - 1} wrong", gaps, 0
                if spec["behavior"] == "disconnect":
                    conn.close()   # die mid-stream, deliberately
                    return "abandoned", len(tokens), None, gaps, 0
            elif evt.get("interrupted"):
                # the clean mid-stream cut: every delivered token
                # already verified above; the record must carry the
                # resumable prefix exactly
                prefix_ok = list(evt.get("output_ids") or []) \
                    == list(prompt) + tokens
                return (("interrupted" if prefix_ok else "replayed"),
                        len(tokens),
                        None if prefix_ok else "bad resumable prefix",
                        gaps, 0)
            elif evt.get("done"):
                out_ok = list(evt.get("output_ids") or []) \
                    == list(prompt) + tokens
                return (("ok" if out_ok else "replayed"), len(tokens),
                        None if out_ok else "final record mismatch",
                        gaps, int(evt.get("resumed", 0) or 0))
        return ("error", len(tokens),
                "stream ended without final record", gaps, 0)

    # --- /predict (npz body; numpy is the one lazy non-stdlib need) ---
    def _predict(self, spec):
        import io

        import numpy as np  # lazy: only the npz codec needs it

        x = np.full(spec["shape"], spec["value"], np.float32)
        buf = io.BytesIO()
        np.savez(buf, x=x)
        data = buf.getvalue()
        attempts = self.max_retries + 1
        last = ("error", "no attempt ran")
        for attempt in range(attempts):
            conn = self._connect()
            try:
                headers = {"Content-Type": "application/octet-stream",
                           "X-Tenant-Id": tenant_name(spec["tenant"])}
                if spec.get("priority_class"):
                    headers["X-Priority-Class"] = \
                        spec["priority_class"]
                conn.request("POST", "/predict", body=data,
                             headers=headers)
                resp = conn.getresponse()
                if resp.status in (429, 503):
                    wait = self._retry_wait(dict(resp.headers))
                    resp.read()
                    last = ("shed", f"http {resp.status}")
                    if attempt < attempts - 1:
                        if spec["behavior"] != "ignore_retry_after":
                            time.sleep(wait)
                        continue
                    return last
                if resp.status != 200:
                    return (("client_error" if resp.status == 400
                             else "error"), f"http {resp.status}")
                payload = resp.read()
                with np.load(io.BytesIO(payload)) as z:
                    y = z[z.files[0]]
                if np.array_equal(y, x):
                    return "ok", None
                return "corrupt", "echo mismatch"
            except OSError as e:
                last = ("error", f"{type(e).__name__}: {e}")
            finally:
                conn.close()
        return last

    # --- deliberate garbage -------------------------------------------
    def _oversize(self, spec):
        """A deliberately oversized non-JSON body: the fleet must
        answer a deterministic 400 (client_error), never crash a
        replica or burn error budget for it."""
        conn = self._connect()
        try:
            conn.request("POST", "/generate",
                         body=b"\xff" * self.oversize_bytes,
                         headers={"Content-Type": "application/json",
                                  "X-Tenant-Id":
                                  tenant_name(spec["tenant"])})
            resp = conn.getresponse()
            resp.read()
            return "client_error" if resp.status == 400 \
                else ("shed" if resp.status in (429, 503) else "error")
        except OSError:
            # the server refusing to swallow a megabyte of garbage
            # (connection torn mid-send) is the garbage-sender's
            # problem — deliberate misbehavior never counts as a
            # fleet failure
            return "client_error"
        finally:
            conn.close()


# ----------------------------------------------------------------------
def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("target", help="router or replica address "
                                   "(http://host:port)")
    ap.add_argument("--base-rps", type=float, default=5.0)
    ap.add_argument("--surge-mult", type=float, default=10.0)
    ap.add_argument("--warm-s", type=float, default=3.0)
    ap.add_argument("--surge-s", type=float, default=10.0)
    ap.add_argument("--cool-s", type=float, default=6.0)
    ap.add_argument("--diurnal", action="store_true",
                    help="sampled sinusoid instead of the surge step")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--generate-frac", type=float, default=0.7)
    ap.add_argument("--max-new-tokens", type=int, default=12)
    ap.add_argument("--misbehave", type=float, default=0.05,
                    help="total misbehaving-client fraction, split "
                         "across disconnect/ignore-retry/oversize")
    ap.add_argument("--class-split", default=None, metavar="SPEC",
                    help="tenant QoS cohorts, e.g. "
                         "'paid=0.25,free=0.5,batch=0.25' — stamps "
                         "X-Priority-Class per tenant (default: none)")
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    third = args.misbehave / 3.0
    class_split = None
    if args.class_split:
        class_split = {}
        for part in args.class_split.split(","):
            if "=" not in part:
                continue
            cls, _, frac = part.partition("=")
            try:
                class_split[cls.strip()] = float(frac)
            except ValueError:
                continue
    wl = SharedPrefixWorkload(
        seed=args.seed, tenants=args.tenants,
        generate_frac=args.generate_frac,
        max_new_tokens=args.max_new_tokens,
        misbehave_disconnect=third, misbehave_ignore_retry=third,
        misbehave_oversize=third, class_split=class_split)
    phases = (diurnal_phases(args.base_rps,
                             period_s=args.warm_s + args.surge_s
                             + args.cool_s)
              if args.diurnal else
              surge_phases(args.base_rps, args.surge_mult,
                           args.warm_s, args.surge_s, args.cool_s))
    runner = OpenLoopRunner(args.target, wl, phases, seed=args.seed,
                            timeout=args.timeout)
    report = runner.run()
    s = report.summary()
    if args.json:
        print(json.dumps(s, indent=2))
    else:
        for k, v in s.items():
            print(f"{k:>20}: {v}")
    return 0 if s["admitted_failures"] == 0 else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
