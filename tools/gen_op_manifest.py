"""Generate OPS_MANIFEST.json — the auditable op-coverage single source.

Role parity: `paddle/phi/api/yaml/ops.yaml` (+ `legacy_ops.yaml`) is the
reference's machine-checkable op inventory; this manifest plays that role
for the TPU build. It records, for every public op name the reference's
`paddle.tensor` surface exports (`python/paddle/tensor/__init__.py`
tensor_method_func) plus the PHI yaml op names:

    {"name", "present" (resolvable in paddle_tpu), "where" (module path),
     "tensor_method" (available as Tensor.<name>), "tested" (appears in
     tests/)}

Run:  python tools/gen_op_manifest.py          # rewrite OPS_MANIFEST.json
      python tools/gen_op_manifest.py --check  # exit 1 on drift (CI)

The companion test `tests/test_op_manifest.py` regenerates in-process and
asserts no drift and no coverage regression.
"""
from __future__ import annotations

import ast
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference"


def reference_tensor_api():
    """Public op names from the reference's paddle.tensor export list."""
    path = os.path.join(REF, "python/paddle/tensor/__init__.py")
    if not os.path.exists(path):
        return []
    tree = ast.parse(open(path).read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "tensor_method_func":
                    return sorted({
                        e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)})
    return []


def reference_yaml_ops():
    names = set()
    for fname in ("paddle/phi/api/yaml/ops.yaml",
                  "paddle/phi/api/yaml/legacy_ops.yaml"):
        path = os.path.join(REF, fname)
        if not os.path.exists(path):
            continue
        for line in open(path):
            m = re.match(r"^- op\s*:\s*([a-zA-Z0-9_]+)", line)
            if m:
                names.add(m.group(1))
    return sorted(names)


# PHI-yaml names that are kernel/static-graph internals, not user API: the
# TPU build subsumes them (XLA collectives, jit data movement, fused train
# steps). Listed in the manifest with internal=true and excluded from the
# coverage denominator — each group's subsumption story:
INTERNAL_OPS = {
    # static-graph collective kernels -> lax.p* inside sharded jit
    "c_allgather", "c_allreduce_max", "c_allreduce_sum", "c_broadcast",
    "c_concat", "c_embedding", "c_identity", "c_reduce_sum",
    "c_sync_calc_stream", "c_sync_comm_stream",
    # device data movement / memory plumbing -> jax.device_put / XLA
    "memcpy_d2h", "memcpy_h2d", "coalesce_tensor", "npu_identity",
    "copy_to", "trans_layout",
    # IR-internal value constructors (PIR full_* family, feed ops)
    "full_", "full_batch_size_like", "full_int_array", "full_with_tensor",
    "assign_out_", "assign_value_", "data", "read_file",
    "view_dtype", "view_shape", "tensor_unfold",
    # fused optimizer update kernels -> optimizer layer + fused train step
    "adadelta_", "adagrad_", "adam_", "adamax_", "adamw_",
    "average_accumulates_", "fused_adam_", "lamb_", "merged_adam_",
    "merged_momentum_", "momentum_", "rmsprop_", "rprop_", "sgd_",
    # AMP loss-scaling kernels -> amp.GradScaler compiled step
    "check_finite_and_unscale_", "update_loss_scaling_",
    "check_numerics", "disable_check_model_nan_inf",
    "enable_check_model_nan_inf",
    # SelectedRows / PS-era kernels with no TPU role
    "merge_selected_rows", "embedding_grad_dense",
    # quant/serving kernels gated out (no int8 path on this build yet)
    "llm_int8_linear", "weight_dequantize", "weight_only_linear",
    "weight_quantize",
    # fft internals (public API is paddle_tpu.fft.*)
    "fft_c2c", "fft_c2r", "fft_r2c",
    # flash-attn kernel entries (public API: nn.functional.flash_attention)
    "flash_attn", "flash_attn_unpadded", "memory_efficient_attention",
    "masked_multihead_attention_", "fused_softmax_mask_upper_triangle",
    "fused_batch_norm_act", "fused_bn_add_activation", "sync_batch_norm_",
    # misc kernel-level forms of ops whose public form exists
    "cross_entropy_with_softmax", "mean_all", "matrix_rank_tol",
    "max_pool2d_with_index", "max_pool3d_with_index", "pool2d", "pool3d",
    "squared_l2_norm", "frobenius_norm", "p_norm", "elementwise_pow",
    "slice_scatter_", "uniform_inplace", "gaussian_inplace",
    "top_k_v2", "set_value", "set_value_with_tensor",
    "repeat_interleave_with_tensor_index", "index_select_strided",
    # loss/act kernel names -> public F.* form exists (log_sigmoid,
    # binary_cross_entropy[_with_logits], kl_div, smooth_l1_loss, …)
    "bce_loss", "huber_loss", "kldiv_loss", "hsigmoid_loss", "logsigmoid",
    "tanh_shrink", "sigmoid_cross_entropy_with_logits", "warpctc",
    # interpolate/conv kernel variants -> F.interpolate / F.conv2d dispatch
    "bicubic_interp", "bilinear_interp", "linear_interp", "nearest_interp",
    "trilinear_interp", "depthwise_conv2d", "depthwise_conv2d_transpose",
    "pad3d",
    # rnn/segment fused kernels -> nn.LSTM/GRU layers, geometric.segment_*
    "rnn", "segment_pool",
    # init/random kernel names -> initializer / creation API forms
    "truncated_gaussian_random",
    # io codec (no TPU role, gated)
    "decode_jpeg",
    # kernel names whose public API form exists under the paddle name:
    # multiclass_nms (vision.ops), deform_conv2d, nn.SpectralNorm,
    # F.max_unpool1d/2d/3d, F.rnnt_loss
    "multiclass_nms3", "deformable_conv", "spectral_norm",
    "unpool", "unpool3d", "warprnnt",
}


def _resolve(name):
    """Find `name` in paddle_tpu's public namespaces; returns module path
    or None."""
    import paddle_tpu as P

    namespaces = [
        ("paddle_tpu", P),
        ("paddle_tpu.nn.functional", P.nn.functional),
        ("paddle_tpu.linalg", P.linalg),
        ("paddle_tpu.fft", P.fft),
        ("paddle_tpu.signal", P.signal),
        ("paddle_tpu.sparse", P.sparse),
        ("paddle_tpu.geometric", P.geometric),
        ("paddle_tpu.incubate.nn.functional", P.incubate.nn.functional),
        ("paddle_tpu.vision.ops", P.vision.ops),
        ("paddle_tpu.nn.quant", P.nn.quant),
    ]
    for mod_name, mod in namespaces:
        obj = getattr(mod, name, None)
        if obj is not None and not isinstance(obj, type(P)):
            return mod_name
    return None


def _test_sources():
    """{filename: source} for every test file."""
    out = {}
    tests_dir = os.path.join(REPO, "tests")
    for f in sorted(os.listdir(tests_dir)):
        if f.endswith(".py"):
            out[f] = open(os.path.join(tests_dir, f)).read()
    return out


def _conformance_specs():
    """Per-op sweep specs from tests/conformance_tables.py +
    tests/op_smoke_table.py — machine-true: tests/test_op_conformance.py
    and tests/test_op_smoke.py parametrize FROM this manifest and resolve
    every listed op in those same tables, so a manifest `conformance`
    entry implies the op is executed by the suite."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    try:
        import conformance_tables
        import op_smoke_table

        out = conformance_tables.specs()
        for n in op_smoke_table.SMOKE_OPS:
            out.setdefault(n, {"kind": "smoke", "grad": False})
        return out
    finally:
        sys.path.pop(0)


def _tested_by(name, sources):
    """Test files that invoke the op (call syntax `name(` or exact quoted
    name — tighter than the old bare-substring heuristic)."""
    pat = re.compile(
        rf"(?:\b{re.escape(name)}\s*\(|\.{re.escape(name)}\b"
        rf"|[\"']{re.escape(name)}[\"'])")
    return [f for f, src in sources.items() if pat.search(src)]


def generate():
    import paddle_tpu as P

    tensor_api = reference_tensor_api()
    yaml_ops = reference_yaml_ops()
    all_names = sorted(set(tensor_api) | set(yaml_ops))
    sources = _test_sources()
    conf_specs = _conformance_specs()

    entries = []
    for name in all_names:
        where = _resolve(name)
        internal = name in INTERNAL_OPS and name not in tensor_api
        conf = conf_specs.get(name)
        if conf is None and name.endswith("_") \
                and (conf_specs.get(name[:-1]) or {}).get("kind") in (
                    "unary", "binary", "comparison", "int_binary",
                    "int_unary") \
                and where is not None:
            # inplace twin of a sweep-covered base op: executed by
            # test_op_conformance.py::test_inplace_variant_matches_outofplace
            conf = {"kind": "inplace", "grad": False,
                    "base": name[:-1]}
        tested_by = _tested_by(name, sources)
        entries.append({
            "name": name,
            "present": where is not None,
            "where": where,
            "internal": internal,
            "tensor_method": hasattr(P.Tensor, name),
            # ops.yaml-parity metadata (VERDICT r2 task 7):
            # conformance: sweep kind + whether its numeric-grad check runs
            "conformance": conf,
            # grad: "checked" only when the sweep actually grad-checks it
            "grad": "checked" if conf and conf.get("grad") else None,
            # inplace: the reference's inplace-map bit — `<name>_` resolves
            "inplace": _resolve(name + "_") is not None,
            # spmd: jnp-backed ops shard via XLA/GSPMD propagation (the
            # build's spmd rule registry IS the compiler)
            "spmd": "xla-propagation" if where is not None else None,
            "tested_by": tested_by,
            "sources": [s for s, names in (("tensor_api", tensor_api),
                                           ("phi_yaml", yaml_ops))
                        if name in names],
        })
    counted = [e for e in entries if not e["internal"]]
    present = sum(e["present"] for e in counted)
    # enforcement: a present op with neither a conformance entry nor any
    # test invoking it is UNPROVEN — regeneration fails on it (task 7
    # "present => conformance-tested is machine-true")
    unproven = sorted(
        e["name"] for e in counted
        if e["present"] and not e["conformance"] and not e["tested_by"])
    manifest = {
        "total": len(counted),
        "internal": len(entries) - len(counted),
        "present": present,
        "coverage_pct": round(100.0 * present / max(1, len(counted)), 1),
        "unproven": unproven,
        "ops": entries,
    }
    return manifest


OP_TABLE_PATH = os.path.join(REPO, "paddle_tpu", "ops", "_op_table.py")


def emit_op_table(manifest) -> str:
    """Render paddle_tpu/ops/_op_table.py FROM the manifest (VERDICT r4
    Next #7: the schema must be generative, not audit-only — reference
    role `paddle/phi/api/yaml/generator/api_base.py:1300`, where ops.yaml
    *produces* the C++ API surface). The emitted table is imported by the
    package and re-validated by tests, so drift breaks the build in both
    directions: a manifest op that stops resolving fails `validate()`, and
    a hand edit to either file fails the regeneration-equality test."""
    present = [e for e in manifest["ops"] if e["present"]]
    by_where: dict = {}
    for e in present:
        by_where.setdefault(e["where"], []).append(e["name"])
    lines = [
        '"""AUTO-GENERATED from OPS_MANIFEST.json by',
        'tools/gen_op_manifest.py --emit.  DO NOT EDIT BY HAND —',
        'regenerate with:  python tools/gen_op_manifest.py --emit',
        '',
        'Generated op table (`ops.yaml` generator role): the public op',
        'surface, Tensor-method set, grad-checked set, and inplace pairs,',
        'emitted FROM the manifest so the schema is the single source of',
        'truth in both directions (tests/test_manifest_ops.py).',
        '"""',
        "",
    ]

    def wrap(items, indent):
        out = []
        row = indent
        for it in sorted(items):
            piece = f'"{it}", '
            if len(row) + len(piece) > 78:
                out.append(row.rstrip())
                row = indent
            row += piece
        if row.strip():
            out.append(row.rstrip())
        return out

    def tup(name, items):
        return [f"{name} = ("] + wrap(items, "    ") + [")"]

    lines += ["# op name -> namespace that must resolve it",
              "PUBLIC_OPS = {"]
    for where in sorted(by_where):
        lines.append(f'    "{where}": (')
        lines += wrap(by_where[where], "        ")
        lines.append("    ),")
    lines.append("}")
    lines.append("")
    lines += tup("TENSOR_METHODS",
                 [e["name"] for e in present if e["tensor_method"]])
    lines.append("")
    lines += tup("GRAD_CHECKED",
                 [e["name"] for e in present if e["grad"] == "checked"])
    lines.append("")
    lines += tup("INPLACE_OPS",
                 [e["name"] for e in present if e["inplace"]])
    lines += [
        "",
        "",
        "def validate():",
        '    """Resolve the generated surface against the live package;',
        '    returns a list of violations (empty == green)."""',
        "    import importlib",
        "",
        "    problems = []",
        "    for where, names in PUBLIC_OPS.items():",
        "        mod = importlib.import_module(where)",
        "        for n in names:",
        "            if getattr(mod, n, None) is None:",
        '                problems.append(f"{where}.{n} missing")',
        "    from paddle_tpu.core.tensor import Tensor",
        "",
        "    for n in TENSOR_METHODS:",
        "        if not hasattr(Tensor, n):",
        '            problems.append(f"Tensor.{n} missing")',
        "    import paddle_tpu as P",
        "",
        "    for n in INPLACE_OPS:",
        "        t = n + '_'",
        "        if (getattr(P, t, None) is None and not hasattr(Tensor, t)",
        "                and getattr(P.nn.functional, t, None) is None):",
        '            problems.append(f"inplace twin {t} missing")',
        "    return problems",
        "",
    ]
    return "\n".join(lines)


OPS_DOC_PATH = os.path.join(REPO, "docs", "OPS.md")


def emit_ops_doc(manifest) -> str:
    """Render docs/OPS.md from the manifest: the public op surface with
    namespace, grad-check status, inplace twin, and test coverage — the
    doc-stub half of the ops.yaml generator role."""
    lines = [
        "<!-- AUTO-GENERATED from OPS_MANIFEST.json by",
        "     tools/gen_op_manifest.py --emit.  DO NOT EDIT BY HAND. -->",
        "",
        "# Op surface (generated)",
        "",
        f"{manifest['present']}/{manifest['total']} reference ops present "
        f"({manifest['coverage_pct']}% of the applicable surface; "
        f"{manifest['internal']} kernel-internal names subsumed by "
        "XLA/the fused train step — see tools/gen_op_manifest.py "
        "INTERNAL_OPS for the per-group story).",
        "",
        "| op | namespace | Tensor method | grad-checked | inplace twin "
        "| tests |",
        "|---|---|---|---|---|---|",
    ]
    for e in manifest["ops"]:
        if not e["present"]:
            continue
        lines.append(
            f"| `{e['name']}` | {e['where']} "
            f"| {'yes' if e['tensor_method'] else ''} "
            f"| {'yes' if e['grad'] == 'checked' else ''} "
            f"| {'yes' if e['inplace'] else ''} "
            f"| {len(e['tested_by'])} |")
    missing = [e["name"] for e in manifest["ops"]
               if not e["present"] and not e["internal"]]
    if missing:
        lines += ["", "Missing (tracked): " +
                  " ".join(f"`{n}`" for n in missing)]
    lines.append("")
    return "\n".join(lines)


def main():
    out_path = os.path.join(REPO, "OPS_MANIFEST.json")
    if "--emit" in sys.argv:
        # emit the generated artifacts from the RECORDED manifest (the
        # committed schema — no paddle_tpu import needed); --check guards
        # recorded-vs-fresh separately
        with open(out_path) as f:
            recorded = json.load(f)
        with open(OP_TABLE_PATH, "w") as f:
            f.write(emit_op_table(recorded))
        print(f"wrote {OP_TABLE_PATH}")
        with open(OPS_DOC_PATH, "w") as f:
            f.write(emit_ops_doc(recorded))
        print(f"wrote {OPS_DOC_PATH}")
        return 0
    manifest = generate()
    if manifest["unproven"]:
        print(f"UNPROVEN present ops (no conformance entry, no test "
              f"invokes them): {manifest['unproven']}")
        print("add a conformance_tables.py spec or a test before "
              "regenerating the manifest")
        return 1
    if "--check" in sys.argv:
        with open(out_path) as f:
            old = json.load(f)
        if old["present"] > manifest["present"]:
            print(f"coverage regressed: {old['present']} -> "
                  f"{manifest['present']}")
            return 1
        drift = [e["name"] for e, o in zip(manifest["ops"], old["ops"])
                 if e != o]
        if drift:
            print(f"manifest drift in: {drift[:20]} — regenerate with "
                  "python tools/gen_op_manifest.py")
            return 1
        print(f"manifest OK: {manifest['present']}/{manifest['total']}")
        return 0
    with open(out_path, "w") as f:
        json.dump(manifest, f, indent=1)
    missing = [e["name"] for e in manifest["ops"]
               if not e["present"] and not e["internal"]]
    print(f"coverage: {manifest['present']}/{manifest['total']} "
          f"({manifest['coverage_pct']}%); missing {len(missing)}:")
    print(" ".join(missing))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    raise SystemExit(main())
