"""Chip-free memory evidence: XLA buffer-assignment A/B for the two
memory features the 1.3B/6.7B targets depend on (VERDICT r4 Next #4) —

  * fused LM-head + cross-entropy (cut-CE): the [B,S,V] logits and their
    cotangent never materialize (`models/gpt.py` `_fused_linear_ce`)
  * recompute (remat): activations rematerialized in backward

Method: compile the full train step (fwd+bwd+AdamW) and read
`compiled.memory_analysis()` — XLA's buffer assignment for the program
that would run. `temp_size_in_bytes` is the activation/workspace pool;
arguments/outputs are the (donated) params+optimizer state. These are
compiler-assigned sizes, not device telemetry: exact for the compiled
executable on the backend it was compiled for (here CPU; TPU assignment
differs in layout padding, not in whether a [B,S,V] logits buffer
exists). The chip-measured numbers land in chip_session's
memory_headroom phase; this report is the always-available A/B.

Run: python tools/memory_report.py          # prints a table + JSON lines
"""
from __future__ import annotations

import json
import sys

sys.path.insert(0, ".")


def _build_lowered(cfg_kwargs, batch, seq):
    """One GPT train step lowered for (batch, seq); returns
    (lowered, model) — the shared setup for every report below."""
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    P.seed(0)
    cfg = GPTConfig(**cfg_kwargs)
    inner = GPTForCausalLM(cfg)
    model = fleet.distributed_model(inner)
    opt = fleet.distributed_optimizer(P.optimizer.AdamW(
        parameters=model.parameters(), learning_rate=1e-4))
    step = model.build_train_step(
        opt, GPTPretrainingCriterion(model=inner), amp_dtype="bfloat16")
    rs = np.random.RandomState(0)
    ids = P.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
    labels = P.to_tensor(
        rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
    return step.lower(ids, labels), model


def step_memory(cfg_kwargs, batch, seq):
    """Compile one GPT train step; return XLA memory analysis numbers."""
    import numpy as np

    lowered, model = _build_lowered(cfg_kwargs, batch, seq)
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    out = {"params": n_params,
           "temp_mb": round(ma.temp_size_in_bytes / 2**20, 1),
           "arg_mb": round(ma.argument_size_in_bytes / 2**20, 1),
           "out_mb": round(ma.output_size_in_bytes / 2**20, 1),
           "alias_mb": round(ma.alias_size_in_bytes / 2**20, 1)}
    # peak live ≈ args (params+opt, donated/aliased) + temps
    out["peak_mb"] = round(out["arg_mb"] + out["temp_mb"]
                           - out["alias_mb"], 1)
    return out


def baseline_config_memory(which="1p3b"):
    """Real-size feasibility evidence for the BASELINE configs without
    hardware: compile the ACTUAL 1.3B / 6.7B hybrid train step (real
    parameter arrays, bf16 AMP, fused head+CE, remat) on the virtual
    8-device CPU mesh and read XLA's buffer assignment. Under SPMD the
    compiled program is per-device, so `memory_analysis()` numbers are
    PER-DEVICE bytes — the "does BASELINE config N fit a 16 GiB v5e /
    95 GiB v5p chip" check. Caveats (stated in the output): CPU
    assignment differs from TPU in layout padding, and XLA:CPU does not
    realize remat's temp-pool win, so the temp number is an upper bound.

      1p3b:      BASELINE config 2 — GPT-1.3B data-parallel, ZeRO
                 stage-2 (dp=8, global batch 8 x seq 2048)
      6p7b:      BASELINE config 3 — GPT-6.7B tensor-parallel mp=4
                 (x dp=2, stage-2 over the dp axis). WARNING: the full
                 model's ~81 GB f32 state + compile workspace OOMs a
                 125 GB host — use 6p7b_half there
      6p7b_half: config 3 at 16 of 32 layers, full width (the mp=4
                 sharding of h=4096 layers is what's being validated;
                 depth scales the rest linearly)
    """
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.models.gpt import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_1p3b, gpt_6p7b,
    )

    extrap = None
    if which == "1p3b":
        cfg = gpt_1p3b(fused_head_ce=True, recompute=True, dropout=0.0)
        hybrid = {"dp_degree": 8, "mp_degree": 1, "pp_degree": 1,
                  "sep_degree": 1, "sharding_degree": 8}
        batch, seq = 8, 2048
    elif which in ("6p7b", "6p7b_half"):
        cfg = gpt_6p7b(fused_head_ce=True, recompute=True, dropout=0.0)
        hybrid = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1,
                  "sep_degree": 1, "sharding_degree": 2}
        batch, seq = 2, 2048
        if which == "6p7b_half":
            cfg.num_layers = 16  # ffn width depends only on
            # hidden_size — the post-init depth override keeps every
            # other literal shared with the full preset
            extrap = ("16 of 32 layers at full width (tied embeddings: "
                      "3.44B of the full 6.66B params): per-layer temp "
                      "and arg bytes scale linearly in depth — double "
                      "the layer-proportional parts for the full model")
    else:
        raise ValueError(
            f"unknown baseline config {which!r}: expected one of "
            "'1p3b', '6p7b', '6p7b_half'")
    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = hybrid
    strategy.sharding = True
    strategy.sharding_configs = {"stage": 2}
    fleet.init(is_collective=True, strategy=strategy)
    P.seed(0)
    inner = GPTForCausalLM(cfg)
    model = fleet.distributed_model(inner)
    opt = fleet.distributed_optimizer(P.optimizer.AdamW(
        parameters=model.parameters(), learning_rate=1e-4))
    step = model.build_train_step(
        opt, GPTPretrainingCriterion(model=inner), amp_dtype="bfloat16")
    rs = np.random.RandomState(0)
    ids = P.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
    labels = P.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)),
                         "int32")
    compiled = step.lower(ids, labels).compile()
    ma = compiled.memory_analysis()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    gib = 2**30
    out = {"config": which, "params": n_params, "hybrid": hybrid,
           "batch": batch, "seq": seq,
           "per_device_temp_gib": round(ma.temp_size_in_bytes / gib, 2),
           "per_device_arg_gib": round(
               ma.argument_size_in_bytes / gib, 2),
           "per_device_alias_gib": round(ma.alias_size_in_bytes / gib, 2),
           "per_device_peak_gib": round(
               (ma.argument_size_in_bytes + ma.temp_size_in_bytes
                - ma.alias_size_in_bytes) / gib, 2),
           "note": ("per-device XLA buffer assignment on the virtual "
                    "8-device CPU mesh; CPU layouts differ from TPU and "
                    "CPU does not realize remat's temp win — treat as "
                    "an upper bound")}
    if extrap:
        out["extrapolation"] = extrap
    return out


def llama7b_pp4_memory():
    """BASELINE config 4 at REAL width: the LLaMA-7B transformer trunk
    (h=4096, 32 MHA heads, swiglu ffn 11008) pipelined pp=4 through the collective
    tier, 16 of 32 layers (4 per stage; depth scales linearly), fwd+bwd
    with remat, seq 2048, 4 microbatches of batch 2. Abstract lowering:
    stage params enter as ShapeDtypeStructs, so the 3.2B-param trunk
    compiles with only the one prototype block's weights real — per-device numbers
    are XLA's buffer assignment for the program that would run on each
    pipeline stage. Embedding/head/optimizer are excluded (accounted
    analytically in the output: they are static state, not schedule
    memory — the pipeline's memory risk is activations x microbatches).
    """
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    import paddle_tpu as P
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed.pipeline_spmd import spmd_pipeline
    from paddle_tpu.models.llama import LlamaBlock, llama_7b

    cfg = llama_7b()
    pp, per_stage, m, mb, seq = 4, 4, 4, 2, 2048  # depth = pp*per_stage
    P.seed(0)
    proto = LlamaBlock(llama_7b(num_layers=1))  # one real block: treedef
    proto.eval()
    params0, buffers = proto.functional_state()

    def stacked_aval(v):
        # functional_state() hands back raw jax.Arrays
        return jax.ShapeDtypeStruct((pp, per_stage) + tuple(v.shape),
                                    jnp.bfloat16)

    stacked_avals = {k: stacked_aval(v) for k, v in params0.items()}
    mesh = Mesh(np.array(jax.devices()[:pp]), ("pp",))

    def stage_fn(params, act):
        def body(a, blk):
            with proto.bind_state(blk, buffers):
                return proto(Tensor(a))._value, None

        act, _ = jax.lax.scan(body, act, params)
        return act

    def loss(stacked, x):
        y = spmd_pipeline(stage_fn, stacked, x, mesh=mesh,
                          remat_stage=True)
        return jnp.mean(y.astype(jnp.float32) ** 2)

    x_aval = jax.ShapeDtypeStruct((m, mb, seq, cfg.hidden_size),
                                  jnp.bfloat16)
    compiled = jax.jit(jax.value_and_grad(loss)).lower(
        stacked_avals, x_aval).compile()
    ma = compiled.memory_analysis()
    gib = 2**30
    trunk_params = per_stage * pp * sum(
        int(np.prod(v.shape)) for v in params0.values())
    # static state per stage-device (analytic, bf16 params + f32
    # master + two f32 AdamW moments on the stage's own params)
    per_dev_state = trunk_params // pp * (2 + 4 + 4 + 4) / gib
    return {"config": "llama7b_pp4_half",
            "trunk_params": trunk_params,
            "pp": pp, "layers_per_stage": per_stage,
            "microbatches": m, "micro_batch": mb, "seq": seq,
            "per_device_temp_gib": round(ma.temp_size_in_bytes / gib, 2),
            "per_device_arg_gib": round(
                ma.argument_size_in_bytes / gib, 2),
            "per_device_grad_out_gib": round(
                ma.output_size_in_bytes / gib, 2),
            "analytic_train_state_gib_per_stage": round(per_dev_state, 2),
            "note": ("collective-tier fwd+bwd of the real-width LLaMA-7B "
                     "trunk, 16 of 32 layers, remat per stage; abstract "
                     "lowering (no weights materialized); CPU buffer "
                     "assignment is an upper bound (remat unrealized); "
                     "embedding/head/optimizer excluded from the compiled "
                     "program and accounted analytically"),
            "extrapolation": ("double the layer-proportional parts for "
                              "32 layers: 8 layers/stage at pp=4")}


def main():
    import sys as _sys

    from paddle_tpu.backend_guard import force_cpu_mesh

    if len(_sys.argv) > 1 and _sys.argv[1] == "--baseline":
        force_cpu_mesh(8)
        for which in _sys.argv[2:] or ["1p3b"]:
            if which == "llama7b_pp4_half":
                out = llama7b_pp4_memory()
            else:
                out = baseline_config_memory(which)
            print(json.dumps({"section": "baseline_config_memory",
                              **out}), flush=True)
        return 0

    force_cpu_mesh(1)

    # a shape where the [B,S,V] logits dominate if materialized:
    # 8 x 512 x 50304 f32 logits + cotangent ≈ 1.6 GB
    base = dict(vocab_size=50304, hidden_size=256, num_layers=4,
                num_heads=8, max_seq_len=512)
    batch, seq = 8, 512
    rows = []
    for fused, remat in ((False, False), (True, False), (True, True)):
        cfgkw = dict(base, fused_head_ce=fused, recompute=remat)
        try:
            m = step_memory(cfgkw, batch, seq)
        except Exception as e:  # keep the report robust per-config
            m = {"error": f"{type(e).__name__}: {str(e)[:120]}"}
        row = {"fused_head_ce": fused, "recompute": remat,
               "batch": batch, "seq": seq, **m}
        rows.append(row)
        print(json.dumps(row), flush=True)
    ok = [r for r in rows if "temp_mb" in r]
    if len(ok) >= 2 and not ok[0]["fused_head_ce"] and \
            ok[1]["fused_head_ce"]:
        saved = ok[0]["temp_mb"] - ok[1]["temp_mb"]
        print(f"# cut-CE saves {saved:.0f} MiB of XLA temp buffers "
              f"({ok[0]['temp_mb']:.0f} -> {ok[1]['temp_mb']:.0f} MiB) "
              f"at B{batch} S{seq} V50304", flush=True)

    # remat-policy A/B: XLA:CPU's buffer assignment does NOT realize
    # remat's memory win (temp pools come out identical), so the
    # chip-free evidence here is program STRUCTURE — the backward
    # recomputes forward ops under remat, and dots_no_batch recomputes
    # fewer GEMMs than full remat. The on-chip memory_headroom phase
    # carries the device-memory half.
    deep = dict(vocab_size=1024, hidden_size=512, num_layers=8,
                num_heads=8, max_seq_len=512, fused_head_ce=True)
    for rc, pol in ((False, None), (True, None), (True, "dots_no_batch")):
        try:
            lowered, _ = _build_lowered(
                dict(deep, recompute=rc, recompute_policy=pol), batch, seq)
            txt = lowered.as_text()
            m = {"lowered_lines": len(txt.splitlines()),
                 "dot_generals": txt.count("dot_general")}
        except Exception as e:
            m = {"error": f"{type(e).__name__}: {str(e)[:120]}"}
        row = {"shape": "deep-h512-L8", "recompute": rc,
               "policy": pol or ("full" if rc else None), **m}
        print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
