"""Fleet telemetry aggregator: merge per-process dumps into one story.

Input: a directory of `telemetry_<host>_<pid>[_rN].jsonl` streams
written by `observability.export.TelemetryExporter` (serving replicas,
training ranks, clients — any process that attached the stack).

Outputs:
  * **merged Perfetto timeline** (`--out merged.json`): every process's
    span/instant/counter events on its own pid track (process_name =
    `host:pid[:rN]`), timestamps re-based onto ONE wall clock via each
    tracer's `trace_wall_epoch`, flight events as instants — so a
    request that crossed a client→server hop shows its client attempt
    span and its server queue/admission/predict/serialize phase spans
    in one view, joined by the `request_id` span arg.
  * **fleet rollup** (`--rollup rollup.json`, also printed): counters
    summed across processes, histograms merged bucket-by-bucket (the
    fixed shared ladder makes this a plain sum) with fleet-wide
    interpolated p50/p95/p99, gauges kept per process, and SLO reports
    combined per endpoint (window counts summed, burn rate recomputed
    against the declared objective).  Timeseries frames (ISSUE 15)
    merge twice: per-process series re-assembled from the incremental
    dumps (`timeseries.per_process`), and a fleet-SUM step function
    per name (`timeseries.fleet`); in the merged timeline they render
    as Perfetto counter tracks (`"ph": "C"`).  Each process's newest
    `request_timelines` summaries ride along under their ident.  A `per_process` section groups
    each process's serving/engine/router gauges under its
    `host:pid[:rN]` ident — the per-replica serving view (ISSUE 9:
    replica ranks ride the dump filename, so a fleet's rollup shows
    each replica's admission and engine state side by side with the
    router's `router.replicas{state}` gauges).  When dumps carry a
    `tenants` ledger snapshot (ISSUE 16), the rollup adds a `tenants`
    section: each process's last snapshot under `per_process`, plus a
    `fleet` Space-Saving merge (matched tenants summed, union
    truncated back to K by folding the smallest into `~other` — the
    conservation invariant survives the merge).

Exit codes: 0 ok, 1 usage/IO error, 2 schema errors in any stream
(same discipline as tools/analyze_chip_log.py).

stdlib-only: file-loads the stdlib-by-contract observability modules
(export, metrics) instead of importing jax-heavy paddle_tpu.

Usage:
  python tools/telemetry_agg.py DUMP_DIR --out merged.json
      [--rollup rollup.json] [--quiet]
"""
from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_obs_module(name):
    path = os.path.join(REPO, "paddle_tpu", "observability", name + ".py")
    spec = importlib.util.spec_from_file_location("_" + name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_export = _load_obs_module("export")
_lifecycle_mod = _load_obs_module("lifecycle")
_metrics_mod = _load_obs_module("metrics")
_tenant_mod = _load_obs_module("tenant_ledger")


# ------------------------------ loading ------------------------------

def load_dumps(dump_dir):
    """[(path, [entries...])] for every telemetry_*.jsonl in dir."""
    out = []
    pattern = os.path.join(dump_dir, "telemetry_*.jsonl")
    for path in sorted(glob.glob(pattern)):
        entries = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entries.append(json.loads(line))
                except ValueError:
                    continue
        out.append((path, entries))
    return out


def _proc_ident(entry):
    ident = f"{entry.get('host', '?')}:{entry.get('pid', '?')}"
    if entry.get("rank") is not None:
        ident += f":r{entry['rank']}"
    return ident


# ------------------------------ merge ------------------------------

def merge_timeline(streams):
    """One Perfetto document from N dump streams.

    Event `ts` values are µs since each process's own tracer epoch; the
    dump's `trace_wall_epoch` says where that epoch sits on the wall
    clock, so shifting by `(wall_epoch - fleet_min_epoch) * 1e6` puts
    every process on one comparable axis.  Flight events carry wall `t`
    directly and shift by the fleet epoch alone."""
    # pass 1: fleet epoch = earliest tracer epoch seen
    epochs = {}
    for _path, entries in streams:
        for e in entries:
            if e.get("phase") != _export.TELEMETRY_PHASE:
                continue
            we = e.get("trace_wall_epoch")
            if isinstance(we, (int, float)):
                ident = _proc_ident(e)
                epochs[ident] = min(epochs.get(ident, we), we)
    t0 = min(epochs.values()) if epochs else 0.0

    events, meta = [], []
    pids = {}       # ident -> synthetic stable pid for the merged doc
    for _path, entries in streams:
        for e in entries:
            if e.get("phase") != _export.TELEMETRY_PHASE:
                continue
            ident = _proc_ident(e)
            if ident not in pids:
                pids[ident] = len(pids) + 1
                meta.append({"name": "process_name", "ph": "M",
                             "pid": pids[ident], "tid": 0,
                             "args": {"name": ident}})
            pid = pids[ident]
            shift_us = (epochs.get(ident, t0) - t0) * 1e6
            for ev in e.get("trace_events") or ():
                if not isinstance(ev, dict):
                    continue
                ev = dict(ev, pid=pid)
                if ev.get("ph") != "M" and isinstance(
                        ev.get("ts"), (int, float)):
                    ev["ts"] = round(ev["ts"] + shift_us, 3)
                events.append(ev)
            for fe in e.get("flight_events") or ():
                if not isinstance(fe, dict) or not fe.get("kind"):
                    continue
                args = {k: v for k, v in fe.items()
                        if k not in ("kind", "t", "seq")}
                ts = (float(fe.get("t", e.get("wall", t0))) - t0) * 1e6
                events.append({"name": str(fe["kind"]), "cat": "flight",
                               "ph": "i", "s": "t",
                               "ts": round(max(ts, 0.0), 3),
                               "pid": pid, "tid": 0, "args": args})
            # timeseries frames (ISSUE 15) → Perfetto COUNTER tracks:
            # each watched name becomes a per-process counter series
            # Perfetto renders as a little area chart above the spans
            ts_block = e.get("timeseries")
            frames = (ts_block.get("frames")
                      if isinstance(ts_block, dict) else None) or ()
            for fr in frames:
                if not isinstance(fr, dict):
                    continue
                wall = fr.get("wall", e.get("wall", t0))
                fts = (float(wall) - t0) * 1e6
                for name, v in (fr.get("values") or {}).items():
                    events.append({"name": str(name), "ph": "C",
                                   "ts": round(max(fts, 0.0), 3),
                                   "pid": pid, "tid": 0,
                                   "args": {"value": v}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms",
            "otherData": {"schema": "telemetry_agg/v1",
                          "processes": {v: k for k, v in pids.items()},
                          "fleet_epoch": t0}}


# ------------------------------ rollup ------------------------------

def collect_timeseries(streams):
    """{ident: {name: [(wall, v), ...]}} — every process's shipped
    sampler frames, concatenated across its dumps (frames are
    incremental by seq, so concatenation replays the whole retained
    series), deduped by seq and sorted by time."""
    out: dict = {}
    for _path, entries in streams:
        for e in entries:
            if e.get("phase") != _export.TELEMETRY_PHASE:
                continue
            ts_block = e.get("timeseries")
            if not isinstance(ts_block, dict):
                continue
            ident = _proc_ident(e)
            proc = out.setdefault(ident, {"_seqs": set(), "series": {}})
            for fr in ts_block.get("frames") or ():
                if not isinstance(fr, dict):
                    continue
                seq = fr.get("seq")
                if seq in proc["_seqs"]:
                    continue  # a re-read dump line must not duplicate
                proc["_seqs"].add(seq)
                wall = float(fr.get("wall", 0.0))
                for name, v in (fr.get("values") or {}).items():
                    proc["series"].setdefault(str(name), []).append(
                        (wall, float(v)))
    series = {}
    for ident, proc in out.items():
        series[ident] = {n: sorted(pts)
                         for n, pts in proc["series"].items()}
    return series


def fleet_timeseries(per_proc, max_points=2048):
    """Fleet-SUM series: for every name, the step-function sum of each
    process's most recent value at each observed wall time (a process
    contributes 0 before its first sample and holds its last value
    after its last).  The queue-depth/token-rate view of the WHOLE
    fleet, bounded to the trailing `max_points` instants."""
    by_name: dict = {}
    for ident, series in per_proc.items():
        for name, pts in series.items():
            by_name.setdefault(name, {})[ident] = pts
    out = {}
    for name, procs in sorted(by_name.items()):
        walls = sorted({w for pts in procs.values() for w, _ in pts})
        walls = walls[-int(max_points):]
        cursors = {ident: 0 for ident in procs}
        latest = {ident: None for ident in procs}
        summed = []
        for w in walls:
            total = 0.0
            for ident, pts in procs.items():
                i = cursors[ident]
                while i < len(pts) and pts[i][0] <= w:
                    latest[ident] = pts[i][1]
                    i += 1
                cursors[ident] = i
                if latest[ident] is not None:
                    total += latest[ident]
            summed.append((round(w, 6), round(total, 6)))
        out[name] = {"wall": [w for w, _ in summed],
                     "v": [v for _, v in summed]}
    return out

def _merge_hist(acc, summ):
    """Accumulate one histogram summary (count/total/min/max + sparse
    bucket counts) into `acc`."""
    acc["count"] = acc.get("count", 0) + int(summ.get("count", 0))
    acc["total"] = acc.get("total", 0.0) + float(summ.get("total", 0.0))
    if "min" in summ:
        acc["min"] = min(acc.get("min", summ["min"]), summ["min"])
    if "max" in summ:
        acc["max"] = max(acc.get("max", summ["max"]), summ["max"])
    buckets = acc.setdefault("buckets", {})
    for le, c in (summ.get("buckets") or {}).items():
        buckets[le] = buckets.get(le, 0) + int(c)
    return acc


def _hist_percentiles(merged):
    """Fleet-wide interpolated percentiles from merged sparse buckets,
    using the shared DEFAULT_BUCKETS ladder."""
    count = merged.get("count", 0)
    buckets = merged.get("buckets") or {}
    if not count or not buckets:
        return {}
    bounds = list(_metrics_mod.DEFAULT_BUCKETS)
    ordered = []
    for i, b in enumerate(bounds):
        c = buckets.get(f"{b:g}", 0)
        if c:
            lo = merged.get("min", 0.0) if i == 0 else bounds[i - 1]
            ordered.append((lo, b, c))
    inf_c = buckets.get("inf", 0)
    if inf_c:
        ordered.append((bounds[-1], merged.get("max", bounds[-1]), inf_c))
    out = {}
    for name, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
        target = q * count
        cum = 0
        val = merged.get("max")
        for lo, hi, c in ordered:
            if cum + c >= target:
                val = lo + (hi - lo) * ((target - cum) / c)
                break
            cum += c
        if val is not None:
            lo_clamp = merged.get("min", val)
            hi_clamp = merged.get("max", val)
            out[name] = round(max(lo_clamp, min(hi_clamp, val)), 6)
    return out


def rollup(streams):
    """Fleet metrics/SLO rollup from the LAST dump of each process
    (dumps are cumulative snapshots — summing all of them would
    multiply-count)."""
    last = {}
    for _path, entries in streams:
        for e in entries:
            if e.get("phase") != _export.TELEMETRY_PHASE:
                continue
            ident = _proc_ident(e)
            prev = last.get(ident)
            if prev is None or e.get("seq", 0) >= prev.get("seq", 0):
                last[ident] = e

    counters: dict = {}
    hists: dict = {}
    gauges: dict = {}
    per_process: dict = {}
    slo_window: dict = {}
    slo_objectives: dict = {}
    for ident, e in sorted(last.items()):
        m = e.get("metrics") or {}
        for k, v in (m.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[k] = counters.get(k, 0) + v
        for k, summ in (m.get("histograms") or {}).items():
            if isinstance(summ, dict):
                _merge_hist(hists.setdefault(k, {}), summ)
        for k, v in (m.get("gauges") or {}).items():
            gauges.setdefault(k, {})[ident] = v
        # the per-replica serving view: this process's fleet-relevant
        # gauges under one key (rank rides the ident suffix)
        serving_view = {
            k: v for k, v in (m.get("gauges") or {}).items()
            if k.startswith(("serving.", "engine.", "router."))}
        if serving_view:
            per_process[ident] = dict(sorted(serving_view.items()))
        slo = e.get("slo")
        if isinstance(slo, dict):
            for ep, rep in (slo.get("endpoints") or {}).items():
                agg = slo_window.setdefault(ep, {
                    "requests": 0, "errors": 0, "errors_by_reason": {},
                    "classes": {}})
                agg["requests"] += int(rep.get("requests", 0))
                agg["errors"] += int(rep.get("errors", 0))
                for reason, c in (rep.get("errors_by_reason")
                                  or {}).items():
                    br = agg["errors_by_reason"]
                    br[reason] = br.get(reason, 0) + int(c)
                if isinstance(rep.get("objective"), dict):
                    slo_objectives[ep] = rep["objective"]
                # per-priority-class rows (ISSUE 18): summed across
                # processes like the endpoint rows, burn recomputed
                # against the CLASS objective (each dump carries it)
                for c, crep in (rep.get("classes") or {}).items():
                    if not isinstance(crep, dict):
                        continue
                    cagg = agg["classes"].setdefault(c, {
                        "requests": 0, "errors": 0,
                        "errors_by_reason": {}})
                    cagg["requests"] += int(crep.get("requests", 0))
                    cagg["errors"] += int(crep.get("errors", 0))
                    for reason, n in (crep.get("errors_by_reason")
                                      or {}).items():
                        cbr = cagg["errors_by_reason"]
                        cbr[reason] = cbr.get(reason, 0) + int(n)
                    if isinstance(crep.get("objective"), dict):
                        slo_objectives[(ep, c)] = crep["objective"]

    for k, h in hists.items():
        h.update(_hist_percentiles(h))
        if h.get("count"):
            h["mean"] = round(h["total"] / h["count"], 6)
    def _slo_row(agg, obj):
        rep = dict(agg)
        if agg["requests"]:
            rep["availability"] = round(
                1.0 - agg["errors"] / agg["requests"], 6)
            if obj and obj.get("error_budget"):
                rep["burn_rate"] = round(
                    (agg["errors"] / agg["requests"])
                    / float(obj["error_budget"]), 4)
        if obj:
            rep["objective"] = obj
        return rep

    slo_out = {}
    for ep, agg in slo_window.items():
        classes = agg.pop("classes", {})
        rep = _slo_row(agg, slo_objectives.get(ep))
        if classes:
            # class rows inherit the endpoint objective when no class
            # objective rode the dumps (same rule as SLOTracker.report)
            rep["classes"] = {
                c: _slo_row(cagg, slo_objectives.get(
                    (ep, c), slo_objectives.get(ep)))
                for c, cagg in sorted(classes.items())}
        slo_out[ep] = rep

    # the time dimension (ISSUE 15): per-process series re-assembled
    # from the incremental frames, plus the fleet-sum step function —
    # counters appear in `timeseries.fleet` only via their sampled
    # values, so the rollup stays a pure function of the dumps
    per_proc_ts = collect_timeseries(streams)
    ts_out = {
        "per_process": {
            ident: {n: {"wall": [round(w, 6) for w, _ in pts],
                        "v": [v for _, v in pts]}
                    for n, pts in sorted(series.items())}
            for ident, series in sorted(per_proc_ts.items())},
        "fleet": fleet_timeseries(per_proc_ts),
    }

    # per-request timelines (ISSUE 15): the newest summaries per
    # process, straight off each process's last dump
    timelines = {}
    for ident, e in sorted(last.items()):
        tls = e.get("request_timelines")
        if isinstance(tls, list) and tls:
            timelines[ident] = tls

    # tenant ledgers (ISSUE 16): each process dumps its FULL ledger
    # snapshot (not incremental), so the last dump per process IS the
    # process's book; the fleet view is a correct Space-Saving merge —
    # matched tenants sum, the union truncates back to K with the
    # smallest folded into `~other`, conservation preserved
    tenants = {}
    per_tenant = {ident: e["tenants"] for ident, e in sorted(last.items())
                  if isinstance(e.get("tenants"), dict)}
    if per_tenant:
        tenants = {"per_process": per_tenant,
                   "fleet": _tenant_mod.merge_snapshots(
                       list(per_tenant.values()))}

    # replica lifecycle (ISSUE 17): each process dumps its FULL phase
    # record (full state, last dump wins — same contract as tenants).
    # A replica dump is its own ledger record; a supervisor dump is a
    # fleet view with joined per-spawn records.  The fleet rollup is
    # phase percentiles across every spawn story seen.
    lifecycle = {}
    per_lc = {ident: e["lifecycle"] for ident, e in sorted(last.items())
              if isinstance(e.get("lifecycle"), dict)}
    if per_lc:
        spawn_records = []
        for rec in per_lc.values():
            if isinstance(rec.get("records"), list):
                spawn_records.extend(
                    r for r in rec["records"] if isinstance(r, dict))
            elif isinstance(rec.get("durations_ms"), dict):
                row = {"phases_ms": dict(rec["durations_ms"])}
                row["phases_ms"]["compile"] = float(
                    rec.get("compile_total_ms", 0.0))
                if "total_ms" in rec:
                    row["total_ms"] = rec["total_ms"]
                spawn_records.append(row)
        lifecycle = {"per_process": per_lc,
                     "fleet": _lifecycle_mod.rollup_records(
                         spawn_records)}

    out = {"schema": "telemetry_rollup/v1",
            "processes": sorted(last),
            "counters": dict(sorted(counters.items())),
            "histograms": dict(sorted(hists.items())),
            "gauges": dict(sorted(gauges.items())),
            "per_process": dict(sorted(per_process.items())),
            "timeseries": ts_out,
            "request_timelines": timelines,
            "slo": slo_out}
    if tenants:
        out["tenants"] = tenants
    if lifecycle:
        out["lifecycle"] = lifecycle
    return out


# ------------------------------ CLI ------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="telemetry_agg", description=__doc__.splitlines()[0])
    ap.add_argument("dump_dir", help="directory of telemetry_*.jsonl")
    ap.add_argument("--out", metavar="MERGED",
                    help="write the merged Perfetto timeline here")
    ap.add_argument("--rollup", metavar="OUT",
                    help="write the fleet rollup JSON here")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the rollup pretty-print")
    args = ap.parse_args(argv)

    streams = load_dumps(args.dump_dir)
    if not streams:
        print(f"telemetry_agg: no telemetry_*.jsonl in {args.dump_dir}",
              file=sys.stderr)
        return 1

    errors = []
    for path, entries in streams:
        for err in _export.validate_telemetry_stream(entries):
            errors.append(f"{os.path.basename(path)}: {err}")
    if errors:
        print(f"telemetry_agg: {len(errors)} schema error(s):",
              file=sys.stderr)
        for err in errors[:20]:
            print(f"  - {err}", file=sys.stderr)

    if args.out:
        doc = merge_timeline(streams)
        d = os.path.dirname(os.path.abspath(args.out))
        os.makedirs(d, exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(doc, f, default=str)
        n_proc = len(doc["otherData"]["processes"])
        print(f"telemetry_agg: merged {len(doc['traceEvents'])} events "
              f"from {n_proc} process(es) -> {args.out}")

    roll = rollup(streams)
    if args.rollup:
        d = os.path.dirname(os.path.abspath(args.rollup))
        os.makedirs(d, exist_ok=True)
        with open(args.rollup, "w") as f:
            json.dump(roll, f, indent=2, sort_keys=True, default=str)
        print(f"telemetry_agg: rollup -> {args.rollup}")
    if not args.quiet:
        print(json.dumps(roll, indent=2, sort_keys=True, default=str))
    return 2 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
