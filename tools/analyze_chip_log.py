"""Render tools/chip_session_log.jsonl into a markdown digest.

The watcher auto-commits raw capture data; this turns it into the
PERF.md-style tables: one section per phase, latest entry per unique
key, errors listed last. Run: python tools/analyze_chip_log.py
"""
from __future__ import annotations

import json
import os
import sys
from collections import OrderedDict

LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "chip_session_log.jsonl")


def load(path=LOG):
    entries = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entries.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return entries


def digest(entries):
    phases: "OrderedDict[str, OrderedDict]" = OrderedDict()
    errors = []
    for e in entries:
        ph = e.get("phase", "?")
        if "error" in e:
            errors.append((ph, e.get("t", ""), e["error"]))
            continue
        if e.get("done"):
            continue
        # latest entry wins per (phase, discriminator): sweeps key on
        # blocks/shape/variant/rung/model, single-result phases on phase
        disc = tuple(str(e.get(k)) for k in
                     ("blocks", "shape", "variant", "rung", "model",
                      "metric", "batch") if k in e)
        phases.setdefault(ph, OrderedDict())[disc] = e
    lines = []
    for ph, rows in phases.items():
        lines.append(f"\n## {ph}  ({len(rows)} rows)\n")
        for disc, e in rows.items():
            body = {k: v for k, v in e.items()
                    if k not in ("phase", "t")}
            lines.append(f"- `{e.get('t', '')}` "
                         + json.dumps(body, default=str))
    if errors:
        lines.append(f"\n## errors ({len(errors)})\n")
        for ph, t, err in errors[-30:]:
            lines.append(f"- `{t}` **{ph}**: {err[:200]}")
    return "\n".join(lines) or "(log empty)"


if __name__ == "__main__":
    path = sys.argv[1] if len(sys.argv) > 1 else LOG
    print(digest(load(path)))
