"""Render tools/chip_session_log.jsonl into a markdown digest.

The watcher auto-commits raw capture data; this turns it into the
PERF.md-style tables: one section per phase, latest entry per unique
key, errors listed last.  `step_stats` entries (the observability
StepTimer stream, docs/OBSERVABILITY.md) get schema validation plus a
per-run summary (compile ledger vs steady walls, tokens/s, MFU) instead
of the latest-entry-wins table; `trace_event` entries (span-tracer
`dump_jsonl` streams) get schema validation plus an event/span digest;
`telemetry_dump` entries (the per-process exporter streams,
observability/export.py) get schema validation plus a per-process dump
digest.  Exit is non-zero on any schema error in any stream (the CI
hook).
Run: python tools/analyze_chip_log.py [log.jsonl]
"""
from __future__ import annotations

import importlib.util
import json
import os
import sys
from collections import OrderedDict

LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "chip_session_log.jsonl")


def _load_obs_module(name):
    """File-load an observability module (stdlib-only by contract) so
    this tool works without importing jax-heavy paddle_tpu."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "paddle_tpu", "observability",
                        name + ".py")
    spec = importlib.util.spec_from_file_location("_" + name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_step_stats = _load_obs_module("step_stats")
_trace = _load_obs_module("trace")
_export = _load_obs_module("export")


def load(path=LOG):
    entries = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entries.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return entries


def digest(entries, schema_errors=None, trace_errors=None,
           telemetry_errors=None):
    phases: "OrderedDict[str, OrderedDict]" = OrderedDict()
    errors = []
    step_entries = []
    trace_entries = []
    telemetry_entries = []
    for e in entries:
        ph = e.get("phase", "?")
        if "error" in e:
            errors.append((ph, e.get("t", ""), e["error"]))
            continue
        if ph == _step_stats.STEP_PHASE:
            step_entries.append(e)
            continue
        if ph == _trace.TRACE_PHASE:
            trace_entries.append(e)
            continue
        if ph == _export.TELEMETRY_PHASE:
            telemetry_entries.append(e)
            continue
        if e.get("done"):
            continue
        # latest entry wins per (phase, discriminator): sweeps key on
        # blocks/shape/variant/rung/model, single-result phases on phase
        disc = tuple(str(e.get(k)) for k in
                     ("blocks", "shape", "variant", "rung", "model",
                      "metric", "batch") if k in e)
        phases.setdefault(ph, OrderedDict())[disc] = e
    lines = []
    for ph, rows in phases.items():
        lines.append(f"\n## {ph}  ({len(rows)} rows)\n")
        for disc, e in rows.items():
            body = {k: v for k, v in e.items()
                    if k not in ("phase", "t")}
            lines.append(f"- `{e.get('t', '')}` "
                         + json.dumps(body, default=str))
    if step_entries:
        lines.append(f"\n## step_stats  ({len(step_entries)} records)\n")
        if schema_errors is None:
            schema_errors = _step_stats.validate_stream(step_entries)
        if schema_errors:
            lines.append(f"**schema errors ({len(schema_errors)}):**")
            for err in schema_errors[:20]:
                lines.append(f"- {err}")
        for run_id, s in _step_stats.summarize_stream(step_entries).items():
            lines.append(f"- **{run_id}**: " + json.dumps(s, default=str))
    if trace_entries:
        lines.append(f"\n## trace_events  ({len(trace_entries)} events)\n")
        if trace_errors is None:
            trace_errors = _trace.validate_trace_stream(trace_entries)
        if trace_errors:
            lines.append(f"**schema errors ({len(trace_errors)}):**")
            for err in trace_errors[:20]:
                lines.append(f"- {err}")
        s = _trace.summarize_trace_stream(trace_entries)
        lines.append("- " + json.dumps(s, default=str))
    if telemetry_entries:
        lines.append(f"\n## telemetry_dumps  ({len(telemetry_entries)} "
                     f"dumps)\n")
        if telemetry_errors is None:
            telemetry_errors = _export.validate_telemetry_stream(
                telemetry_entries)
        if telemetry_errors:
            lines.append(f"**schema errors ({len(telemetry_errors)}):**")
            for err in telemetry_errors[:20]:
                lines.append(f"- {err}")
        for ident, s in sorted(_export.summarize_telemetry_stream(
                telemetry_entries).items()):
            lines.append(f"- **{ident}**: " + json.dumps(s, default=str))
    if errors:
        lines.append(f"\n## errors ({len(errors)})\n")
        for ph, t, err in errors[-30:]:
            lines.append(f"- `{t}` **{ph}**: {err[:200]}")
    return "\n".join(lines) or "(log empty)"


def main(argv):
    path = argv[1] if len(argv) > 1 else LOG
    entries = load(path)
    # validate once; digest renders the same result and the exit code
    # makes a corrupt step-stats or trace stream fail loudly in CI
    errors = _step_stats.validate_stream(entries)
    trace_errors = _trace.validate_trace_stream(entries)
    telemetry_errors = _export.validate_telemetry_stream(entries)
    print(digest(entries, schema_errors=errors, trace_errors=trace_errors,
                 telemetry_errors=telemetry_errors))
    return 1 if (errors or trace_errors or telemetry_errors) else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
