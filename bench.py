"""Single-chip GPT pretrain throughput benchmark.

Prints ONE JSON line (last line of stdout):
    {"metric", "value", "unit", "vs_baseline", ...}
Metric: tokens/sec/chip on a GPT-125M-shape training step (fwd+bwd+AdamW),
bf16 compute. vs_baseline = achieved MFU / 0.45 (the BASELINE.md north-star
MFU target; the reference publishes no absolute numbers — BASELINE.md).

Backend hardening (VERDICT.md round-1 task 1): the environment's TPU PJRT
plugin can fail or hang at init when its tunnel is down. The default
backend is therefore probed in a watchdog subprocess first; on probe
failure — or on any TPU-side crash mid-run — the benchmark re-runs as a
CPU proxy (fresh subprocess, `--force-cpu`) and still emits the JSON line
with ``"degraded": true``. This script always produces a parseable result.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

_ACCEL_PLATFORMS = ("tpu", "axon")

_TELEMETRY_FLAG = "--telemetry"


def _telemetry_requested() -> bool:
    return _TELEMETRY_FLAG in sys.argv[1:]


def _attach_telemetry():
    """Enable the observability stack for this bench process.  The
    metrics snapshot is embedded in the emitted bench JSON
    (`"telemetry"` key), so every BENCH_*.json line carries its own
    provenance: which flash tiers actually dispatched, autotune
    hits/misses, retraces, per-step walls — the antidote to round-5's
    "stale reused number with no provenance" headline."""
    from paddle_tpu import observability as obs

    obs.attach()
    return obs


def run_bench(degraded: bool = False, note: str = "",
              telemetry: bool = False) -> dict:
    import jax

    import paddle_tpu as P
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    platform = jax.devices()[0].platform
    on_tpu = platform in _ACCEL_PLATFORMS

    # GPT-125M shape on TPU; tiny proxy on CPU so the script always
    # completes. fused_head_ce: the LM-head projection fuses into the
    # chunked CE — the [B,S,V] logits (~3.3 GB bf16 at batch 32, plus
    # their cotangent) never materialize; identical numerics (tested)
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=1024,
                        fused_head_ce=True)
        batch_candidates, seq, iters = [64, 32, 16, 8], 1024, 20
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128, fused_head_ce=True)
        batch_candidates, seq, iters = [2], 128, 3

    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                               "sep_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)

    trace_dir = os.environ.get("BENCH_XPROF_DIR")

    obs = timer = None
    if telemetry:
        obs = _attach_telemetry()

    rs = np.random.RandomState(0)
    tps = None
    model = opt = crit = step = ids = labels = loss = None
    last_exc = None
    for batch in batch_candidates:  # biggest batch that fits wins (MXU util)
        # release the previous attempt's device buffers BEFORE reallocating
        model = opt = crit = step = ids = labels = loss = None
        import gc

        gc.collect()
        try:
            # fresh model/opt/step per attempt: a failed donated step leaves
            # state unusable.  The StepTimer is fresh per attempt too —
            # a failed larger-batch attempt's walls must not pollute the
            # winning batch's telemetry summary (tokens_per_step would
            # misprice them)
            if obs is not None:
                timer = obs.StepTimer(run_id=f"bench_gpt125m_b{batch}",
                                      sink=os.environ.get("BENCH_STEP_LOG"))
            P.seed(0)
            inner = GPTForCausalLM(cfg)
            model = fleet.distributed_model(inner)
            opt = fleet.distributed_optimizer(
                P.optimizer.AdamW(parameters=model.parameters(),
                                  learning_rate=1e-4))
            crit = GPTPretrainingCriterion(model=inner)
            step = model.build_train_step(opt, crit, amp_dtype="bfloat16")
            ids = P.to_tensor(
                rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
            labels = P.to_tensor(
                rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
            # warmup/compile — two calls: the first call's inputs are fresh
            # device_puts; the second proves the steady-state executable is
            # reused (train_step pins state shardings so there is no
            # second-call retrace).  Under --telemetry the first wall is
            # the compile-ledger entry (trace+compile+step), and the
            # input upload bytes are the host->device transfer estimate.
            t_first = time.perf_counter()
            loss = step(ids, labels)
            loss.block_until_ready()
            if timer is not None:
                timer.tokens_per_step = batch * seq
                timer.record(time.perf_counter() - t_first,
                             compile_step=True,
                             transfer_bytes=2 * batch * seq * 4)
            loss = step(ids, labels)
            loss.block_until_ready()

            # multi-step program: all timed steps run inside ONE compiled
            # lax.scan, so per-dispatch host/tunnel gaps (measured ~44 ms
            # IDLE per step, PERF.md) are out of the loop entirely
            losses = step.run_steps(ids, labels, repeat=iters)  # warmup
            float(np.asarray(losses._value[-1]))

            if trace_dir:
                jax.profiler.start_trace(trace_dir)
            try:
                # Timing: dispatch the N-step program once, then FETCH the
                # final loss. A D2H value read is the only true
                # synchronization through this PJRT tunnel —
                # block_until_ready returns before chained device work has
                # run (reads 10-50x too fast, physically impossible MFU).
                # The last loss depends on every prior step's param update,
                # so the fetch waits for the whole scan.
                t0 = time.perf_counter()
                losses = step.run_steps(ids, labels, repeat=iters)
                final_loss = float(np.asarray(losses._value[-1]))
                dt = time.perf_counter() - t0
                if timer is not None:
                    # one compiled N-step scan: one record, walls
                    # divided per step
                    timer.record(dt, n_steps=iters)
            finally:
                if trace_dir:
                    jax.profiler.stop_trace()
            if not np.isfinite(final_loss):
                raise RuntimeError(f"non-finite loss {final_loss}")
            tokens = batch * seq * iters
            tps = tokens / dt
            break
        except Exception as e:
            last_exc = e
            print(f"batch={batch} failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
    if tps is None:
        raise RuntimeError("all batch sizes failed") from last_exc

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_token = 6 * n_params  # fwd+bwd matmul flops
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak
    mfu = tps * flops_per_token / peak
    result = {
        "metric": "gpt125m_train_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }
    peak_mem = P.device.max_memory_allocated()
    if peak_mem:
        result["peak_memory_bytes"] = int(peak_mem)
    if degraded or not on_tpu:
        result["degraded"] = True
    if note:
        result["note"] = note
    if timer is not None:
        # MFU rates use the same FLOPs accounting as the headline metric
        timer.flops_per_step = flops_per_token * batch * seq
        timer.peak_flops = peak
        # goodput partition (ISSUE 7): productive step wall vs lost
        # (compile/rollback/retry/drain) from the run's own step
        # records + flight events; gauges land in the metrics snapshot
        # below, rows are emitted for tools/perf_gate.py
        goodput_report = None
        try:
            goodput_report = obs.goodput.from_live(timer)
            obs.goodput.publish(goodput_report)
        except Exception as e:
            print(f"goodput-accounting-failed: {e}", file=sys.stderr)
        result["telemetry"] = {
            "metrics": obs.metrics.snapshot(),
            "step_stats": timer.summary(),
        }
        if goodput_report is not None:
            result["telemetry"]["goodput"] = goodput_report
            for row in obs.goodput.metric_rows(
                    goodput_report,
                    degraded=bool(degraded or not on_tpu)):
                _emit(row)
        # merged Perfetto timeline: the tracer buffer already correlates
        # compile spans (cost_analysis-annotated), flight instants, and
        # step frames — one export IS the merged trace (ISSUE 2
        # acceptance).  Opt-in via env so plain --telemetry runs stay
        # single-file JSON.
        trace_path = os.environ.get("BENCH_TRACE")
        if trace_path:
            try:
                result["trace_file"] = obs.trace.export(trace_path)
            except OSError as e:
                print(f"trace-export-failed: {e}", file=sys.stderr)
    return result


def _bench_vision_model(build_model, metric, flops_per_image,
                        batch_candidates, img_size=224, iters=10,
                        degraded=False) -> dict:
    """Shared secondary-bench body (BASELINE configs 1 and 5): image-model
    train step (fwd+bwd+optimizer, bf16 AMP), chained-fetch timing.
    degraded=True marks the emitted line (CPU-proxy trend data) and the
    caller is expected to shrink batch/iters accordingly."""
    import gc

    import jax

    import paddle_tpu as P
    from paddle_tpu.distributed import fleet, topology

    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    rs = np.random.RandomState(0)
    last_exc = None
    for batch in batch_candidates:
        model = opt = crit = step = None
        gc.collect()
        try:
            P.seed(0)
            model = fleet.distributed_model(build_model())
            opt = fleet.distributed_optimizer(
                P.optimizer.Momentum(parameters=model.parameters(),
                                     learning_rate=1e-3, momentum=0.9))
            crit = P.nn.CrossEntropyLoss()
            step = model.build_train_step(opt, crit, amp_dtype="bfloat16")
            imgs = P.to_tensor(
                rs.randn(batch, 3, img_size, img_size).astype(np.float32))
            labels = P.to_tensor(rs.randint(0, 1000, (batch,)), "int32")
            # scanned multi-step program (one dispatch, repeat= avoids
            # stacking iters copies of the image batch); no single-step
            # warmup — only the scanned program is ever timed, so its
            # compile would be pure waste
            losses = step.run_steps(imgs, labels, repeat=iters)  # warmup
            final = float(np.asarray(losses._value[-1]))
            t0 = time.perf_counter()
            losses = step.run_steps(imgs, labels, repeat=iters)
            final = float(np.asarray(losses._value[-1]))
            dt = time.perf_counter() - t0
            if not np.isfinite(final):
                raise RuntimeError(f"non-finite loss {final}")
            ips = batch * iters / dt
            mfu = ips * flops_per_image / 197e12
            result = {"metric": metric, "value": round(ips, 1),
                      "unit": "images/s",
                      "vs_baseline": round(mfu / 0.45, 4)}
            if degraded:
                result["degraded"] = True
            return result
        except Exception as e:
            last_exc = e
            print(f"{metric}: batch={batch} failed "
                  f"({type(e).__name__}: {e})", file=sys.stderr)
    return {"metric": metric, "value": 0.0, "unit": "images/s",
            "vs_baseline": 0.0, "degraded": True,
            "note": f"failed: {type(last_exc).__name__}: {last_exc}"}


def _bench_decode(degraded: bool) -> dict:
    """Serving decode throughput (VERDICT r3 Next #4): GPT-125M
    static-KV generate(), tokens/s at batch 8."""
    import jax

    import paddle_tpu as P
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    on_tpu = jax.devices()[0].platform in _ACCEL_PLATFORMS
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=512)
        B, S0, NEW = 8, 128, 128
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=64)
        B, S0, NEW = 2, 8, 8
    P.seed(0)
    model = GPTForCausalLM(cfg)
    model.to(dtype="bfloat16")
    model.eval()
    rs = np.random.RandomState(0)
    prompt = P.to_tensor(rs.randint(0, cfg.vocab_size, (B, S0)), "int32")
    out = model.generate(prompt, max_new_tokens=NEW)  # compile+warm
    np.asarray(out._value)
    t0 = time.perf_counter()
    out = model.generate(prompt, max_new_tokens=NEW)
    np.asarray(out._value)
    dt = time.perf_counter() - t0
    result = {"metric": "gpt125m_decode_tokens_per_sec",
              "value": round(B * NEW / dt, 1), "unit": "tokens/s",
              # decode is HBM-bound: score vs streaming the bf16 weights
              # once per token at ~80% of v5e's ~819 GB/s
              "vs_baseline": round(
                  (sum(int(np.prod(p.shape)) for p in model.parameters())
                   * 2 * (NEW / dt) / 1e9) / (0.8 * 819), 4)}
    if degraded or not on_tpu:
        result["degraded"] = True
    return result


def _bench_serving_decode(degraded: bool) -> dict:
    """Multi-client continuous-batching decode (ISSUE 8): N concurrent
    sequences with STAGGERED arrival and MIXED prompt lengths stream
    through the paged-KV `InferenceEngine`; value = total generated
    tokens / wall from first submission to last completion.  The same
    run measures single-stream sequential `generate()` on the same
    model/prompts — the line carries that number and the batching
    speedup, so the claim "continuous batching beats the predictor-lock
    serving loop" ships with its own evidence."""
    import jax

    import paddle_tpu as P
    from paddle_tpu.inference.engine import EngineConfig, InferenceEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    on_tpu = jax.devices()[0].platform in _ACCEL_PLATFORMS
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=512)
        n_clients, new_tokens = 16, 96
        lens = (32, 64, 96, 128)
        # prefix_cache off: this row measures DECODE throughput; warm
        # -prefill compiles inside the timed burst would skew it (the
        # cache has its own serving_prefix_* rows)
        ecfg = EngineConfig(page_size=32, max_slots=8, decode_chunk=8,
                            max_seq_len=512, prefix_cache=False)
        stagger = 0.01
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128)
        n_clients, new_tokens = 8, 24
        lens = (4, 8, 12, 20)
        ecfg = EngineConfig(page_size=8, max_slots=4, decode_chunk=4,
                            max_seq_len=128, prefix_cache=False)
        stagger = 0.002
    P.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, cfg.vocab_size,
                          (lens[i % len(lens)],)).astype(np.int32)
               for i in range(n_clients)]

    # single-stream sequential reference: the predictor-lock serving
    # model — one generate() at a time.  Warm each distinct prompt
    # shape first so compiles stay out of both timings.
    for s0 in sorted({p.size for p in prompts}):
        out = model.generate(P.to_tensor(
            prompts[[p.size for p in prompts].index(s0)][None, :],
            "int32"), max_new_tokens=new_tokens)
        np.asarray(out._value)
    t0 = time.perf_counter()
    seq_tokens = 0
    for p in prompts:
        out = model.generate(P.to_tensor(p[None, :], "int32"),
                             max_new_tokens=new_tokens)
        seq_tokens += np.asarray(out._value).shape[1] - p.size
    seq_dt = time.perf_counter() - t0
    seq_tps = seq_tokens / seq_dt

    # engine warm: compile the prefill buckets + the decode program
    engine = InferenceEngine(model, ecfg)
    engine.generate(prompts[:len(lens)], max_new_tokens=2)

    engine.start()
    handles = []

    t0 = time.perf_counter()
    for p in prompts:           # staggered arrival, mixed lengths
        handles.append(engine.submit(p, max_new_tokens=new_tokens))
        time.sleep(stagger)
    for h in handles:
        h.result(timeout=600.0)
    dt = time.perf_counter() - t0
    engine.stop()
    eng_tokens = sum(len(h.tokens) for h in handles)
    eng_tps = eng_tokens / dt

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    result = {
        "metric": "serving_decode_tokens_per_sec",
        "value": round(eng_tps, 1), "unit": "tokens/s",
        # aggregate decode is HBM-bound like the single-stream line:
        # score vs streaming the bf16 weights once per STEP (batching
        # amortizes the stream across the batch) at ~80% of v5e BW
        "vs_baseline": round(
            (n_params * 2 * (eng_tps / max(1, ecfg.max_slots)) / 1e9)
            / (0.8 * 819), 4),
        "sequential_tokens_per_sec": round(seq_tps, 1),
        "batching_speedup": round(eng_tps / seq_tps, 2),
        "clients": n_clients,
    }
    if degraded or not on_tpu:
        result["degraded"] = True
    return result


def _bench_quantized_decode(degraded: bool) -> list:
    """Quantized-decode tier rows (ISSUE 12): the SAME staggered
    multi-client burst through four engines over one model family —
    bf16 baseline, int8 weight-only, int8 KV pool, and draft-model
    speculative decoding — plus the single-stream sequential reference,
    all measured in the same run.  Emits one gateable row per tier
    carrying the same-run baselines, so every speedup claim ships with
    its own evidence.

    The spec-decode draft here is SYNTHETIC-AGREEING (upper bound): the
    draft is the target's first layer(s) and the target's extra layers
    have their residual projections zeroed, so target ≡ draft bit-exactly
    and every proposal is accepted — the row measures the MECHANICAL
    ceiling of the spec pipeline (pass overhead at acceptance 1.0), with
    `tokens_per_pass` reported so nothing hides.  Real-model acceptance
    depends on the trained draft and is a hardware-window measurement.
    """
    import jax

    import paddle_tpu as P
    from paddle_tpu.inference.engine import EngineConfig, InferenceEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    on_tpu = jax.devices()[0].platform in _ACCEL_PLATFORMS
    if on_tpu:
        dims = dict(vocab_size=50304, hidden_size=768, num_heads=12,
                    max_seq_len=512)
        layers, draft_layers = 12, 2
        n_clients, new_tokens, spec_k = 16, 96, 4
        lens = (32, 64, 96, 128)
        # prefix_cache off: decode-tier rows, same rationale as
        # _bench_serving_decode
        ecfg = dict(page_size=32, max_slots=8, decode_chunk=8,
                    max_seq_len=512, prefix_cache=False)
        stagger = 0.01
    else:
        dims = dict(vocab_size=1024, hidden_size=128, num_heads=4,
                    max_seq_len=128)
        layers, draft_layers = 2, 1
        n_clients, new_tokens, spec_k = 8, 24, 4
        lens = (4, 8, 12, 20)
        ecfg = dict(page_size=8, max_slots=4, decode_chunk=4,
                    max_seq_len=128, prefix_cache=False)
        stagger = 0.002
    P.seed(0)
    model = GPTForCausalLM(GPTConfig(num_layers=layers, **dims))
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()
    # synthetic fully-agreeing draft: copy the shared prefix of the
    # target's weights, zero the target's EXTRA layers' residual
    # projections (out_proj/down_proj weight+bias) — those blocks become
    # exact identities, so target logits == draft logits bit-for-bit
    P.seed(0)
    draft = GPTForCausalLM(GPTConfig(num_layers=draft_layers, **dims))
    if on_tpu:
        draft.to(dtype="bfloat16")
    draft.eval()
    tstate = {n: p for n, p in model.named_parameters()}
    for name, p in draft.named_parameters():
        p.set_value(tstate[name]._value)
    for li in range(draft_layers, layers):
        blk = model.gpt.h[li]
        for lin in (blk.attn.out_proj, blk.mlp.down_proj):
            lin.weight.set_value(np.zeros(lin.weight.shape, np.float32))
            if lin.bias is not None:
                lin.bias.set_value(np.zeros(lin.bias.shape, np.float32))

    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, dims["vocab_size"],
                          (lens[i % len(lens)],)).astype(np.int32)
               for i in range(n_clients)]

    # single-stream sequential reference (the predictor-lock serving
    # model), warmed per prompt shape
    for s0 in sorted({p.size for p in prompts}):
        out = model.generate(P.to_tensor(
            prompts[[p.size for p in prompts].index(s0)][None, :],
            "int32"), max_new_tokens=new_tokens)
        np.asarray(out._value)
    t0 = time.perf_counter()
    seq_tokens = 0
    for p in prompts:
        out = model.generate(P.to_tensor(p[None, :], "int32"),
                             max_new_tokens=new_tokens)
        seq_tokens += np.asarray(out._value).shape[1] - p.size
    seq_tps = seq_tokens / (time.perf_counter() - t0)

    def engine_tps(tier_kw, draft_model=None):
        engine = InferenceEngine(
            model, EngineConfig(**ecfg, **tier_kw),
            draft_model=draft_model)
        engine.generate(prompts[:len(lens)], max_new_tokens=2)  # warm
        steps0 = engine.steps   # warm-up steps stay out of the ratio
        engine.start()
        handles = []
        t0 = time.perf_counter()
        for p in prompts:
            handles.append(engine.submit(p, max_new_tokens=new_tokens))
            time.sleep(stagger)
        for h in handles:
            h.result(timeout=600.0)
        dt = time.perf_counter() - t0
        engine.stop()
        toks = sum(len(h.tokens) for h in handles)
        return toks / dt, toks / max(1, engine.steps - steps0)

    bf16_tps, _ = engine_tps({})
    int8w_tps, _ = engine_tps({"weight_precision": "int8"})
    kv_tps, _ = engine_tps({"kv_precision": "int8"})
    spec_tps, tokens_per_pass = engine_tps(
        {"spec_tokens": spec_k}, draft_model=draft)

    rows = []
    for metric, tps, extra in (
            ("serving_decode_int8w_tokens_per_sec", int8w_tps, {}),
            ("serving_decode_kvint8_tokens_per_sec", kv_tps, {}),
            ("serving_decode_spec_tokens_per_sec", spec_tps, {
                "spec_tokens": spec_k,
                "tokens_per_pass": round(tokens_per_pass, 2),
                "draft_layers": draft_layers,
                "note": "synthetic fully-agreeing draft (acceptance "
                        "1.0 upper bound; pass overhead is what is "
                        "measured)"})):
        row = {
            "metric": metric,
            "value": round(tps, 1), "unit": "tokens/s",
            "bf16_engine_tokens_per_sec": round(bf16_tps, 1),
            "sequential_tokens_per_sec": round(seq_tps, 1),
            "speedup_vs_bf16_engine": round(tps / bf16_tps, 2)
            if bf16_tps > 0 else 0.0,
            "speedup_vs_sequential": round(tps / seq_tps, 2)
            if seq_tps > 0 else 0.0,
            "vs_baseline": 0.0,
        }
        row.update(extra)
        if degraded or not on_tpu:
            row["degraded"] = True
        rows.append(row)
    return rows


def _bench_prefix_cache(degraded: bool) -> list:
    """Shared-prefix serving workload (ISSUE 13): N requests over a
    small TENANT population — every tenant has a common system prompt,
    each request appends a unique user suffix — first through a
    prefix-cache-enabled engine, then the SAME requests through a
    cache-disabled engine built from the same model in the same run.
    Three gateable rows ship with their own evidence:

      * serving_prefix_cache_hit_rate        — admission hits / total
      * serving_ttft_warm_vs_cold_speedup    — mean cold TTFT / mean
        warm-HIT TTFT (per-request time to FIRST token, measured at the
        handle; compiles warmed out of both sides)
      * serving_prefill_tokens_saved_frac    — prompt tokens NOT
        re-prefilled / total prompt tokens

    CPU proxy numbers are degraded-marked; the RATIOS are the claim
    (the cache removes prefill compute on both platforms)."""
    import jax

    import paddle_tpu as P
    from paddle_tpu.inference.engine import EngineConfig, InferenceEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    on_tpu = jax.devices()[0].platform in _ACCEL_PLATFORMS
    if on_tpu:
        dims = dict(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=512)
        page, sys_pages, n_tenants, n_reqs = 32, 8, 4, 24
        sfx_len, new_tokens = 17, 8
        ecfg = dict(page_size=page, max_slots=4, max_seq_len=512,
                    prefill_bucket=page)
    else:
        dims = dict(vocab_size=1024, hidden_size=128, num_layers=2,
                    num_heads=4, max_seq_len=128)
        page, sys_pages, n_tenants, n_reqs = 8, 6, 4, 16
        sfx_len, new_tokens = 5, 4
        ecfg = dict(page_size=page, max_slots=4, max_seq_len=128,
                    prefill_bucket=page)
    P.seed(0)
    model = GPTForCausalLM(GPTConfig(**dims))
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()
    rs = np.random.RandomState(0)
    sys_len = page * sys_pages
    tenants = [rs.randint(0, dims["vocab_size"],
                          (sys_len,)).astype(np.int32)
               for _ in range(n_tenants)]
    reqs = [np.concatenate([
        tenants[i % n_tenants],
        rs.randint(0, dims["vocab_size"], (sfx_len,)).astype(np.int32)])
        for i in range(n_reqs)]
    # warmup tenant (same shapes, never measured): compiles the cold
    # prefill bucket, the warm (sb, npp) program, pack, and decode on
    # BOTH engines so no timed request pays a compile
    wt = rs.randint(0, dims["vocab_size"], (sys_len,)).astype(np.int32)
    warm_reqs = [np.concatenate([
        wt, rs.randint(0, dims["vocab_size"],
                       (sfx_len,)).astype(np.int32)])
        for _ in range(2)]

    def run(prefix_cache):
        eng = InferenceEngine(model, EngineConfig(
            **ecfg, prefix_cache=prefix_cache))
        for w in warm_reqs:
            eng.generate([w], max_new_tokens=new_tokens)
        eng.clear_prefix_cache()
        base = eng.prefix_cache_stats()
        eng.start()
        ttfts = []
        try:
            for p in reqs:
                t0 = time.perf_counter()
                h = eng.submit(p, max_new_tokens=new_tokens)
                it = h.stream(timeout=600.0)
                next(it)                     # block for the FIRST token
                ttfts.append((time.perf_counter() - t0,
                              h.cache_state))
                for _ in it:                 # drain the rest
                    pass
        finally:
            eng.stop()
        st = eng.prefix_cache_stats()
        eng.clear_prefix_cache()
        # delta vs the post-warmup ledger: only the measured burst
        st = {k: st[k] - base[k] if isinstance(st.get(k), (int, float))
              and isinstance(base.get(k), (int, float)) else st.get(k)
              for k in st}
        return ttfts, st

    warm_ttfts, wstats = run(True)
    cold_ttfts, _ = run(False)
    hits = sum(1 for _, c in warm_ttfts if c in ("hit", "partial"))
    hit_rate = hits / max(1, len(warm_ttfts))
    warm_hit_mean = float(np.mean([t for t, c in warm_ttfts
                                   if c in ("hit", "partial")] or [0.0]))
    cold_mean = float(np.mean([t for t, _ in cold_ttfts] or [0.0]))
    speedup = (cold_mean / warm_hit_mean) if warm_hit_mean > 0 else 0.0
    saved_frac = (wstats.get("prefill_tokens_saved", 0)
                  / max(1, wstats.get("prefill_tokens_total", 0)))
    shared = dict(
        tenants=n_tenants, requests=n_reqs, system_prompt_tokens=sys_len,
        suffix_tokens=sfx_len,
        cold_ttft_ms=round(float(cold_mean) * 1e3, 2),
        warm_hit_ttft_ms=round(float(warm_hit_mean) * 1e3, 2))
    rows = []
    for metric, value, unit in (
            ("serving_prefix_cache_hit_rate", round(hit_rate, 4),
             "frac"),
            ("serving_ttft_warm_vs_cold_speedup", round(speedup, 2),
             "x"),
            ("serving_prefill_tokens_saved_frac", round(saved_frac, 4),
             "frac")):
        row = {"metric": metric, "value": value, "unit": unit,
               "vs_baseline": 0.0}
        row.update(shared)
        if degraded or not on_tpu:
            row["degraded"] = True
        rows.append(row)
    return rows


def _bench_fleet_decode(degraded: bool) -> dict:
    """Horizontal serving scale-out (ISSUE 9, reworked under ISSUE 14):
    the `tools/loadgen.py` SHARED-PREFIX tenant workload — the same
    definition the surge chaos scenario drives — runs as an open-loop
    burst of /generate streams through the admission-aware `Router`
    over a TWO-replica `ReplicaFleet` (each replica a real paged-KV
    `InferenceEngine` with its prefix cache on, requests carrying
    `X-Prefix-Fingerprint` so prefix-AFFINITY routing is active);
    value = total generated tokens / wall.  The same run measures the
    same workload against ONE replica directly — the line carries that
    number and the fleet speedup, so the claim "a second replica buys
    real aggregate decode throughput" ships with its own evidence.
    Replica processes run the CPU proxy until per-replica chip-slice
    assignment lands, so the line is degraded-marked off-TPU either
    way."""
    from paddle_tpu.inference.fleet import ReplicaFleet
    from paddle_tpu.inference.serving import InferenceClient

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    try:
        import loadgen
    finally:
        sys.path.pop(0)

    n_reqs, new_tokens = 12, 24
    # 16-token system prompts = 2 full engine pages (page_size=8):
    # page-aligned by construction, so tenants share committed prefix
    # pages AND fingerprint alike (granule 16 — affinity active)
    workload = loadgen.SharedPrefixWorkload(
        seed=0, tenants=3, system_prompt_tokens=16,
        suffix_tokens=(3, 8), vocab=256, generate_frac=1.0,
        max_new_tokens=new_tokens)
    fleet = ReplicaFleet(num_replicas=2, kind="gpt",
                         launch_timeout=300, request_timeout=120.0)
    fleet.start()
    try:
        addrs = [info["address"] for info in
                 fleet.describe()["replicas"].values()]

        def burst(address):
            # a FRESH workload per burst: same seed → bit-identical
            # request specs against the single replica and the fleet
            # (the comparison is apples-to-apples by construction)
            wl = loadgen.SharedPrefixWorkload(
                seed=0, tenants=3, system_prompt_tokens=16,
                suffix_tokens=(3, 8), vocab=256, generate_frac=1.0,
                max_new_tokens=new_tokens)
            runner = loadgen.OpenLoopRunner(
                address, wl, timeout=300.0, max_retries=2,
                max_retry_wait=1.0)
            report = runner.run(
                schedule=wl.schedule_burst(n_reqs, window_s=0.25))
            return report.summary()

        # warm EVERY replica with EVERY request the schedule will send
        # (2 tokens each): compiles (all prefill buckets + the decode
        # program) stay out of both timings AND every tenant's prefix
        # pages are committed in every replica's cache BEFORE either
        # burst — without this the run ORDER biases the comparison
        # (the single burst would warm r0's prefix cache for the fleet
        # burst's bit-identical prompts).  Both bursts measure fully
        # warm serving.
        probe = [s for _, s in workload.schedule_burst(n_reqs, 0.25)]
        for addr in addrs:
            cli = InferenceClient(addr, timeout=300.0, retries=1)
            for s in probe:
                cli.generate(s["prompt"], max_new_tokens=2)
        single = burst(addrs[0])                 # one replica, direct
        via_fleet = burst(fleet.router.address)  # via the router
    finally:
        fleet.stop()
    single_tps = single["tokens_per_sec"]
    fleet_tps = via_fleet["tokens_per_sec"]
    result = {
        "metric": "fleet_decode_tokens_per_sec",
        "value": round(fleet_tps, 1), "unit": "tokens/s",
        # fraction of ideal linear scaling over the measured single
        # replica: 1.0 would be a perfect 2x
        "vs_baseline": round(fleet_tps / (2.0 * single_tps), 4)
        if single_tps > 0 else 0.0,
        "single_replica_tokens_per_sec": round(single_tps, 1),
        "fleet_speedup": round(fleet_tps / single_tps, 2)
        if single_tps > 0 else 0.0,
        "clients": n_reqs, "replicas": 2,
        "completed": [single["ok"], via_fleet["ok"]],
        "admitted_failures": [single["admitted_failures"],
                              via_fleet["admitted_failures"]],
        "workload": "loadgen shared-prefix (3 tenants, affinity on)",
    }
    result["degraded"] = True  # CPU-proxy replicas (see docstring)
    result["note"] = ("replicas share one CPU host on the proxy, so "
                      "scale-out cannot exceed 1x there; the line "
                      "exists for trend + router-overhead tracking "
                      "until per-replica chip slices land")
    return result


def _bench_fleet_cold_start(degraded: bool) -> dict:
    """Replica cold start (ISSUE 17, ROADMAP item 5's baseline): a REAL
    `add_replica()` on a running 1-replica toy fleet, measured by the
    lifecycle plane — value = spawn -> first_probe_up wall ms (what the
    autoscaler's predictive signal actually buys), with the per-phase
    breakdown (imports / weight_load / warmup+compile / announce /
    probe / other) riding the row so the cold-start PR knows WHERE the
    time goes before optimizing it.  Toy replicas on the CPU proxy:
    weight_load and compile are ~0 but ATTRIBUTED (named phases, not
    folded into `other`) — the row is degraded-marked either way."""
    import time as _time

    from paddle_tpu.inference.fleet import ReplicaFleet
    from paddle_tpu.observability import lifecycle as _lc

    fleet = ReplicaFleet(num_replicas=1, kind="toy", token_time=0.02,
                         service_time=0.02, max_slots=4,
                         launch_timeout=60, monitor_interval=0.1)
    fleet.start()
    try:
        rank = fleet.add_replica()
        if rank is None:
            raise RuntimeError("add_replica failed")
        deadline = _time.monotonic() + 30.0
        while _time.monotonic() < deadline and \
                fleet.router.routable_count() < 2:
            _time.sleep(0.05)
        if fleet.router.routable_count() < 2:
            raise RuntimeError("scale-up never became routable")
        rec = next((r for r in fleet.lifecycle.records()
                    if r.get("rank") == rank), None)
        if rec is None or "total_ms" not in rec:
            raise RuntimeError("no joined lifecycle record for the "
                               "scale-up")
        problems = _lc.validate_record(rec)
        observed = fleet.observed_spawn_ms()
    finally:
        fleet.stop()
    result = {
        "metric": "fleet_replica_cold_start_ms",
        "value": round(float(rec["total_ms"]), 1), "unit": "ms",
        "lower_better": True, "vs_baseline": 0.0,
        "phases_ms": {k: round(float(v), 2)
                      for k, v in sorted(rec["phases_ms"].items())},
        "observed_spawn_ms": (None if observed is None
                              else round(observed, 1)),
        "replicas": "1->2", "kind": "toy", "rank": rank,
        "record_problems": problems,
    }
    result["degraded"] = True  # CPU-proxy toy replica (see docstring)
    result["note"] = ("toy replica on the CPU proxy: spawn cost is "
                      "fork+imports; weight_load/compile ~0 but "
                      "attributed — the gpt-replica cold start adds "
                      "real weight_load + per-program compile_ms "
                      "(lifecycle.compile_ms) on top")
    return result


def _bench_qos_paid_p99(degraded: bool) -> dict:
    """Paid-tier isolation under surge (ISSUE 18):
    `serving_qos_paid_p99_ratio` = the paid class's ok-latency p99
    under a two-class (50/50 paid/free) surge, over the p99 of the
    IDENTICAL surge with no class differentiation — what a paid
    request pays for sharing the fleet with free traffic.  QoS holding
    means the ratio sits well under 1.0 (class-weighted admission
    sheds free first, strict-priority dequeue keeps paid moving); 1.0
    means the classes bought nothing.  Toy replicas on the CPU proxy —
    queueing dynamics, not chip throughput, are the claim — so the row
    is degraded-marked either way."""
    from paddle_tpu.inference.fleet import ReplicaFleet, toy_token

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    try:
        import loadgen
    finally:
        sys.path.pop(0)

    def surge(class_split):
        fleet = ReplicaFleet(num_replicas=1, kind="toy",
                             token_time=0.02, service_time=0.02,
                             max_slots=4, launch_timeout=60,
                             monitor_interval=0.1)
        fleet.start()
        try:
            wl = loadgen.SharedPrefixWorkload(
                seed=0, tenants=4, system_prompt_tokens=16,
                suffix_tokens=(3, 6), generate_frac=1.0,
                max_new_tokens=16, class_split=class_split)
            phases = loadgen.surge_phases(
                base_rps=3.0, surge_mult=8.0, warm_s=1.0,
                surge_s=4.0, cool_s=1.0)
            runner = loadgen.OpenLoopRunner(
                fleet.router.address, wl, phases, seed=0,
                expected_token=toy_token, timeout=30.0, max_retries=2)
            return runner.run().summary()
        finally:
            fleet.stop()

    two = surge({"paid": 0.5, "free": 0.5})   # classes on
    flat = surge(None)                        # same surge, no classes
    paid = (two.get("classes") or {}).get("paid") or {}
    free = (two.get("classes") or {}).get("free") or {}
    paid_p99 = (paid.get("latency_ms") or {}).get("p99")
    base_p99 = (flat.get("latency_ms", {}).get("generate") or {}).get(
        "p99")
    if not paid_p99 or not base_p99:
        raise RuntimeError(
            f"missing p99 (paid={paid_p99}, baseline={base_p99})")
    result = {
        "metric": "serving_qos_paid_p99_ratio",
        "value": round(paid_p99 / base_p99, 3), "unit": "ratio",
        "lower_better": True, "vs_baseline": 0.0,
        "paid_p99_ms": round(paid_p99, 1),
        "single_class_p99_ms": round(base_p99, 1),
        "paid_shed": paid.get("shed", 0),
        "free_shed": free.get("shed", 0),
        "paid_admitted_failures": paid.get("admitted_failures", 0),
        "workload": "loadgen shared-prefix surge (4 tenants, "
                    "50/50 paid/free vs single-class)",
    }
    result["degraded"] = True  # CPU-proxy toy replicas (see docstring)
    result["note"] = ("toy-replica queueing proxy: the ratio claims "
                      "scheduling policy, not chip throughput")
    return result


def _bench_stream_resume_gap(degraded: bool) -> dict:
    """Mid-stream failover seam cost (ISSUE 20):
    `serving_stream_resume_gap_ms` = router-measured wall between the
    last token a dying replica delivered and the survivor's first
    post-verify token (`router.resume_gap_ms` p50) — the one latency
    blip a client sees when a replica dies under it.  Measured for
    real: a 2-replica GPT fleet, a concurrent stream burst, kill -9 on
    the replica carrying the most streams one second in; the broken
    streams must finish OK via router resume and stay bit-exact
    against a local same-seed reference engine, or the row is a
    failure.  The gap is dominated by the survivor's tail re-prefill,
    so prefix caches are warmed first (the deployed shape).  GPT
    replicas on the CPU proxy: prefill walls are CPU walls, so the
    row is degraded-marked off-TPU."""
    import threading

    from paddle_tpu import observability as obs
    from paddle_tpu.inference.fleet import (
        ReplicaFleet, _build_gpt_engine,
    )
    from paddle_tpu.inference.serving import InferenceClient
    from paddle_tpu.observability import metrics as _metrics

    n_streams, new_tokens, attempts = 6, 72, 3
    was_enabled = _metrics.enabled()
    obs.attach(crash_hook=False)
    fleet = ReplicaFleet(num_replicas=2, kind="gpt", max_slots=4,
                         launch_timeout=300, request_timeout=120.0)
    fleet.start()
    try:
        rs = np.random.RandomState(0)
        sysp = rs.randint(0, 250, (16,)).tolist()
        prompts = [sysp + rs.randint(0, 250, (3 + i % 5,)).tolist()
                   for i in range(n_streams)]
        # the greedy-determinism oracle: same seed as the replicas
        ref = _build_gpt_engine(seed=0)
        exps = []
        for p in prompts:
            out = ref.generate([np.asarray(p, np.int32)],
                               max_new_tokens=new_tokens)[0]
            exps.append([int(t) for t in np.asarray(out)[len(p):]])
        # warm both replicas' prefix caches + compiles directly (the
        # resume leg's tail re-prefill rides the survivor's cache)
        for view in fleet.router.replica_views():
            cli = InferenceClient(view["address"], timeout=120,
                                  retries=1)
            for p in prompts:
                cli.generate(p, max_new_tokens=2)

        results = []
        lock = threading.Lock()
        delivered_counts = [0] * n_streams

        def _note_token(i):
            with lock:
                delivered_counts[i] += 1

        def one(i):
            cli = InferenceClient(fleet.router.address, timeout=120,
                                  retries=1)
            try:
                r = cli.generate(prompts[i],
                                 max_new_tokens=new_tokens,
                                 on_token=lambda _t: _note_token(i))
                row = (r["tokens"] == exps[i],
                       int(r.get("resumed", 0) or 0))
            except Exception:  # noqa: BLE001 — a broken stream is
                row = (False, 0)  # simply a failed measurement
            with lock:
                results.append(row)

        def busiest_rank(fallback):
            best, best_n = fallback, -1
            for v in fleet.router.replica_views():
                n = sum((v.get("inflight") or {}).values())
                if n > best_n:
                    best, best_n = int(v["id"][1:]), n
            return best

        exact = resumed = 0
        for attempt in range(attempts):
            results.clear()
            with lock:
                delivered_counts[:] = [0] * n_streams
            threads = [threading.Thread(target=one, args=(i,))
                       for i in range(n_streams)]
            for t in threads:
                t.start()
                time.sleep(0.02)
            # wait until the burst is OBSERVABLY flowing (half the
            # streams past their second token) so the kill lands
            # MID-stream — a zero-delivered break would take the plain
            # failover path and measure nothing
            flow_deadline = time.monotonic() + 60.0
            while time.monotonic() < flow_deadline:
                with lock:
                    flowing = sum(1 for c in delivered_counts
                                  if c >= 2)
                if flowing >= n_streams // 2:
                    break
                time.sleep(0.02)
            fleet.kill_replica(busiest_rank(attempt % 2))
            for t in threads:
                t.join(timeout=240)
            fleet.wait_ready(n=2, timeout=120)
            exact = sum(1 for ok, _ in results if ok)
            resumed = sum(1 for _, r in results if r > 0)
            if resumed >= 1:
                break
        gap = _metrics.snapshot()["histograms"].get(
            "router.resume_gap_ms") or {}
        if resumed < 1 or not gap.get("count"):
            raise RuntimeError(
                f"no mid-stream resume landed in {attempts} attempts "
                f"(exact={exact}/{len(results)})")
        if exact != len(results):
            raise RuntimeError(
                f"resumed burst not bit-exact: {exact}/{len(results)}")
    finally:
        fleet.stop()
        if not was_enabled:
            obs.detach()
    result = {
        "metric": "serving_stream_resume_gap_ms",
        "value": round(gap["p50"], 1), "unit": "ms",
        "lower_better": True, "vs_baseline": 0.0,
        # seam-blip noise (scheduler + respawn timing) swamps small
        # deltas; gate on real regressions, not jitter
        "tolerance": 1.0,
        "resumes": int(gap["count"]),
        "gap_p95_ms": round(gap.get("p95", gap["p50"]), 1),
        "streams": n_streams, "resumed_streams": resumed,
        "bit_exact": exact,
        "workload": "2-replica gpt fleet, kill -9 mid-burst, "
                    "router resume (shared 16-token prefix)",
    }
    result["degraded"] = True  # CPU-proxy gpt replicas (see docstring)
    result["note"] = ("gpt replicas on the CPU proxy: the gap is "
                      "CPU re-prefill wall; trend-only until "
                      "per-replica chip slices land")
    return result


def _multichip_sharded_probe() -> None:
    """``--multichip-sharded-probe`` (run in a SUBPROCESS on a forced
    8-virtual-device CPU mesh): train a tiny GPT under the default
    multi-chip configuration — dp=8, fleet ``sharding_degree`` wiring,
    auto ZeRO-1 (ISSUE 11) — and print ONE JSON line of dryrun
    evidence: scanned-step throughput, the real sharded-placement proof
    (largest parameter's full/shard byte ratio, must equal dp), and the
    PT403 replicated-argument audit of the lowered program (must be
    ~zero).  This is the MULTICHIP placement proof bench.py can emit
    without a hardware window."""
    from paddle_tpu.backend_guard import force_cpu_mesh

    force_cpu_mesh(8)

    import paddle_tpu as P
    from paddle_tpu.analysis.perf_audit import (
        build_default_multichip_step, replicated_args,
    )
    from paddle_tpu.models.gpt import GPTConfig

    # the SAME configuration the static audit gates (one definition of
    # "default multi-chip" — perf_audit.build_default_multichip_step),
    # at a slightly larger proxy so the throughput trend means something
    cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                    num_heads=4, max_seq_len=128, fused_head_ce=True)
    step, cfg = build_default_multichip_step(model_cfg=cfg, dp=8)
    batch, seq, iters = 16, 128, 4
    rs = np.random.RandomState(0)
    ids = P.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
    labels = P.to_tensor(
        rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
    losses = step.run_steps(ids, labels, repeat=iters)  # warm/compile
    float(np.asarray(losses._value[-1]))
    t0 = time.perf_counter()
    losses = step.run_steps(ids, labels, repeat=iters)
    final = float(np.asarray(losses._value[-1]))
    dt = time.perf_counter() - t0
    if not np.isfinite(final):
        raise RuntimeError(f"non-finite loss {final}")
    # placement proof 1: the biggest parameter really lives in dp shards
    big = max(step._state["params"].values(), key=lambda v: v.nbytes)
    ratio = big.nbytes / big.addressable_shards[0].data.nbytes
    # placement proof 2: PT403 over the lowered program — no big
    # replicated arguments survive the sharded weight update
    pt403 = replicated_args(step.lower(ids, labels).as_text())
    _emit({
        "probe": "multichip_sharded",
        "tokens_per_sec": round(batch * seq * iters / dt, 1),
        "param_shard_ratio": round(float(ratio), 2),
        "replicated_arg_mbytes": pt403["pt403_replicated_mbytes"],
        "replicated_arg_count": pt403["pt403_replicated_count"],
        "dp": 8, "sharding_stage": step.sharding_stage,
        "final_loss": round(final, 4),
    })


def _bench_multichip_sharded(degraded: bool) -> dict | None:
    """ZeRO-1 pod-training dryrun rows (ISSUE 11): spawn the
    8-virtual-device probe in a fresh subprocess (this process's jax is
    pinned to 1 device on the CPU path) and emit two rows —

      multichip_sharded_train_tokens_per_sec   CPU-proxy trend (always
                                               degraded-marked: 8
                                               virtual devices share
                                               one host's cores)
      multichip_sharded_param_shard_ratio      the placement PROOF, not
                                               a speed number: largest
                                               param full/shard bytes,
                                               8.0 under ZeRO-1 over
                                               dp=8.  NOT degraded — a
                                               regression to a
                                               replicated update reads
                                               1.0 and fails the
                                               perf_gate baseline.
    """
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--multichip-sharded-probe"],
        capture_output=True, text=True, timeout=900, env=env)
    probe = None
    for line in reversed(r.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            probe = json.loads(line)
            break
    if probe is None:
        raise RuntimeError(
            f"probe produced no JSON (rc={r.returncode}): "
            f"{r.stderr[-400:]}")
    _emit({
        "metric": "multichip_sharded_train_tokens_per_sec",
        "value": probe["tokens_per_sec"], "unit": "tokens/s",
        "vs_baseline": 0.0, "degraded": True,
        "dp": probe["dp"], "sharding_stage": probe["sharding_stage"],
        "note": "8-virtual-device CPU-mesh ZeRO-1 dryrun (trend only; "
                "virtual devices share one host's cores)",
    })
    row = {
        "metric": "multichip_sharded_param_shard_ratio",
        "value": probe["param_shard_ratio"], "unit": "x",
        "vs_baseline": round(probe["param_shard_ratio"] / probe["dp"], 4),
        "replicated_arg_mbytes": probe["replicated_arg_mbytes"],
        "replicated_arg_count": probe["replicated_arg_count"],
        "dp": probe["dp"], "sharding_stage": probe["sharding_stage"],
    }
    if degraded:
        # only mark the proof row degraded when the WHOLE bench run is a
        # forced fallback; the ratio itself is backend-independent
        row["note"] = "emitted during a degraded bench run"
    _emit(row)
    return row


def _bench_telemetry_overhead(degraded: bool) -> dict:
    """Telemetry-overhead honesty row (ISSUE 15): decode tokens/s with
    the FULL observability plane on (metrics registry + schema, flight,
    timeseries sampler at a fast interval, per-request timelines, and —
    ISSUE 16 — the per-tenant ledger, which the engine constructs
    whenever the registry is live, billing every decode token, slot-ms
    and page-second on this arm) vs
    the same engine shape with `PADDLE_TPU_METRICS=off` semantics
    (registry disabled, timelines off) — measured SAME-RUN on the same
    model and prompts.  Value = (off - on)/off, LOWER better, ~0 when
    the plane is free.  The observability stack must prove it is not
    the perf regression; this row makes a telemetry-induced decode tax
    fail `perf_gate` like any other regression."""
    import jax

    import paddle_tpu as P
    from paddle_tpu import observability as obs
    from paddle_tpu.inference.engine import EngineConfig, InferenceEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.observability import metrics as _metrics
    from paddle_tpu.observability import timeseries as _tsmod

    on_tpu = jax.devices()[0].platform in _ACCEL_PLATFORMS
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=512)
        n_clients, new_tokens = 8, 64
        ecfg_kw = dict(page_size=32, max_slots=8, decode_chunk=8,
                       max_seq_len=512, prefix_cache=False)
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128)
        n_clients, new_tokens = 6, 24
        ecfg_kw = dict(page_size=8, max_slots=4, decode_chunk=4,
                       max_seq_len=128, prefix_cache=False)
    P.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, cfg.vocab_size, (8,)).astype(np.int32)
               for _ in range(n_clients)]

    ledger_armed = []  # the on-arm engine's ledger must actually exist

    def measure(telemetry_on: bool) -> float:
        prev_cap = os.environ.get("PADDLE_TPU_ITL_TIMELINE_CAP")
        sampler = None
        engine = None
        try:
            if telemetry_on:
                obs.attach(crash_hook=False)
            else:
                # the PADDLE_TPU_METRICS=off shape: registry AND span
                # tracer disabled (detach — a tracer left buffering
                # would depress the off baseline and underreport the
                # tax), timelines off — what a telemetry-averse
                # deployment would run
                obs.detach()
                os.environ["PADDLE_TPU_ITL_TIMELINE_CAP"] = "0"
            engine = InferenceEngine(model, EngineConfig(**ecfg_kw))
            if telemetry_on:
                ledger_armed.append(engine.tenant_ledger is not None)
            engine.generate(prompts[:1], max_new_tokens=2)  # warm
            if telemetry_on:
                sampler = _tsmod.TimeSeriesSampler(
                    names=("engine.tokens", "engine.batch_occupancy",
                           "engine.page_utilization"),
                    interval_s=0.05)
                sampler.start()
            engine.start()
            t0 = time.perf_counter()
            handles = [engine.submit(p, max_new_tokens=new_tokens)
                       for p in prompts]
            for h in handles:
                h.result(timeout=600.0)
            dt = time.perf_counter() - t0
            return sum(len(h.tokens) for h in handles) / dt
        finally:
            if engine is not None:
                engine.stop()  # a leaked loop thread would compete
                # with every later measurement
            if sampler is not None:
                sampler.stop()
            if prev_cap is None:
                os.environ.pop("PADDLE_TPU_ITL_TIMELINE_CAP", None)
            else:
                os.environ["PADDLE_TPU_ITL_TIMELINE_CAP"] = prev_cap

    was_enabled = _metrics.enabled()
    try:
        tps_on = measure(True)
        tps_off = measure(False)
    finally:
        # leave the stack as this bench found it even when a measure
        # raises (run_secondary_benches catches and keeps going — the
        # later benches must not inherit a flipped registry)
        if was_enabled:
            obs.attach(crash_hook=False)
        else:
            obs.detach()
    frac = (tps_off - tps_on) / tps_off if tps_off > 0 else 0.0
    result = {
        "metric": "serving_telemetry_overhead_frac",
        "value": round(max(frac, 1e-4), 4),  # >0 so --update keeps it
        "unit": "frac",
        "lower_better": True,
        # relative tolerance vs a small baseline fraction is noisy by
        # nature: a generous row-level tolerance keeps the gate about
        # real regressions (2x the baseline tax), not jitter
        "tolerance": 1.0,
        "tokens_per_sec_on": round(tps_on, 1),
        "tokens_per_sec_off": round(tps_off, 1),
        # honesty flag: the "on" arm really carried the tenant ledger
        # (False would mean this row measures less plane than deployed)
        "tenant_ledger_on": bool(ledger_armed and all(ledger_armed)),
        "vs_baseline": 0.0,
    }
    if degraded or not on_tpu:
        result["degraded"] = True
    return result


def run_secondary_benches(degraded: bool = False) -> None:
    """BASELINE configs 1 (ResNet50) and 5 (ViT attention shapes) plus
    the serving decode metric: emit one JSON line each BEFORE the primary
    GPT line (the driver reads the last line as the headline metric).
    With degraded=True (CPU proxy) the lines are emitted for trend data
    with shrunken batch/iters, marked accordingly (VERDICT r3 Weak #7:
    secondaries must not vanish on fallback). Every metric emits a line
    even on failure (zero value + note) — absence is the one outcome
    this function never produces."""
    from paddle_tpu.vision import models as V

    kw = {} if not degraded else {"iters": 2}  # CPU proxy: trend only
    # config 1: ResNet50 single-chip (PHI conv-kernel parity).
    # 224x224 fwd ~4.1 GFLOPs/img; train ~3x.
    _emit(_bench_vision_model(
        lambda: V.resnet50(num_classes=1000),
        "resnet50_train_images_per_sec_per_chip",
        flops_per_image=3 * 4.09e9, degraded=degraded,
        batch_candidates=[256, 128, 64] if not degraded else [2], **kw))
    # config 5: ViT-B/16 (flash-attention path at vision shapes).
    # 224x224 fwd ~17.6 GFLOPs/img; train ~3x.
    _emit(_bench_vision_model(
        lambda: V.vit_b_16(num_classes=1000),
        "vit_b16_train_images_per_sec_per_chip",
        flops_per_image=3 * 17.6e9, degraded=degraded,
        batch_candidates=[128, 64, 32] if not degraded else [2], **kw))
    # config 5 (second model family): Swin-T windowed attention.
    # 224x224 fwd ~4.5 GFLOPs/img; train ~3x.
    _emit(_bench_vision_model(
        lambda: V.swin_t(num_classes=1000),
        "swin_t_train_images_per_sec_per_chip",
        flops_per_image=3 * 4.5e9, degraded=degraded,
        batch_candidates=[128, 64, 32] if not degraded else [2], **kw))
    try:
        _emit(_bench_decode(degraded))
    except Exception as e:
        print(f"decode-bench-failed: {e}", file=sys.stderr)
        _emit({"metric": "gpt125m_decode_tokens_per_sec", "value": 0.0,
               "unit": "tokens/s", "vs_baseline": 0.0, "degraded": True,
               "note": f"failed: {type(e).__name__}: {e}"})
    try:
        _emit(_bench_serving_decode(degraded))
    except Exception as e:
        print(f"serving-decode-bench-failed: {e}", file=sys.stderr)
        _emit({"metric": "serving_decode_tokens_per_sec", "value": 0.0,
               "unit": "tokens/s", "vs_baseline": 0.0, "degraded": True,
               "note": f"failed: {type(e).__name__}: {e}"})
    try:
        for row in _bench_quantized_decode(degraded):
            _emit(row)
    except Exception as e:
        print(f"quantized-decode-bench-failed: {e}", file=sys.stderr)
        for metric in ("serving_decode_int8w_tokens_per_sec",
                       "serving_decode_kvint8_tokens_per_sec",
                       "serving_decode_spec_tokens_per_sec"):
            _emit({"metric": metric, "value": 0.0, "unit": "tokens/s",
                   "vs_baseline": 0.0, "degraded": True,
                   "note": f"failed: {type(e).__name__}: {e}"})
    try:
        for row in _bench_prefix_cache(degraded):
            _emit(row)
    except Exception as e:
        print(f"prefix-cache-bench-failed: {e}", file=sys.stderr)
        # failure emits degraded 0-rows, never absence (a vanished row
        # reads as "nothing regressed" to the gate)
        for metric in ("serving_prefix_cache_hit_rate",
                       "serving_ttft_warm_vs_cold_speedup",
                       "serving_prefill_tokens_saved_frac"):
            _emit({"metric": metric, "value": 0.0, "unit": "frac",
                   "vs_baseline": 0.0, "degraded": True,
                   "note": f"failed: {type(e).__name__}: {e}"})
    try:
        _emit(_bench_fleet_decode(degraded))
    except Exception as e:
        print(f"fleet-decode-bench-failed: {e}", file=sys.stderr)
        _emit({"metric": "fleet_decode_tokens_per_sec", "value": 0.0,
               "unit": "tokens/s", "vs_baseline": 0.0, "degraded": True,
               "note": f"failed: {type(e).__name__}: {e}"})
    try:
        _emit(_bench_telemetry_overhead(degraded))
    except Exception as e:
        print(f"telemetry-overhead-bench-failed: {e}", file=sys.stderr)
        # a failed measurement must not read as "telemetry is free":
        # the honesty row goes out degraded with a loud note, never
        # silently absent
        _emit({"metric": "serving_telemetry_overhead_frac",
               "value": 0.0, "unit": "frac", "lower_better": True,
               "vs_baseline": 0.0, "degraded": True,
               "note": f"failed: {type(e).__name__}: {e}"})
    try:
        _emit(_bench_fleet_cold_start(degraded))
    except Exception as e:
        print(f"fleet-cold-start-bench-failed: {e}", file=sys.stderr)
        # the cold-start row is ROADMAP item 5's baseline — a failed
        # measurement goes out degraded with a loud note, never absent
        _emit({"metric": "fleet_replica_cold_start_ms", "value": 0.0,
               "unit": "ms", "lower_better": True, "vs_baseline": 0.0,
               "degraded": True,
               "note": f"failed: {type(e).__name__}: {e}"})
    try:
        _emit(_bench_qos_paid_p99(degraded))
    except Exception as e:
        print(f"qos-paid-p99-bench-failed: {e}", file=sys.stderr)
        # a failed measurement must not read as "QoS holds": the row
        # goes out degraded with a loud note, never silently absent
        _emit({"metric": "serving_qos_paid_p99_ratio", "value": 0.0,
               "unit": "ratio", "lower_better": True,
               "vs_baseline": 0.0, "degraded": True,
               "note": f"failed: {type(e).__name__}: {e}"})
    try:
        _emit(_bench_stream_resume_gap(degraded))
    except Exception as e:
        print(f"stream-resume-gap-bench-failed: {e}", file=sys.stderr)
        # a failed measurement must not read as "failover is free":
        # the seam-cost row goes out degraded with a loud note, never
        # silently absent
        _emit({"metric": "serving_stream_resume_gap_ms", "value": 0.0,
               "unit": "ms", "lower_better": True,
               "vs_baseline": 0.0, "degraded": True,
               "note": f"failed: {type(e).__name__}: {e}"})
    try:
        _bench_multichip_sharded(degraded)
    except Exception as e:
        print(f"multichip-sharded-bench-failed: {e}", file=sys.stderr)
        # a failed probe must not read as "sharding fine": the proof row
        # goes out degraded (never gates) with value 0, not silently
        # absent and not a fake healthy ratio
        _emit({"metric": "multichip_sharded_param_shard_ratio",
               "value": 0.0, "unit": "x", "vs_baseline": 0.0,
               "degraded": True,
               "note": f"failed: {type(e).__name__}: {e}"})


def _emit_secondaries_degraded() -> None:
    """CPU-proxy secondary lines; never raises (one shared call site for
    the two fallback paths in main())."""
    try:
        run_secondary_benches(degraded=True)
    except Exception as e:
        print(f"secondary-benches-failed: {e}", file=sys.stderr)


def _emit(result: dict) -> None:
    sys.stdout.flush()
    print(json.dumps(result))
    sys.stdout.flush()


_GOOD_BENCH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tools", "last_good_bench.jsonl")
_HEADLINE = "gpt125m_train_tokens_per_sec_per_chip"
_MAX_REUSE_AGE_S = 24 * 3600  # one ROUND: a round's builder sessions plus
# the driver's end-of-round capture span up to ~a day; captured_at still
# bounds reuse to this round's own measurements, never an earlier round's


def _emit_from_chip_session(reason: str) -> bool:
    """Probe-failure fallback (VERDICT r3 Next #1): reuse the freshest
    non-degraded on-chip result captured by tools/chip_session.py at ANY
    point in this ROUND (24h bound via captured_at; a round spans
    multiple builder sessions plus the driver's end-of-round capture),
    instead of surrendering the datapoint to a CPU
    proxy just because the tunnel is down at capture time. Emits secondary
    metrics first and the headline last (driver reads the last line).
    Returns True when a headline result was emitted."""
    try:
        best: dict[str, dict] = {}
        with open(_GOOD_BENCH) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                m = obj.get("metric")
                if not m or obj.get("degraded") or obj.get("value", 0) <= 0:
                    continue
                if time.time() - obj.get("captured_at", 0) > _MAX_REUSE_AGE_S:
                    continue
                if m not in best or obj.get("captured_at", 0) >= \
                        best[m].get("captured_at", 0):
                    best[m] = obj
    except OSError:
        return False
    head = best.pop(_HEADLINE, None)
    if head is None:
        return False
    # records chip_session wrote at capture time reuse as plain
    # chip_session results; a record carrying reconstructed=true (values
    # transcribed back from PERF.md after the capture-time JSONL was
    # lost) must say so in both source and note — it is a this-round
    # measurement, but not a capture-time artifact
    for obj in best.values():
        age_min = (time.time() - obj.pop("captured_at")) / 60.0
        if obj.pop("reconstructed", False):
            obj["source"] = "chip_session_reconstructed"
            obj["note"] = (f"on-chip measurement from {age_min:.0f} min "
                           "earlier this round; record reconstructed "
                           "(see provenance)")
        else:
            obj["source"] = "chip_session"
            obj["note"] = (f"measured on-chip {age_min:.0f} min earlier "
                           "this round")
        _emit(obj)
    age_min = (time.time() - head.pop("captured_at")) / 60.0
    if head.pop("reconstructed", False):
        head["source"] = "chip_session_reconstructed"
        head["note"] = (f"{reason}; reusing the on-chip result measured "
                        f"{age_min:.0f} min earlier this round — record "
                        "reconstructed, not a capture-time artifact "
                        "(see provenance)")
    else:
        head["source"] = "chip_session"
        head["note"] = (f"{reason}; reusing on-chip result measured "
                        f"{age_min:.0f} min earlier this round")
    _emit(head)
    return True


_TPU_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", ".jax_tpu_cache")


def main() -> None:
    if "--multichip-sharded-probe" in sys.argv[1:]:
        # subprocess entry: forced 8-virtual-device CPU mesh, one JSON
        # line of ZeRO-1 dryrun evidence (see _multichip_sharded_probe)
        _multichip_sharded_probe()
        return
    # share the watcher's persistent TPU compile cache: programs compiled
    # in an earlier tunnel window load instead of recompiling
    from paddle_tpu.backend_guard import enable_persistent_compile_cache

    enable_persistent_compile_cache(_TPU_CACHE)
    if "--force-cpu" in sys.argv[1:]:
        from paddle_tpu.backend_guard import force_cpu_mesh

        force_cpu_mesh(1)
        result = run_bench(degraded=True, note="forced-cpu",
                           telemetry=_telemetry_requested())
        _emit_secondaries_degraded()
        _emit(result)
        return

    from paddle_tpu.backend_guard import (
        force_cpu_mesh, probe_default_backend,
    )

    note = ""
    telemetry = _telemetry_requested()
    # a down tunnel often comes back within minutes: retry for up to
    # ~7.5 min worst case (5 x 75 s timeouts + 4 x 20 s sleeps) before
    # surrendering the round's datapoint to the CPU proxy
    probe = probe_default_backend(timeout=75.0, retries=5, backoff=20.0)
    if probe is not None and probe[0] in _ACCEL_PLATFORMS:
        try:
            result = run_bench(telemetry=telemetry)
            # secondary metrics (BASELINE configs 1 & 5) must never sink
            # the headline: emitted first, failures noted in their lines
            try:
                run_secondary_benches()
            except Exception as e2:
                print(f"secondary-benches-failed: {e2}", file=sys.stderr)
            _emit(result)
            # (persistence of good lines is chip_session's job — a single
            # writer keeps the record's filter logic in one place)
            return
        except Exception as e:  # TPU ran but the bench crashed mid-run
            note = f"tpu-run-failed: {type(e).__name__}: {e}"
            print(note, file=sys.stderr)
            # Kernel-granular degradation (VERDICT r2 task 3): before
            # abandoning the chip, retry once with the whole Pallas tier
            # disabled — a broken custom kernel should cost speed, not the
            # datapoint. Fresh subprocess: this process's TPU state may be
            # poisoned. (Skipped when already running pallas-disabled.)
            try:
                if os.environ.get("FLAGS_disable_pallas") == "1":
                    raise RuntimeError("already pallas-disabled")
                env = dict(os.environ, FLAGS_disable_pallas="1")
                retry_cmd = [sys.executable, os.path.abspath(__file__)]
                if telemetry:
                    retry_cmd.append(_TELEMETRY_FLAG)
                r = subprocess.run(
                    retry_cmd,
                    capture_output=True, text=True, timeout=900, env=env)
                for line in reversed(r.stdout.splitlines()):
                    line = line.strip()
                    if line.startswith("{"):
                        out = json.loads(line)
                        if not out.get("degraded"):
                            out["note"] = (note + "; retried-pallas-disabled"
                                           ).strip("; ")
                            _emit(out)
                            return
                        break
            except Exception as e2:
                print(f"pallas-disabled-retry-failed: {e2}", file=sys.stderr)
            # a previously captured on-chip result beats any CPU proxy
            if _emit_from_chip_session(note):
                return
            # CPU fallback needs a fresh process: this one holds a live
            # TPU backend and possibly poisoned device state.
            try:
                cpu_cmd = [sys.executable, os.path.abspath(__file__),
                           "--force-cpu"]
                if telemetry:
                    cpu_cmd.append(_TELEMETRY_FLAG)
                r = subprocess.run(
                    cpu_cmd,
                    capture_output=True, text=True, timeout=600)
                for line in reversed(r.stdout.splitlines()):
                    line = line.strip()
                    if line.startswith("{"):
                        out = json.loads(line)
                        out["note"] = note
                        _emit(out)
                        return
            except Exception as e2:
                print(f"cpu-subprocess-failed: {e2}", file=sys.stderr)
            # this process holds a (possibly poisoned) TPU backend and the
            # fresh-process fallback also failed — emit a parseable line
            # rather than risk an in-process re-init hang
            _emit({"metric": "gpt125m_train_tokens_per_sec_per_chip",
                   "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                   "degraded": True, "note": note + "; cpu-fallback-failed"})
            return
    else:
        note = "tpu-probe-failed" if probe is None else f"platform={probe[0]}"
        print(f"backend probe: {note}", file=sys.stderr)
        # a previously captured on-chip result beats any CPU proxy
        if _emit_from_chip_session(note):
            return
        print("no chip_session result available; falling back to CPU proxy",
              file=sys.stderr)

    # Probe failed or reported a non-accelerator platform: no backend has
    # been initialized in this process yet (the probe ran in a subprocess),
    # so an in-process forced-CPU run is safe.
    force_cpu_mesh(1)
    try:
        result = run_bench(degraded=True, note=note, telemetry=telemetry)
        _emit_secondaries_degraded()  # trend data even on the proxy
        _emit(result)
    except Exception as e:
        _emit({"metric": "gpt125m_train_tokens_per_sec_per_chip",
               "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
               "degraded": True, "note": f"{note}; cpu-run-failed: {e}"})


if __name__ == "__main__":
    main()
