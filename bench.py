"""Single-chip GPT pretrain throughput benchmark.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric: tokens/sec/chip on a GPT-125M-shape training step (fwd+bwd+AdamW),
bf16 compute. vs_baseline = achieved MFU / 0.45 (the BASELINE.md north-star
MFU target; the reference publishes no absolute numbers — BASELINE.md).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as P
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    # GPT-125M shape on TPU; tiny proxy on CPU so the script always completes
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=1024)
        batch_candidates, seq, iters = [32, 16, 8], 1024, 20
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128)
        batch_candidates, seq, iters = [2], 128, 3

    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                               "sep_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)

    rs = np.random.RandomState(0)
    tps = None
    model = opt = crit = step = ids = labels = loss = None
    last_exc = None
    for batch in batch_candidates:  # biggest batch that fits wins (MXU util)
        # release the previous attempt's device buffers BEFORE reallocating
        model = opt = crit = step = ids = labels = loss = None
        import gc

        gc.collect()
        try:
            # fresh model/opt/step per attempt: a failed donated step leaves
            # state unusable
            P.seed(0)
            model = fleet.distributed_model(GPTForCausalLM(cfg))
            opt = fleet.distributed_optimizer(
                P.optimizer.AdamW(parameters=model.parameters(),
                                  learning_rate=1e-4))
            crit = GPTPretrainingCriterion()
            step = model.build_train_step(opt, crit, amp_dtype="bfloat16")
            ids = P.to_tensor(
                rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
            labels = P.to_tensor(
                rs.randint(0, cfg.vocab_size, (batch, seq)), "int32")
            # warmup/compile
            loss = step(ids, labels)
            loss.block_until_ready()

            t0 = time.perf_counter()
            for _ in range(iters):
                loss = step(ids, labels)
            loss.block_until_ready()
            dt = time.perf_counter() - t0
            tokens = batch * seq * iters
            tps = tokens / dt
            break
        except Exception as e:
            last_exc = e
            print(f"batch={batch} failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
    if tps is None:
        raise RuntimeError("all batch sizes failed") from last_exc

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_token = 6 * n_params  # fwd+bwd matmul flops
    peak = {"tpu": 197e12}.get(platform, 1e12)  # v5e bf16 peak
    mfu = tps * flops_per_token / peak
    print(json.dumps({
        "metric": "gpt125m_train_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
