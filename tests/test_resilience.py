"""Resilience subsystem tests (ISSUE 3): deterministic fault-injection
matrix over the recovery paths — torn/corrupted checkpoint → rollback,
failing collective → retry then raise, NaN loss → guarded skip (+ scaler
interplay), stalled heartbeat → watchdog dump.  Everything is seeded,
CPU-only, and fast (the long random matrix lives under the `chaos`
marker / tools/chaos_check.py, outside tier-1).
"""
import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import fleet, topology
from paddle_tpu.distributed.checkpoint import (
    CheckpointCorruptionError, CheckpointManager, load_state_dict,
    save_state_dict, verify_checkpoint, wait_async_save,
)
from paddle_tpu.observability import flight, metrics
from paddle_tpu.resilience import (
    CircuitBreaker, CircuitOpenError, DeadlineExceeded, InjectedFault,
    RetryPolicy, StepGuard, Watchdog, faults,
)
from paddle_tpu.resilience.guards import RollbackError


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


def _no_sleep(policy):
    policy.sleep = lambda s: None
    return policy


# --------------------------------------------------------------------------
# fault-injection harness
# --------------------------------------------------------------------------

def test_fault_rule_determinism():
    """Same seed → identical injection pattern across runs."""
    def pattern(seed):
        faults.clear()
        out = []
        with faults.inject("collective.call", p=0.5, seed=seed, times=None):
            for _ in range(32):
                try:
                    faults.fire("collective.call")
                    out.append(0)
                except InjectedFault:
                    out.append(1)
        return out

    a, b, c = pattern(7), pattern(7), pattern(8)
    assert a == b
    assert a != c  # different seed, different stream
    assert 0 < sum(a) < 32  # p=0.5 actually mixes


def test_fault_count_triggers():
    with faults.inject("train.step", kind="nan", at=3):
        assert faults.fire("train.step") is None
        assert faults.fire("train.step") is None
        action = faults.fire("train.step")
        assert action is not None and action.kind == "nan"
        assert faults.fire("train.step") is None  # at= implies times=1


def test_fault_env_spec_parsing():
    rules = faults._parse_env_spec(
        "collective.call,p=0.3,times=2;train.step,at=3,kind=nan")
    assert len(rules) == 2
    assert rules[0].point == "collective.call" and rules[0].p == 0.3
    assert rules[1].kind == "nan" and rules[1].at == 3
    with pytest.raises(ValueError):
        faults._parse_env_spec("not.a.point,p=1")


def test_fault_injection_lands_on_observability():
    metrics.enable()
    metrics.reset()
    flight.clear()
    try:
        with faults.inject("dataloader.batch", at=1):
            with pytest.raises(InjectedFault):
                faults.fire("dataloader.batch", n=4)
        snap = metrics.snapshot()["counters"]
        assert snap["resilience.faults{point=dataloader.batch}"] == 1
        kinds = [e["kind"] for e in flight.events()]
        assert "resilience.fault_injected" in kinds
    finally:
        metrics.disable()


# --------------------------------------------------------------------------
# retry / backoff / circuit breaker
# --------------------------------------------------------------------------

def test_retry_then_success_and_giveup():
    sleeps = []
    pol = RetryPolicy("t", max_attempts=3, seed=1,
                      sleep=lambda s: sleeps.append(s))
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise ValueError("transient")
        return "ok"

    assert pol.call(flaky) == "ok"
    assert len(sleeps) == 2
    # exponential shape survives the jitter (jitter=0.25 < multiplier=2)
    assert sleeps[1] > sleeps[0]

    def always():
        raise ValueError("permanent")

    with pytest.raises(ValueError, match="permanent"):
        pol.call(always)


def test_retry_jitter_deterministic():
    a = RetryPolicy("same", seed=5, sleep=lambda s: None)
    b = RetryPolicy("same", seed=5, sleep=lambda s: None)
    assert [a.backoff(i) for i in (1, 2, 3)] == \
           [b.backoff(i) for i in (1, 2, 3)]


def test_retry_deadline():
    clock = {"t": 0.0}

    def sleep(s):
        clock["t"] += s

    pol = RetryPolicy("dl", max_attempts=10, base_delay=1.0, multiplier=1.0,
                      jitter=0.0, deadline=2.5, sleep=sleep,
                      clock=lambda: clock["t"])

    def always():
        raise OSError("down")

    with pytest.raises(DeadlineExceeded):
        pol.call(always)
    assert clock["t"] <= 2.5  # never slept past the deadline


def test_circuit_breaker_opens_and_recovers():
    clock = {"t": 0.0}
    br = CircuitBreaker(failure_threshold=2, reset_timeout=10.0,
                        clock=lambda: clock["t"])
    pol = RetryPolicy("cb", max_attempts=1, sleep=lambda s: None,
                      circuit_breaker=br)

    def boom():
        raise OSError("down")

    for _ in range(2):
        with pytest.raises(OSError):
            pol.call(boom)
    assert br.state == "open"
    with pytest.raises(CircuitOpenError):  # fails fast, no call
        pol.call(lambda: "never")
    clock["t"] += 11.0  # past reset_timeout: one half-open trial admitted
    assert pol.call(lambda: "back") == "back"
    assert br.state == "closed"


# --------------------------------------------------------------------------
# collective: injected fault → retry then raise
# --------------------------------------------------------------------------

def _init_mesh(dp=8):
    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": dp}
    fleet.init(is_collective=True, strategy=strategy)


def test_collective_fault_retried_then_raises():
    from paddle_tpu.distributed import all_reduce
    from paddle_tpu.distributed.collective import _collective_retry

    _init_mesh(dp=8)
    _no_sleep(_collective_retry())
    t = P.Tensor(np.ones((8, 4), np.float32))
    # 2 transient failures, 3 attempts → recovered
    with faults.inject("collective.call", times=2):
        all_reduce(t)
    assert np.isfinite(t.numpy()).all()
    # persistent failure exhausts the budget → the real error surfaces
    t2 = P.Tensor(np.ones((8, 4), np.float32))
    with faults.inject("collective.call", times=100):
        with pytest.raises(InjectedFault):
            all_reduce(t2)


# --------------------------------------------------------------------------
# checkpoint: atomic save, CRC verify, rotation, rollback
# --------------------------------------------------------------------------

def _sd(val=1.0):
    return {"w": Tensor(jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
                        * val),
            "step": Tensor(jnp.asarray(7, jnp.int32))}


def _zeros_like_sd():
    return {"w": Tensor(jnp.zeros((3, 4), jnp.float32)),
            "step": Tensor(jnp.asarray(0, jnp.int32))}


def test_checkpoint_crc_roundtrip(tmp_path):
    path = str(tmp_path / "ck")
    src = _sd()
    save_state_dict(src, path)
    rep = verify_checkpoint(path)
    assert rep["shards"] == 2 and rep["unverified"] == 0
    tgt = _zeros_like_sd()
    load_state_dict(tgt, path)
    np.testing.assert_array_equal(tgt["w"].numpy(), src["w"].numpy())
    assert int(tgt["step"].numpy()) == 7


def test_checkpoint_mid_write_kill_preserves_previous(tmp_path):
    """Simulated kill mid-write: tmp bytes on disk, no commit — the
    previous checkpoint stays the loadable one and round-trips with
    verified CRCs."""
    mgr = CheckpointManager(str(tmp_path), keep_last_k=3)
    mgr.save(_sd(), step=0)
    with faults.inject("checkpoint.write", kind="torn", at=1):
        with pytest.raises(InjectedFault):
            mgr.save(_sd(2.0), step=1)
    # step 1 never committed (no metadata): not listed, not restorable
    assert mgr.checkpoints() == [0]
    assert mgr.latest_step() == 0
    tgt = _zeros_like_sd()
    assert mgr.restore(tgt) == 0
    np.testing.assert_array_equal(tgt["w"].numpy(), _sd()["w"].numpy())


def test_checkpoint_corruption_rolls_back(tmp_path):
    """Bit-rot after a clean commit: CRC verification catches it and
    restore falls back to the previous checkpoint, quarantining the
    corrupt one."""
    mgr = CheckpointManager(str(tmp_path), keep_last_k=3)
    mgr.save(_sd(), step=0)
    with faults.inject("checkpoint.write", kind="corrupt", at=1):
        mgr.save(_sd(2.0), step=1)
    with pytest.raises(CheckpointCorruptionError):
        verify_checkpoint(mgr._dir(1))
    metrics.enable()
    metrics.reset()
    try:
        tgt = _zeros_like_sd()
        assert mgr.restore(tgt) == 0
        np.testing.assert_array_equal(tgt["w"].numpy(), _sd()["w"].numpy())
        snap = metrics.snapshot()["counters"]
        assert snap.get("resilience.rollbacks", 0) >= 1
    finally:
        metrics.disable()
    assert os.path.isdir(mgr._dir(1) + ".corrupt")  # quarantined
    assert 1 not in mgr.checkpoints()


def test_checkpoint_rotation_and_latest_pointer(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_k=2)
    for s in range(4):
        mgr.save(_sd(float(s + 1)), step=s)
    assert mgr.checkpoints() == [2, 3]  # pruned to last K
    assert mgr.latest_step() == 3
    with open(os.path.join(str(tmp_path), "latest")) as f:
        assert f.read().strip() == "ckpt_00000003"
    tgt = _zeros_like_sd()
    assert mgr.restore(tgt) == 3
    np.testing.assert_array_equal(tgt["w"].numpy(), _sd(4.0)["w"].numpy())


def test_failed_async_save_does_not_block_restore(tmp_path):
    """A captured async-save failure must not abort restore(): the
    rollback path consumes it and falls back to the last committed
    checkpoint (the exact situation rollback exists for)."""
    mgr = CheckpointManager(str(tmp_path), keep_last_k=3)
    mgr.save(_sd(), step=0)
    with faults.inject("checkpoint.write", kind="torn", at=1):
        mgr.save(_sd(2.0), step=1, async_save=True)
        # error still pending (wait_async_save not called) when the
        # guard escalation lands on restore()
        tgt = _zeros_like_sd()
        assert mgr.restore(tgt) == 0
    np.testing.assert_array_equal(tgt["w"].numpy(), _sd()["w"].numpy())
    wait_async_save()  # error was consumed by restore; wait is clean


def test_async_save_error_reraised_on_next_wait(tmp_path):
    """Satellite: an exception in the async save thread is captured and
    re-raised at the next save/wait, never silently lost."""
    path = str(tmp_path / "ck")
    with faults.inject("checkpoint.write", kind="torn", at=1):
        save_state_dict(_sd(), path, async_save=True)
        with pytest.raises(InjectedFault):
            wait_async_save()
    # error is consumed: the next wait is clean, and a new save works
    wait_async_save()
    save_state_dict(_sd(), path, async_save=True)
    wait_async_save()
    assert verify_checkpoint(path)["shards"] == 2


# --------------------------------------------------------------------------
# NaN guard + train step + scaler interplay
# --------------------------------------------------------------------------

def _make_step(guard=None, lr=0.1):
    _init_mesh(dp=2)
    P.seed(0)
    model = fleet.distributed_model(nn.Linear(8, 4))
    opt = P.optimizer.SGD(parameters=model.parameters(), learning_rate=lr)
    return model.build_train_step(opt, nn.MSELoss(), guard=guard)


def _batch():
    P.seed(1)
    return P.randn([8, 8]), P.randn([8, 4])


def test_guard_zero_faults_bitforbit():
    """Acceptance: with zero injected faults the guarded step matches
    the unguarded loss trajectory bit-for-bit."""
    x, y = _batch()
    plain = _make_step(None)
    ref = [float(plain(x, y)) for _ in range(5)]
    guarded = _make_step(StepGuard(raise_without_rollback=False))
    got = [float(guarded(x, y)) for _ in range(5)]
    assert got == ref  # exact float equality, not allclose


def test_guard_nan_step_skipped_state_preserved():
    x, y = _batch()
    g = StepGuard(max_consecutive_bad=10, raise_without_rollback=False)
    step = _make_step(g)
    step(x, y)
    with faults.inject("train.step", kind="nan", at=1):
        bad = float(step(x, y))
    assert np.isnan(bad)
    after = float(step(x, y))
    # reference: the skipped step must not have touched the state, so
    # the next loss equals the unfaulted second loss
    ref = _make_step(None)
    ref(x, y)
    assert after == float(ref(x, y))
    assert g.total_bad == 1 and g.consecutive_bad == 0


def test_guard_escalates_to_checkpoint_rollback(tmp_path):
    """K consecutive NaN steps → rollback restores the last verified
    checkpoint into the live training state."""
    x, y = _batch()
    g = StepGuard(max_consecutive_bad=2)
    step = _make_step(g)
    step.attach_checkpoint_manager(CheckpointManager(str(tmp_path)))
    step(x, y)
    step.save_checkpoint()  # known-good state
    w_saved = np.asarray(step._state["params"][
        list(step._state["params"])[0]])
    with faults.inject("train.step", kind="nan", times=2):
        float(step(x, y))  # bad 1 → warn
        float(step(x, y))  # bad 2 → rollback
    assert g.rollbacks == 1
    w_now = np.asarray(step._state["params"][
        list(step._state["params"])[0]])
    np.testing.assert_array_equal(w_now, w_saved)
    # training continues sanely after the rollback
    assert np.isfinite(float(step(x, y)))


def test_guard_without_rollback_target_raises():
    g = StepGuard(max_consecutive_bad=1)
    with pytest.raises(RollbackError):
        g.observe(False)


def test_scaler_guard_interplay():
    """GradScaler-reported overflows do NOT escalate while dynamic
    scaling still has room (expected behavior during scale search);
    at the scale floor they count toward the ladder."""
    g = StepGuard(max_consecutive_bad=3, raise_without_rollback=False)
    scaler = P.amp.GradScaler(init_loss_scaling=4.0).attach_guard(g)
    # overflow with scale>1: skip recorded, no escalation
    scaler._found_inf = True
    scaler.update()
    assert g.consecutive_bad == 0 and g.total_bad == 1
    # drive the scale to its floor, still overflowing → escalates
    scaler._scale = 1.0
    for _ in range(3):
        scaler._found_inf = True
        scaler.update()
    assert g.rollbacks == 1  # 3 consecutive amp_floor steps tripped it
    # a clean step resets the streak
    scaler._found_inf = False
    scaler.update()
    assert g.consecutive_bad == 0
    # static scaling (no dynamic room at all) counts as at-floor too
    g2 = StepGuard(max_consecutive_bad=2, raise_without_rollback=False)
    s2 = P.amp.GradScaler(use_dynamic_loss_scaling=False).attach_guard(g2)
    for _ in range(2):
        s2._found_inf = True
        s2.update()
    assert g2.rollbacks == 1


# --------------------------------------------------------------------------
# watchdog
# --------------------------------------------------------------------------

def test_watchdog_stall_dumps_and_rearms(tmp_path):
    flight.record("pre_stall_marker", detail=1)  # something in the ring
    stalls = []
    wd = Watchdog(timeout=0.15, poll=0.03, dump_dir=str(tmp_path),
                  on_stall=stalls.append, name="t")
    with wd:
        wd.beat()
        deadline = time.time() + 5.0
        while not stalls and time.time() < deadline:
            time.sleep(0.02)
    assert stalls, "watchdog never tripped"
    assert wd.trips >= 1
    dump_path, _trace_path = wd.last_dump
    assert dump_path and os.path.exists(dump_path)
    with open(dump_path) as f:
        content = f.read()
    assert "watchdog_stall" in content
    wd.stop()  # idempotent
    wd.stop()


def test_watchdog_fed_by_step_timer():
    from paddle_tpu.observability import StepTimer

    clock = {"t": 0.0}
    wd = Watchdog(timeout=60.0, clock=lambda: clock["t"],
                  name="timer-fed").watch_step_timer()
    try:
        wd.beat()
        clock["t"] += 10.0
        assert wd.stalled_for() == 10.0
        t = StepTimer(run_id="wd-test", read_device_memory=False)
        t.record(0.01)  # the record hook beats the watchdog
        assert wd.stalled_for() == 0.0
    finally:
        wd.stop()


def test_watchdog_check_raises():
    from paddle_tpu.resilience import WatchdogStall

    clock = {"t": 0.0}
    wd = Watchdog(timeout=1.0, clock=lambda: clock["t"], name="sync")
    wd.beat()
    clock["t"] += 5.0
    with pytest.raises(WatchdogStall):
        wd.check()


# --------------------------------------------------------------------------
# elastic heartbeat over a flaky store
# --------------------------------------------------------------------------

class _FlakyStore:
    def __init__(self):
        self.fail_next = 0
        self.kv = {}

    def set(self, k, v):
        if self.fail_next > 0:
            self.fail_next -= 1
            raise ConnectionError("transient store error")
        self.kv[k] = v

    def get(self, k, timeout=None):
        return self.kv[k]

    def check(self, k):
        return k in self.kv


def test_elastic_heartbeat_survives_transient_store_errors():
    from paddle_tpu.distributed.fleet.elastic import ElasticManager

    st = _FlakyStore()
    m = ElasticManager(store=st, job_id="rz", np_range="1",
                       heartbeat_interval=0.05, heartbeat_ttl=5.0)
    _no_sleep(m._hb_retry)
    st.fail_next = 2  # register's first beat retries through these
    m.register()
    assert m.alive_ranks() == [0]
    st.fail_next = 50  # past the retry budget: beats missed, thread lives
    time.sleep(0.15)
    st.fail_next = 0
    time.sleep(0.12)  # recovered beat lands
    assert m.alive_ranks() == [0]
    assert m.missed_beats >= 1
    assert m._thread.is_alive()
    # stop/shutdown idempotent (satellite)
    m.exit()
    assert m._thread is None
    m.exit()
    m.stop()
    m.shutdown()


def test_elastic_register_idempotent():
    from paddle_tpu.distributed.fleet.elastic import ElasticManager

    m = ElasticManager(store=_FlakyStore(), job_id="rz2", np_range="1",
                       heartbeat_interval=0.05)
    m.register()
    t1 = m._thread
    m.register()  # no-op on a live manager
    assert m._thread is t1
    m.exit()
    m.register()  # restart after exit
    assert m._thread is not None and m._thread.is_alive()
    m.exit()


# --------------------------------------------------------------------------
# dataloader retry
# --------------------------------------------------------------------------

def test_dataloader_batch_retry():
    import paddle_tpu.io.dataloader as dlm
    from paddle_tpu.io.dataset import Dataset

    class DS(Dataset):
        def __getitem__(self, i):
            return np.float32([i, i])

        def __len__(self):
            return 8

    _no_sleep(dlm._fetch_retry())
    dl = P.io.DataLoader(DS(), batch_size=4)
    with faults.inject("dataloader.batch", at=1):  # first fetch retried
        batches = list(dl)
    assert len(batches) == 2
    np.testing.assert_array_equal(batches[0].numpy()[:, 0], [0, 1, 2, 3])


# --------------------------------------------------------------------------
# serving: retry then degrade-to-smaller-batch
# --------------------------------------------------------------------------

def test_serving_degrades_to_smaller_batch(tmp_path):
    from paddle_tpu import static
    from paddle_tpu.inference.serving import InferenceServer

    P.enable_static()
    try:
        x = static.data("x", [-1, 4], "float32")
        lin = nn.Linear(4, 3)
        out = nn.functional.softmax(lin(x))
        exe = static.Executor()
        prefix = str(tmp_path / "served")
        static.save_inference_model(prefix, [x], [out], exe)
    finally:
        P.disable_static()

    srv = InferenceServer(prefix)
    _no_sleep(srv._retry)
    xv = np.random.RandomState(0).rand(8, 4).astype(np.float32)
    ref = srv.predict({"x": xv})
    metrics.enable()
    metrics.reset()
    try:
        # full-batch run fails both retry attempts; halves succeed and
        # results re-concatenate to the undegraded answer
        with faults.inject("serving.request", times=2):
            got = srv.predict({"x": xv})
        key = list(ref)[0]
        np.testing.assert_allclose(got[key], ref[key], rtol=1e-6)
        snap = metrics.snapshot()["counters"]
        assert snap.get("resilience.degraded_batches", 0) >= 1
    finally:
        metrics.disable()
    # unsplittable (batch 1) surfaces the real error instead of looping
    with faults.inject("serving.request", times=50):
        with pytest.raises(InjectedFault):
            srv.predict({"x": xv[:1]})


# --------------------------------------------------------------------------
# metrics schema + chaos smoke
# --------------------------------------------------------------------------

def test_attach_declares_resilience_schema():
    from paddle_tpu import observability as obs

    metrics.reset()
    obs.attach(crash_hook=False)
    try:
        snap = metrics.snapshot()["counters"]
        for key in ("resilience.faults{point=train.step}",
                    "resilience.retries{policy=collective}",
                    "resilience.skipped_steps{source=guard}",
                    "resilience.rollbacks", "resilience.watchdog_trips",
                    "resilience.degraded_batches"):
            assert key in snap and snap[key] == 0, key
    finally:
        obs.detach()
        metrics.reset()


@pytest.mark.chaos
@pytest.mark.slow  # tier-1 runs `-m 'not slow'`; chaos rides the slow tier
def test_chaos_check_tool():
    """The long seeded-random fault matrix (tools/chaos_check.py) —
    registered under the `chaos` marker, outside tier-1."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "chaos_check", os.path.join(os.path.dirname(__file__), os.pardir,
                                    "tools", "chaos_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.run_chaos(steps=24, seed=3, ckpt_every=4)
    assert report["recovered"] and report["final_loss_finite"]
