"""Autoscaler tests (ISSUE 14): decision logic under a fake clock
(sustained-burn scale-up, idle scale-down, cooldown hysteresis, min/max
bounds, the affinity-aware scale-down victim pick and its drain
ordering), the fleet's dynamic-membership fixes (removed ranks never
relaunched, stop() sweeps dynamically-added replicas), the router's
fleet-level SLO feed, and the new capacity/autoscaler telemetry.  Unit
tests drive the whole loop with fake replicas, a fake transport, fake
processes and an injectable clock — the only real sockets are the
routers' unstarted/ephemeral listeners.  The seeded 10× surge lives
under the `chaos` marker (tools/chaos_check.py --scenario surge).
"""
import json
import os
import sys
import threading
import time

import pytest

from paddle_tpu import observability as obs
from paddle_tpu.inference.autoscaler import Autoscaler
from paddle_tpu.inference.fleet import ReplicaFleet
from paddle_tpu.inference.router import ReplicaUnreachable, Router
from paddle_tpu.observability import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry():
    obs.attach(crash_hook=False)
    yield
    obs.detach()


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --------------------------------------------------------------------------
# fake replica plane (same idiom as test_router: no replica sockets)
# --------------------------------------------------------------------------

class _FakeReplica:
    def __init__(self, limit=4, engine=None, ready=True):
        self.limit = limit
        self.engine = engine
        self.ready = ready

    def ready_payload(self):
        body = {"status": "ready" if self.ready else "not_ready",
                "reason": "ok", "inflight": 0, "queued": 0,
                "limit": self.limit, "admission_limit": self.limit}
        if self.engine is not None:
            body["engine"] = dict(self.engine)
        return ((200 if self.ready else 503), {},
                json.dumps(body).encode())


class _FakeTransport:
    def __init__(self, replicas):
        self.replicas = dict(replicas)  # address -> _FakeReplica

    def request(self, address, method, path, body=None, headers=None,
                timeout=30.0):
        rep = self.replicas.get(address)
        if rep is None:
            raise ReplicaUnreachable(f"no fake replica at {address}")
        if path == "/ready":
            return rep.ready_payload()
        raise AssertionError(f"unexpected path {path}")

    def stream(self, address, path, body, headers=None, timeout=30.0):
        raise AssertionError("no streams in these tests")


class _FakeProc:
    def __init__(self, record, rank):
        self.record = record
        self.rank = rank
        self.rc = None
        self.pid = 91000 + rank

    def poll(self):
        return self.rc

    def wait(self, timeout=None):
        return self.rc

    def send_signal(self, sig):
        self.record.append(("signal", self.rank, int(sig)))
        self.rc = 0

    def kill(self):
        self.record.append(("kill", self.rank))
        self.rc = -9


def _scaled_fleet(tmp_path, n=2, pool=6, clock=None, fleet_kw=None,
                  **scaler_kw):
    """A ReplicaFleet over fake processes behind a Router over a fake
    transport, plus an Autoscaler on a fake clock.  The transport
    pre-registers `pool` addresses so dynamic growth has somewhere to
    land."""
    record = []
    transport = _FakeTransport(
        {f"fake://r{i}": _FakeReplica() for i in range(pool)})
    router = Router(transport=transport, probe_interval=0.05,
                    clock=clock or time.monotonic)

    def spawner(handle, cmd, env):
        with open(handle.announce + ".tmp", "w") as f:
            json.dump({"address": f"fake://{handle.rid}",
                       "pid": 91000 + handle.rank}, f)
        os.replace(handle.announce + ".tmp", handle.announce)
        return _FakeProc(record, handle.rank)

    fleet = ReplicaFleet(num_replicas=n, router=router,
                         heartbeat=False, spawner=spawner,
                         workdir=str(tmp_path), monitor_interval=0.05,
                         **dict(fleet_kw or {}, ))
    fleet.start()
    scaler = Autoscaler(fleet, clock=clock or time.monotonic,
                        **scaler_kw)
    return fleet, scaler, record


def _wait_routable(router, n, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if router.routable_count() >= n:
            return True
        time.sleep(0.02)
    return router.routable_count() >= n


# --------------------------------------------------------------------------
# scale-up: sustained burn, cooldown, max bound
# --------------------------------------------------------------------------

def test_sustained_burn_scales_up_cooldown_suppresses_flapping(tmp_path):
    clk = _Clock()
    fleet, scaler, _rec = _scaled_fleet(
        tmp_path, n=1, clock=clk, min_replicas=1, max_replicas=3,
        up_sustain=2, down_sustain=99, cooldown_s=5.0, burn_up=3.0)
    try:
        # a sustained error-budget burn on the router's OWN ledger
        for _ in range(4):
            fleet.router.slo.record_shed("generate", "edge")
        assert scaler.tick() == "hold"      # one tick is noise...
        assert scaler.tick() == "up"        # ...two is sustained
        assert fleet.replica_count() == 2
        assert "r1" in fleet.router.replica_summary()
        # still burning, but inside the cooldown: no flap
        assert scaler.tick() == "hold"
        assert scaler.tick() == "hold"
        assert fleet.replica_count() == 2
        clk.advance(6.0)                    # cooldown elapsed — the
        # evidence kept accumulating through the holds, so the next
        # tick acts immediately
        assert scaler.tick() == "up"
        assert fleet.replica_count() == 3
        # max bound holds no matter how hard the budget burns
        clk.advance(6.0)
        assert scaler.tick() == "hold"
        assert scaler.tick() == "hold"
        assert fleet.replica_count() == 3
        snap = metrics.snapshot()
        assert snap["counters"].get(
            "autoscaler.decisions{action=up}") == 2
        assert snap["gauges"].get(
            "autoscaler.replicas{state=actual}") == 3
        assert snap["gauges"].get(
            "autoscaler.replicas{state=target}") == 3
    finally:
        fleet.stop()


def test_occupancy_high_water_also_scales_up(tmp_path):
    clk = _Clock()
    fleet, scaler, _rec = _scaled_fleet(
        tmp_path, n=1, clock=clk, min_replicas=1, max_replicas=2,
        up_sustain=2, down_sustain=99, cooldown_s=0.0, occ_up=0.5)
    try:
        assert _wait_routable(fleet.router, 1)
        # park tickets in the edge controller: occupancy, no burn
        tickets = [fleet.router.admission.admit() for _ in range(3)]
        assert scaler.signals()["occupancy"] >= 0.5
        assert scaler.tick() == "hold"
        assert scaler.tick() == "up"
        assert fleet.replica_count() == 2
        for t in tickets:
            t.release(ok=True)
    finally:
        fleet.stop()


# --------------------------------------------------------------------------
# scale-down: sustained idle, drain ordering, affinity-aware victim
# --------------------------------------------------------------------------

def test_idle_scales_down_through_drain_never_affinity_hot(tmp_path):
    clk = _Clock()
    fleet, scaler, record = _scaled_fleet(
        tmp_path, n=3, clock=clk, min_replicas=1, max_replicas=4,
        up_sustain=99, down_sustain=2, cooldown_s=0.0)
    try:
        assert _wait_routable(fleet.router, 3)
        # r0 is affinity-hot (three warm tenants), r1 warm, r2 cold
        with fleet.router._lock:
            for i in range(3):
                fleet.router._affinity[f"fp{i}"] = "r0"
            fleet.router._affinity["fp3"] = "r1"
        assert fleet.router.affinity_counts() == {"r0": 3, "r1": 1}
        assert scaler.tick() == "hold"
        assert scaler.tick() == "down"
        # the COLD replica went, not the affinity-hot one
        assert fleet.replica_ranks() == [0, 1]
        assert "r2" not in fleet.router.replica_summary()
        kinds = [(e["kind"], e.get("rank")) for e in fleet.events]
        assert kinds.index(("drain_mark", 2)) \
            < kinds.index(("drain_sigterm", 2))
        assert ("signal", 2, 15) in record          # SIGTERM, not kill
        removed = [e for e in fleet.events
                   if e["kind"] == "replica_removed"]
        assert removed and removed[0]["rank"] == 2 \
            and removed[0]["rc"] == 0               # clean drain exit
        # next idle round retires r1 (warm beats hot)
        assert scaler.tick() == "hold"
        assert scaler.tick() == "down"
        assert fleet.replica_ranks() == [0]
        # min bound: idle forever, the last replica stays
        assert scaler.tick() == "hold"
        assert scaler.tick() == "hold"
        assert fleet.replica_count() == 1
        snap = metrics.snapshot()["counters"]
        assert snap.get("autoscaler.decisions{action=down}") == 2
        assert snap.get("autoscaler.decisions{action=hold}", 0) >= 3
    finally:
        fleet.stop()


# --------------------------------------------------------------------------
# predictive scale-up (ISSUE 15): the timeseries-derivative signal
# --------------------------------------------------------------------------

_PRED_KW = dict(min_replicas=1, max_replicas=3, up_sustain=2,
                down_sustain=99, cooldown_s=0.0,
                # thresholds parked out of reach: only the derivative
                # can fire — the unit isolates the predictive path
                burn_up=1e9, occ_up=0.99,
                deriv_up=0.05, queue_deriv_up=1e9,
                deriv_window_s=10.0, deriv_floor=0.3)


def test_predictive_scale_up_fires_on_occupancy_slope(tmp_path):
    """Fake-clock unit for the ISSUE 15 signal: occupancy RAMPS while
    burn and the occupancy threshold stay quiet — the sustained
    positive derivative alone must scale up, under the normal sustain
    hysteresis, counted as up_predictive."""
    clk = _Clock()
    fleet, scaler, _rec = _scaled_fleet(tmp_path, n=1, clock=clk,
                                        **_PRED_KW)
    try:
        assert _wait_routable(fleet.router, 1)
        tickets = []

        def occupy(n):
            for _ in range(n):
                tickets.append(fleet.router.admission.admit())

        # occ 0 → .25 → .5 → .75 over 3 s: slope ≈ 0.25/s ≥ 0.05, but
        # the floor (0.3) holds fire until occupancy is real
        assert scaler.tick() == "hold"
        clk.advance(1.0)
        occupy(1)
        assert scaler.tick() == "hold"          # occ .25 < floor
        clk.advance(1.0)
        occupy(1)
        assert scaler.tick() == "hold"          # streak 1 of 2
        clk.advance(1.0)
        occupy(1)
        assert scaler.tick() == "up_predictive"  # sustained slope
        assert fleet.replica_count() == 2
        assert scaler.events[-1]["kind"] == "scale_up_predictive"
        assert scaler.events[-1]["d_occupancy"] >= 0.05
        # burn never crossed: no burn_threshold_crossed event logged
        assert all(e["kind"] != "burn_threshold_crossed"
                   for e in scaler.events)
        snap = metrics.snapshot()["counters"]
        assert snap.get(
            "autoscaler.decisions{action=up_predictive}") == 1
        # no threshold-triggered scale-up happened in THIS scaler (the
        # registry counter is process-global, so assert on the events)
        assert all(e["kind"] != "scale_up" for e in scaler.events)
        for t in tickets:
            t.release(ok=True)
    finally:
        fleet.stop()


def test_predictive_stays_silent_on_flat_occupancy(tmp_path):
    """HIGH but FLAT occupancy (above the floor, below the threshold)
    must never fire the predictive path: the derivative is the signal,
    not the level."""
    clk = _Clock()
    fleet, scaler, _rec = _scaled_fleet(tmp_path, n=1, clock=clk,
                                        **_PRED_KW)
    try:
        assert _wait_routable(fleet.router, 1)
        tickets = [fleet.router.admission.admit() for _ in range(2)]
        for _ in range(8):                      # occ pinned at .5
            clk.advance(1.0)
            assert scaler.tick() == "hold"
        assert fleet.replica_count() == 1
        assert scaler.describe()["d_occupancy"] == pytest.approx(
            0.0, abs=1e-6)
        for t in tickets:
            t.release(ok=True)
    finally:
        fleet.stop()


# --------------------------------------------------------------------------
# fleet dynamic membership (the ISSUE 14 satellite fix)
# --------------------------------------------------------------------------

def test_membership_changes_safe_against_monitor_and_stop(tmp_path):
    fleet, _scaler, record = _scaled_fleet(
        tmp_path, n=1, fleet_kw={"max_restarts": 3})
    try:
        rank = fleet.add_replica()
        assert rank == 1
        assert fleet.replica_ranks() == [0, 1]
        spawned_before = [e for e in fleet.events
                         if e["kind"] == "replica_spawned"
                         and e["rank"] == 1]
        assert len(spawned_before) == 1
        # remove it: the monitor must NOT relaunch the retired rank
        # even though max_restarts allows it
        assert fleet.remove_replica(1) == 0
        assert fleet.replica_ranks() == [0]
        assert "r1" not in fleet.router.replica_summary()
        # retired/unknown ranks are graceful no-ops, never KeyErrors
        assert fleet.remove_replica(1) is None
        assert fleet.drain_replica(1) is False
        assert fleet.kill_replica(1) is False
        time.sleep(0.3)  # several monitor sweeps
        spawned_after = [e for e in fleet.events
                         if e["kind"] == "replica_spawned"
                         and e["rank"] == 1]
        assert len(spawned_after) == 1  # no double-relaunch
        # a replica added later is swept by stop() (no orphans)
        rank2 = fleet.add_replica()
        assert rank2 == 2
    finally:
        fleet.stop()
    assert ("signal", 2, 15) in record  # stop() SIGTERMed the late add
    assert all(e[0] != "kill" or e[1] != 2 for e in record)


# --------------------------------------------------------------------------
# router: fleet-level SLO feed + capacity gauges
# --------------------------------------------------------------------------

def _bare_router(replicas, **kw):
    transport = _FakeTransport(
        {f"fake://{rid}": rep for rid, rep in replicas.items()})
    r = Router(replicas={rid: f"fake://{rid}" for rid in replicas},
               transport=transport, **kw)
    r.probe_once()
    return r


def test_router_slo_burns_on_sheds_not_client_errors():
    r = _bare_router({"r0": _FakeReplica()})
    try:
        t0 = time.perf_counter()
        r._finish_request("generate", "shed", None, t0)
        r._finish_request("generate", "ok", None, t0)
        r._finish_request("generate", "interrupted", None, t0)
        r._finish_request("predict", "client_error", None, t0)
        rep = r.slo.report(publish_gauges=False)
        gen = rep["endpoints"]["generate"]
        assert gen["requests"] == 3 and gen["errors"] == 2
        assert gen["burn_rate"] > 100  # 2/3 error rate vs 0.1% budget
        assert gen["errors_by_reason"] == {"shed:edge": 1,
                                           "interrupted": 1}
        # a misbehaving client buys itself nothing
        assert rep["endpoints"]["predict"]["requests"] == 0
        # the snapshot plane carries the ledger (ISSUE 14 satellite)
        assert "slo" in r.telemetry_snapshot()
    finally:
        r._httpd.server_close()


def test_router_capacity_gauges_track_routable_fleet():
    r = _bare_router({"r0": _FakeReplica(limit=4,
                                         engine={"max_slots": 2}),
                      "r1": _FakeReplica(limit=3)})
    try:
        snap = metrics.snapshot()["gauges"]
        assert snap.get("router.capacity{endpoint=predict}") == 7
        assert snap.get("router.capacity{endpoint=generate}") == 2
    finally:
        r._httpd.server_close()


def test_autoscaler_schema_zeros_present_in_snapshot():
    snap = metrics.snapshot()
    for action in ("up", "down", "hold", "up_predictive"):
        assert f"autoscaler.decisions{{action={action}}}" \
            in snap["counters"]
    for state in ("target", "actual"):
        assert f"autoscaler.replicas{{state={state}}}" in snap["gauges"]
    for ep in ("predict", "generate"):
        assert f"router.capacity{{endpoint={ep}}}" in snap["gauges"]


def test_autoscaler_gauges_ride_the_telemetry_rollup(tmp_path):
    """The new gauges are first-class in the fleet aggregation plane
    (ISSUE 14 satellite): a process dump rolls them up next to the
    router's replica-state gauges."""
    from paddle_tpu.observability.export import TelemetryExporter

    metrics.set_gauge("autoscaler.replicas", 2, state="actual")
    metrics.set_gauge("router.capacity", 8, endpoint="generate")
    tel_dir = tmp_path / "tel"
    tel_dir.mkdir()
    TelemetryExporter(outdir=str(tel_dir),
                      run_id="scaler").dump_once(reason="test")
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_agg
    finally:
        sys.path.pop(0)
    roll = telemetry_agg.rollup(telemetry_agg.load_dumps(str(tel_dir)))
    keys = set(roll.get("gauges", {}))
    assert any(k.startswith("autoscaler.replicas") for k in keys)
    assert any(k.startswith("autoscaler.decisions")
               for k in roll.get("counters", {}))
    assert any(k.startswith("router.capacity") for k in keys)


# --------------------------------------------------------------------------
# the 10x surge (chaos tier)
# --------------------------------------------------------------------------

@pytest.mark.chaos
def test_surge_chaos_scenario():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_check
    finally:
        sys.path.pop(0)
    report = chaos_check.run_surge_chaos(seed=0)
    assert report["recovered"], report
